"""The fused training step — the production TPU execution path.

The reference executes one OpenCL/CUDA kernel per unit per minibatch
(veles/accelerated_units.py execute_kernel).  Translating that 1:1
would dispatch dozens of tiny XLA computations per step and lose badly
(SURVEY.md §7 "hard parts").  Instead, the whole iteration —

    gather minibatch rows from the HBM-resident dataset
    -> every forward unit's apply
    -> evaluator metrics + err_output
    -> every gradient unit's backward + SGD update

— is traced into ONE jitted function.  XLA fuses the elementwise chains
into the matmuls/convs, keeps everything in HBM, and the parameter /
optimizer pytrees are DONATED so updates are in-place in device memory.
Separate train/eval traces give dropout-style units their two modes
without traced branching.

``FusedStepRunner`` is a drop-in graph node: it sits where the
forwards+evaluator+gds chain would, reads the loader's minibatch
indices, and rebinds every unit's Vectors (weights, output, metrics) to
the step outputs — so Decision, Snapshotter, and plotters observe
exactly what they would in eager mode, and ``map_read`` on any Vector
still yields the current value.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.loader.base import TRAIN
from veles_tpu import prng


class FusedStepRunner(AcceleratedUnit):
    def __init__(self, workflow=None, loader=None, forwards=None,
                 evaluator=None, gds=None, rng_stream: str = "fused",
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.loader = loader
        self.forwards: List[Any] = forwards or []
        self.evaluator = evaluator
        #: gds[i] is the GradientUnit of forwards[i] (may contain None
        #: for frozen/param-less layers that still need err routing)
        self.gds: List[Any] = gds or []
        self.rng_stream = rng_stream
        #: a jax.sharding.Mesh when DataParallel is installed — the
        #: steps are then jitted with the minibatch sharded over the
        #: mesh's data axis and params replicated (parallel/ package)
        self.mesh = None
        self._train_step = None
        self._eval_step = None
        self._params: Optional[Dict[str, Dict[str, Any]]] = None
        self._opt: Optional[Dict[str, Dict[str, Any]]] = None
        self._rng_counter = 0
        self._conf_handles: List[Any] = []
        #: per-GD lr multipliers (traced arg — lr_adjust writes these
        #: without triggering a retrace)
        self.lr_scales = [1.0] * len(self.gds)

    _unpicklable = AcceleratedUnit._unpicklable + (
        "_train_step", "_eval_step", "_params", "_opt", "mesh",
        "_batch_sharding")

    # -- pytree assembly ----------------------------------------------

    def _collect_params(self) -> Dict[str, Dict[str, Any]]:
        return {f.name: f.gather_params() for f in self.forwards}

    def _collect_opt(self) -> Dict[str, Dict[str, Any]]:
        opt = {}
        for gd in self.gds:
            if gd is None:
                continue
            opt[gd.name] = {k: v.unmap()
                            for k, v in gd.accumulated_grads.items()}
        return opt

    def _scatter_params(self, params, opt) -> None:
        """Rebind unit Vectors to the donated-step outputs so the rest
        of the framework observes updated weights."""
        for f in self.forwards:
            p = params[f.name]
            if "weights" in p:
                f.weights.devmem = p["weights"]
            if "bias" in p:
                f.bias.devmem = p["bias"]
        for gd in self.gds:
            if gd is None:
                continue
            for k, vec in gd.accumulated_grads.items():
                vec.devmem = opt[gd.name][k]

    # -- trace construction -------------------------------------------

    def _has_targets(self) -> bool:
        return hasattr(self.evaluator, "target")

    def _build_steps(self) -> None:
        import jax
        import jax.numpy as jnp

        forwards = list(self.forwards)
        gds = list(self.gds)
        evaluator = self.evaluator
        n_fwd = len(forwards)
        has_targets = self._has_targets()
        want_confusion = bool(getattr(evaluator, "compute_confusion",
                                      False))
        n_classes = getattr(evaluator, "n_classes", None)
        seed = prng.get(self.rng_stream).seed

        def forward_pass(params, x, rng_counter, train: bool):
            residuals = []
            for i, f in enumerate(forwards):
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.key(seed), rng_counter), i) \
                    if f.stochastic else None
                x, res = f.apply_fwd(params[f.name], x, rng=rng, train=train)
                residuals.append(res)
            return x, residuals

        def metrics_of(out, target, mask):
            m = evaluator.metrics_fn(out, target, mask)
            if want_confusion and n_classes is not None:
                conf = jnp.zeros((n_classes, n_classes), jnp.int32)
                conf = conf.at[target, m["max_idx"]].add(
                    mask.astype(jnp.int32))
                m["confusion"] = conf
            return m

        def gather(dataset, target_store, indices):
            x = jnp.take(dataset, indices, axis=0)
            t = jnp.take(target_store, indices, axis=0)
            return x, t

        def train_step(params, opt, dataset, target_store, indices, mask,
                       lr_scales, rng_counter):
            x, target = gather(dataset, target_store, indices)
            out, residuals = forward_pass(params, x, rng_counter, True)
            m = metrics_of(out, target, mask)
            err = m.pop("err_output")
            new_params = dict(params)
            new_opt = dict(opt)
            for i in range(n_fwd - 1, -1, -1):
                f, gd = forwards[i], gds[i]
                if gd is None:
                    continue
                err_in, grads = gd.backward_from_saved(
                    params[f.name], residuals[i], err)
                if grads:
                    p, v = gd.update_params(params[f.name], grads,
                                            opt.get(gd.name, {}),
                                            lr_scales[i])
                    new_params[f.name] = p
                    if gd.name in opt:
                        new_opt[gd.name] = v
                err = err_in
            return new_params, new_opt, m

        def eval_step(params, dataset, target_store, indices, mask,
                      rng_counter):
            x, target = gather(dataset, target_store, indices)
            out, _ = forward_pass(params, x, rng_counter, False)
            m = metrics_of(out, target, mask)
            m.pop("err_output")
            return m, out

        if self.mesh is not None:
            # SPMD data parallelism: minibatch rows sharded over the
            # data axis, params/dataset replicated.  mask.sum() and the
            # per-param batch reductions cross the sharded axis, so the
            # partitioner emits the gradient allreduce (ICI psum) —
            # this IS the master-slave aggregation, in-compiler.
            from veles_tpu.parallel.mesh import (batch_sharding,
                                                 replicated_sharding)
            repl = replicated_sharding(self.mesh)
            batch = self._batch_sharding = batch_sharding(self.mesh)
            self._train_step = jax.jit(
                train_step, donate_argnums=(0, 1),
                in_shardings=(repl, repl, repl, repl, batch, batch,
                              repl, repl))
            self._eval_step = jax.jit(
                eval_step,
                in_shardings=(repl, repl, repl, batch, batch, repl))
        else:
            self._train_step = jax.jit(train_step, donate_argnums=(0, 1))
            self._eval_step = jax.jit(eval_step)

    # -- lifecycle -----------------------------------------------------

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.mesh is not None:
            # the STATIC minibatch shape is max_minibatch_size, which
            # clamps below minibatch_size when every class is smaller —
            # DataParallel.install() can only check minibatch_size
            # (load_data hasn't run yet there)
            n = int(self.mesh.devices.size)
            mb = self.loader.max_minibatch_size
            if mb % n:
                raise ValueError(
                    f"static minibatch shape {mb} (loader "
                    f"max_minibatch_size) not divisible by mesh size "
                    f"{n}; lower minibatch_size or pad the dataset")
        if self._train_step is None:
            self._build_steps()

    def _target_store(self):
        ld = self.loader
        if self._has_targets():
            return ld.original_targets.unmap()
        return ld.original_labels.unmap()

    def run(self) -> None:
        ld = self.loader
        ev = self.evaluator
        self._ensure_params()
        indices = ld.minibatch_indices.unmap()
        mask = ld.minibatch_mask.unmap()
        dataset = ld.original_data.unmap()
        targets = self._target_store()
        if self.mesh is not None:
            # Vectors upload replicated (MeshJaxDevice.put); the batch
            # args must enter the step sharded over the data axis —
            # replicated->sharded is a local slice, no communication.
            import jax
            indices = jax.device_put(indices, self._batch_sharding)
            mask = jax.device_put(mask, self._batch_sharding)
        if ld.minibatch_class == TRAIN:
            self._params, self._opt, m = self._train_step(
                self._params, self._opt, dataset, targets, indices, mask,
                np.asarray(self.lr_scales, np.float32),
                self._rng_counter)
            self._scatter_params(self._params, self._opt)
        else:
            m, out = self._eval_step(self._params, dataset, targets,
                                     indices, mask, self._rng_counter)
            self.forwards[-1].output.devmem = out
        self._rng_counter += 1
        # Publish metrics through the evaluator's Vectors (device
        # handles only — no sync; Decision sums lazily per class).
        ev.n_err.devmem = m["n_err"]
        ev.loss.devmem = m["loss_sum"]
        ev.count.devmem = m["count"]
        if "max_idx" in m:
            ev.max_idx.devmem = m["max_idx"]
        if "confusion" in m:
            # keep device handles; fold into the host matrix once per
            # class end (a sync per minibatch would stall the pipeline)
            self._conf_handles.append(m["confusion"])
            if bool(ld.class_ended) and ev.confusion:
                for h in self._conf_handles:
                    ev.confusion.mem += np.asarray(h)
                self._conf_handles.clear()

    # -- zmq DCN compat mode (server.py / client.py) -------------------

    def _ensure_params(self) -> None:
        if self._params is None:
            self._params = self._collect_params()
            self._opt = self._collect_opt()

    def host_params(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Current parameters as host numpy arrays (slave -> diff)."""
        self._ensure_params()
        return {fn: {pn: np.asarray(v) for pn, v in d.items()}
                for fn, d in self._params.items()}

    def set_host_params(self, params) -> None:
        """Adopt master-provided parameters (device upload; velocities
        stay local, as in the reference's slave)."""
        self._ensure_params()
        self._params = {
            fn: {pn: self.device.put(np.asarray(params[fn][pn]))
                 for pn in d}
            for fn, d in self._params.items()}
        self._scatter_params(self._params, self._opt or {})

    # -- snapshot support ---------------------------------------------

    def sync_params_to_vectors(self) -> None:
        """Pull the current param pytree into host Vectors (snapshot)."""
        if self._params is None:
            return
        self._scatter_params(self._params, self._opt or {})
        for f in self.forwards:
            for v in (f.weights, f.bias):
                if v:
                    v.map_read()

    def __getstate__(self) -> dict:
        self.sync_params_to_vectors()
        d = super().__getstate__()
        d["_conf_handles"] = []
        return d
