"""The fused training step — the production TPU execution path.

The reference executes one OpenCL/CUDA kernel per unit per minibatch
(veles/accelerated_units.py execute_kernel).  Translating that 1:1
would dispatch dozens of tiny XLA computations per step and lose badly
(SURVEY.md §7 "hard parts").  Instead, the whole iteration —

    gather minibatch rows from the HBM-resident dataset
    -> every forward unit's apply
    -> evaluator metrics + err_output
    -> every gradient unit's backward + SGD update

— is traced into ONE jitted function, and a ``lax.scan`` over up to
``loader.superstep`` same-class minibatches runs MANY iterations per
device dispatch (amortizing per-execute latency, which dominates on
tunneled/remote TPUs).  Metrics and the confusion matrix accumulate
ON DEVICE in donated carry buffers; the host fetches 12 bytes once per
class end instead of 3 scalars per minibatch.  Matmuls/convs run in
the device's ``compute_dtype`` (bfloat16 on TPU — the MXU's native
format) against float32 master weights.

``FusedStepRunner`` is a drop-in graph node: it sits where the
forwards+evaluator+gds chain would, reads the loader's minibatch
indices, and rebinds every unit's Vectors (weights, output, metrics) to
the step outputs — so Decision, Snapshotter, and plotters observe
exactly what they would in eager mode.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.loader.base import TRAIN
from veles_tpu import events, prng, telemetry
from veles_tpu.ops import batching


class FusedStepRunner(AcceleratedUnit):
    def __init__(self, workflow=None, loader=None, forwards=None,
                 evaluator=None, gds=None, rng_stream: str = "fused",
                 compute_dtype: Any = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.loader = loader
        self.forwards: List[Any] = forwards or []
        self.evaluator = evaluator
        #: gds[i] is the GradientUnit of forwards[i] (may contain None
        #: for frozen/param-less layers that still need err routing)
        self.gds: List[Any] = gds or []
        self.rng_stream = rng_stream
        #: None = the device's policy (bf16 on TPU, f32 elsewhere)
        self.compute_dtype = compute_dtype
        #: a jax.sharding.Mesh when DataParallel is installed — the
        #: steps are then jitted with the minibatch sharded over the
        #: mesh's data axis and params replicated (parallel/ package)
        self.mesh = None
        #: True = the loader's resident dataset is ROW-SHARDED over
        #: the mesh (each device holds 1/N of the rows; set from
        #: loader.shard_resident at initialize).  The in-trace gather
        #: then runs as a shard_map local gather + psum assembly
        #: (batching.make_sharded_row_gather) — f32-exact vs the
        #: replicated placement — and the dataset/target step args are
        #: jitted with the row-sharded in_sharding.
        self.data_sharded = False
        self._train_step = None
        self._eval_step = None
        #: the Keel ExecutionCore (engine/core.py): every placement /
        #: donation / compile decision this runner makes goes through
        #: it, and it charges the params+opt footprint to the process
        #: HBM arbiter's `train` pool.  Built with the steps (the mesh
        #: must be resolved first).
        self._core = None
        self._params: Optional[Dict[str, Dict[str, Any]]] = None
        self._opt: Optional[Dict[str, Dict[str, Any]]] = None
        self._rng_counter = 0
        #: True = the loader's dataset is not HBM-resident; the step
        #: consumes host-assembled superstep batches (resolved at
        #: initialize from loader.device_resident)
        self.streaming = False
        #: on-device metric accumulator [n_err, loss_sum, count] and
        #: confusion accumulator, reset at each take_class_metrics()
        self._acc: Any = None
        self._conf: Any = None
        #: per-minibatch ABSOLUTE learning rates, shape (k, n_gd, 2)
        #: [(lr_weights, lr_bias)], written by LearningRateAdjust as a
        #: traced argument (no retrace).  None = read the live gd unit
        #: rates each firing (constant within the superstep).  Absolute
        #: rates, not scales: the traced step must never bake a
        #: schedule-mutated rate as its base (that made every scale
        #: multiply the wrong constant once lr_adjust ran before the
        #: first train dispatch).
        self.lr_rates = None
        #: cumulative samples dispatched (host-side mask sums), train
        #: and eval separately — feed the end-of-run MFU report
        #: (veles_tpu/profiling.py): train costs fwd+bwd, eval fwd only
        self.processed_images = 0.0
        self.processed_eval_images = 0.0
        #: streaming upload double-buffer: the last two device_put
        #: batches; the third dispatch blocks on the oldest transfer
        from collections import deque
        self._inflight: Any = deque()
        #: cumulative seconds this runner spent submitting streaming
        #: uploads and blocked on their drain — the transfer-busy
        #: numerator of the input pipeline's efficiency accounting
        #: (bench.py): on a link-bound host a perfect pipeline spends
        #: ~all its wall here, and the remainder is framework overhead
        self.stream_transfer_seconds = 0.0
        #: this runner's share of the process-wide
        #: ``fused.stream_transfer_bytes`` registry counter — the ONE
        #: write site (_run_streaming) increments both, so the
        #: ``stream_transfer_bytes`` property keeps its per-runner
        #: meaning while the registry carries the process aggregate
        self._stream_bytes = 0
        #: times a streaming upload OOMed and recovered by draining
        #: the double-buffer (Faultline telemetry; see _run_streaming)
        self.stream_oom_retries = 0
        #: which step kinds ("train"/"eval") have dispatched — the
        #: first firing of each is the compile+execute sample and is
        #: recorded apart from the steady-state dispatch histogram
        self._dispatch_seen: set = set()
        #: monotonic timestamp of the first firing (end-of-run
        #: throughput/MFU summary, see _record_telemetry_summary)
        self._first_run_ts = None

    _unpicklable = AcceleratedUnit._unpicklable + (
        "_train_step", "_eval_step", "_params", "_opt", "mesh",
        "_batch_sharding", "_acc", "_conf", "_inflight", "_core")

    @property
    def stream_transfer_bytes(self) -> int:
        """Cumulative host->device bytes THIS runner's streaming path
        shipped (pixel batches + targets/labels) — the wire-format
        accounting: divided by processed images it certifies what the
        codec actually moved per sample.  The former plain attribute
        (and its ``__setstate__`` back-compat shim) is now a read-only
        view over the accounting that also feeds the process-wide
        ``fused.stream_transfer_bytes`` registry counter."""
        return self._stream_bytes

    # -- pytree assembly ----------------------------------------------

    def _collect_params(self) -> Dict[str, Dict[str, Any]]:
        return {f.name: f.gather_params() for f in self.forwards}

    def _collect_opt(self) -> Dict[str, Dict[str, Any]]:
        opt = {}
        for gd in self.gds:
            if gd is None:
                continue
            gd.reconcile_velocities()   # param shapes may have changed
            opt[gd.name] = {k: v.unmap()
                            for k, v in gd.accumulated_grads.items()}
        return opt

    def _scatter_params(self, params, opt) -> None:
        """Rebind unit Vectors to the donated-step outputs so the rest
        of the framework observes updated weights."""
        for f in self.forwards:
            p = params[f.name]
            for pname, vec in f.param_vectors().items():
                if pname in p:
                    vec.devmem = p[pname]
        for gd in self.gds:
            if gd is None:
                continue
            for k, vec in gd.accumulated_grads.items():
                vec.devmem = opt[gd.name][k]

    # -- trace construction -------------------------------------------

    def _has_targets(self) -> bool:
        return hasattr(self.evaluator, "target")

    def _want_confusion(self) -> bool:
        ev = self.evaluator
        return bool(getattr(ev, "compute_confusion", False)) and \
            getattr(ev, "n_classes", None) is not None

    def _conf_shape(self) -> Tuple[int, int]:
        if self._want_confusion():
            n = self.evaluator.n_classes
            return (n, n)
        return (1, 1)

    def _resolved_dtype(self):
        return batching.resolve_compute_dtype(self.compute_dtype,
                                              self.device)

    def _build_steps(self) -> None:
        import jax.numpy as jnp
        from jax import lax

        from veles_tpu.engine import core as engine_core

        forwards = list(self.forwards)
        gds = list(self.gds)
        evaluator = self.evaluator
        want_confusion = self._want_confusion()
        seed = prng.get(self.rng_stream).seed
        cd = self._resolved_dtype()
        out_shape = tuple(forwards[-1].output.shape)
        streaming = self.streaming
        if self._core is not None:    # invalidate_trace rebuild: the
            self._core.release()      # old ledger entry must not leak
        core = self._core = engine_core.ExecutionCore(
            self.device, self.mesh, pool="train", name=self.name)
        # the shared Keel trace bodies: quantized wire ingest, the
        # forward chain with residuals, and the backward+SGD walk —
        # composing them here traces the identical jaxpr the
        # pre-refactor loop did (parity pinned by test_engine_core)
        ingest = engine_core.build_ingest(
            getattr(self.loader, "dequant", None))
        forward_pass = engine_core.build_forward(forwards, seed, cd)
        backward_update = engine_core.build_backward(forwards, gds, cd)

        cast = batching.make_caster(cd)

        def metrics_of(out, target, mask):
            m = evaluator.metrics_fn(out.astype(jnp.float32), target,
                                     mask)
            if want_confusion:
                n = evaluator.n_classes
                conf = jnp.zeros((n, n), jnp.int32)
                conf = conf.at[target, m["max_idx"]].add(
                    mask.astype(jnp.int32))
                m["confusion"] = conf
            return m

        data_sharded = self.data_sharded and self.mesh is not None
        if data_sharded:
            # row-sharded residency: the gather crosses device shards
            # (local gather + exact psum), and the assembled minibatch
            # re-enters the SAME batch sharding the replicated-data
            # path uses — identical downstream program, so residency
            # placement cannot change the numerics
            sharded_gather = batching.make_sharded_row_gather(self.mesh)
            _mb_rows = core.row_sharding

        def gather(dataset, target_store, indices):
            if data_sharded:
                x, t = sharded_gather(indices, dataset, target_store)
                x = lax.with_sharding_constraint(x, _mb_rows)
                t = lax.with_sharding_constraint(t, _mb_rows)
                return x, t
            x = jnp.take(dataset, indices, axis=0)
            t = jnp.take(target_store, indices, axis=0)
            return x, t

        def accumulate(acc, conf, m):
            acc = acc + jnp.stack([m["n_err"], m["loss_sum"],
                                   m["count"]])
            if want_confusion:
                conf = conf + m["confusion"]
            return acc, conf

        def train_body(dataset, target_store):
            def body(carry, xs):
                params, opt, acc, conf, rc = carry
                # lr is this minibatch's (n_gd, 2) row of absolute
                # (weights, bias) rates — per-iteration schedules stay
                # exact inside a superstep (round-1 VERDICT weak #8)
                if streaming:
                    # host-assembled batch rows ride the scan directly;
                    # no HBM-resident dataset exists to gather from
                    x, target, mask, lr = xs
                else:
                    indices, mask, lr = xs
                    x, target = gather(dataset, target_store, indices)
                x = ingest(x)
                cparams = cast(params)
                out, residuals = forward_pass(cparams, x, rc, True)
                m = metrics_of(out, target, mask)
                err = m.pop("err_output")
                new_params, new_opt = backward_update(
                    cparams, params, opt, residuals, err, lr)
                acc, conf = accumulate(acc, conf, m)
                return (new_params, new_opt, acc, conf, rc + 1), None
            return body

        # scan unroll for the train loop: >1 lets XLA schedule one
        # minibatch's weight updates behind the next one's matmuls at
        # the cost of an unroll-times bigger program (slower compile).
        # Measured on v5e (docs/perf.md): no win at AlexNet scale, so
        # the default stays 1; the knob remains for smaller nets where
        # per-step overheads matter more.
        import os
        unroll = max(1, int(os.environ.get("VELES_TPU_SCAN_UNROLL",
                                           "1")))

        def train_step(params, opt, acc, conf, dataset, target_store,
                       indices, mask, lr_rates, rng_counter):
            body = train_body(dataset, target_store)
            (params, opt, acc, conf, _), _ = lax.scan(
                body, (params, opt, acc, conf, rng_counter),
                (indices, mask, lr_rates), unroll=unroll)
            return params, opt, acc, conf

        def train_step_stream(params, opt, acc, conf, xb, tb, mask,
                              lr_rates, rng_counter):
            body = train_body(None, None)
            (params, opt, acc, conf, _), _ = lax.scan(
                body, (params, opt, acc, conf, rng_counter),
                (xb, tb, mask, lr_rates), unroll=unroll)
            return params, opt, acc, conf

        def eval_step(params, acc, conf, dataset, target_store,
                      indices, mask, rng_counter):
            cparams = cast(params)

            def body(carry, xs):
                acc, conf, _, rc = carry
                indices, mask = xs
                x, target = gather(dataset, target_store, indices)
                out, _ = forward_pass(cparams, ingest(x), rc, False)
                m = metrics_of(out, target, mask)
                m.pop("err_output")
                acc, conf = accumulate(acc, conf, m)
                return (acc, conf, out.astype(jnp.float32), rc + 1), None

            init_out = jnp.zeros(out_shape, jnp.float32)
            (acc, conf, out, _), _ = lax.scan(
                body, (acc, conf, init_out, rng_counter),
                (indices, mask))
            return acc, conf, out

        def eval_step_stream(params, acc, conf, xb, tb, mask,
                             rng_counter):
            cparams = cast(params)

            def body(carry, xs):
                acc, conf, _, rc = carry
                x, target, mask = xs
                out, _ = forward_pass(cparams, ingest(x), rc, False)
                m = metrics_of(out, target, mask)
                m.pop("err_output")
                acc, conf = accumulate(acc, conf, m)
                return (acc, conf, out.astype(jnp.float32), rc + 1), None

            init_out = jnp.zeros(out_shape, jnp.float32)
            (acc, conf, out, _), _ = lax.scan(
                body, (acc, conf, init_out, rng_counter),
                (xb, tb, mask))
            return acc, conf, out

        if self.mesh is not None:
            # SPMD data parallelism: minibatch rows sharded over the
            # data axis, params/dataset replicated.  mask.sum() and the
            # per-param batch reductions cross the sharded axis, so the
            # partitioner emits the gradient allreduce (ICI psum) —
            # this IS the master-slave aggregation, in-compiler.
            repl = core.replicated
            # streaming batch rows ride the batch sharding — each
            # device receives only its slice of every minibatch
            batch = self._batch_sharding = core.batch_sharding
            if streaming:
                self._train_step = core.jit(
                    train_step_stream, donate=(0, 1, 2, 3),
                    in_shardings=(repl, repl, repl, repl, batch,
                                  batch, batch, repl, repl))
                self._eval_step = core.jit(
                    eval_step_stream, donate=(1, 2),
                    in_shardings=(repl, repl, repl, batch, batch,
                                  batch, repl))
            else:
                # the resident store enters row-sharded under Lattice
                # (1/N rows per device), replicated otherwise — the
                # ONLY in_sharding difference between the two modes
                store = core.row_sharding if data_sharded else repl
                self._train_step = core.jit(
                    train_step, donate=(0, 1, 2, 3),
                    in_shardings=(repl, repl, repl, repl, store, store,
                                  batch, batch, repl, repl))
                self._eval_step = core.jit(
                    eval_step, donate=(1, 2),
                    in_shardings=(repl, repl, repl, store, store,
                                  batch, batch, repl))
        elif streaming:
            self._train_step = core.jit(train_step_stream,
                                        donate=(0, 1, 2, 3))
            self._eval_step = core.jit(eval_step_stream,
                                       donate=(1, 2))
        else:
            self._train_step = core.jit(train_step,
                                        donate=(0, 1, 2, 3))
            self._eval_step = core.jit(eval_step, donate=(1, 2))

    # -- lifecycle -----------------------------------------------------

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not any(self.loader.class_lengths):
            # Workflow.initialize retries on AttributeError — the
            # loader must load first so its residency mode is known
            raise AttributeError(
                f"{self.name}: loader has not loaded its data yet")
        self.streaming = not getattr(self.loader, "device_resident",
                                     True)
        if self.streaming and self.device.is_jax:
            if getattr(self.loader, "dequant", None) is None:
                # assemble streaming batches directly in the compute
                # dtype (prefetch thread): the trace's first op is this
                # cast anyway, and doing it host-side halves H2D bytes
                # on the bf16 platforms where the transfer bottlenecks
                self.loader.stream_dtype = \
                    np.dtype(self._resolved_dtype())
            # else: quantized ingest — the wire is uint8 (1 byte/px,
            # half the bf16 wire) and the traced prologue dequantizes;
            # a stream_dtype cast would widen the bytes back out
        # row-sharded residency (Lattice): resolved by the loader's
        # per-device budget accounting — the runner only follows
        self.data_sharded = bool(
            self.mesh is not None and not self.streaming
            and getattr(self.loader, "shard_resident", False))
        if self.mesh is not None:
            # sharded jit partitions poorly around custom-call kernels;
            # units with hand kernels (LRN) must take their XLA form
            for f in self.forwards:
                f.force_xla = True
            # the STATIC minibatch shape is max_minibatch_size, which
            # clamps below minibatch_size when every class is smaller —
            # DataParallel.install() can only check minibatch_size
            # (load_data hasn't run yet there)
            n = int(self.mesh.devices.size)
            mb = self.loader.max_minibatch_size
            if mb % n:
                raise ValueError(
                    f"static minibatch shape {mb} (loader "
                    f"max_minibatch_size) not divisible by mesh size "
                    f"{n}; lower minibatch_size or pad the dataset")
        if self._train_step is None:
            self._build_steps()

    def _target_store(self):
        ld = self.loader
        if self._has_targets():
            return ld.original_targets.unmap()
        return ld.original_labels.unmap()

    def _fresh_acc(self) -> Tuple[np.ndarray, np.ndarray]:
        return (np.zeros(3, np.float32),
                np.zeros(self._conf_shape(), np.int32))

    def _superstep_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        ld = self.loader
        if ld.superstep_k and ld.superstep_indices is not None:
            return ld.superstep_indices, ld.superstep_mask
        # generic loaders (zmq slave jobs) provide one minibatch
        return (np.asarray(ld.minibatch_indices.map_read())[None],
                np.asarray(ld.minibatch_mask.map_read())[None])

    def run(self) -> None:
        import time
        ld = self.loader
        self._ensure_params()
        if self._train_step is None:   # invalidated (e.g. a resize)
            self._build_steps()
        if self._acc is None:
            self._acc, self._conf = self._fresh_acc()
        indices, mask = self._superstep_arrays()
        k = indices.shape[0]
        train = ld.minibatch_class == TRAIN
        images = float(np.sum(mask))
        if train:
            self.processed_images += images
        else:
            self.processed_eval_images += images
        if self._first_run_ts is None:
            self._first_run_ts = time.monotonic()
        t0 = time.perf_counter()
        if self.streaming:
            self._run_streaming(ld, k, mask, train)
        else:
            self._run_resident(ld, k, indices, mask, train)
        self._rng_counter += k
        self._record_dispatch("train" if train else "eval",
                              time.perf_counter() - t0, k, images)

    def _record_dispatch(self, kind: str, dt: float, k: int,
                         images: float) -> None:
        """Per-dispatch wall time into the registry.  The FIRST firing
        of each step kind traces + compiles, so it lands in its own
        gauge (and the journal) instead of polluting the steady-state
        histogram the p50/p99 report reads.  Wall here is host-observed
        submission time — on an async backend the device may still be
        chewing; the honest end-to-end barrier remains the metric-carry
        fetch (take_class_metrics / bench.py sync_images)."""
        if not telemetry.enabled():
            return
        if kind not in self._dispatch_seen:
            self._dispatch_seen.add(kind)
            telemetry.gauge(
                f"fused.first_{kind}_dispatch_seconds").set(dt)
            telemetry.event(events.EV_FUSED_FIRST_DISPATCH, kind=kind,
                            seconds=round(dt, 4),
                            streaming=bool(self.streaming))
        else:
            telemetry.histogram(
                f"fused.{kind}_dispatch_seconds").record(dt)
        telemetry.counter(events.CTR_FUSED_DISPATCHES).inc()
        telemetry.counter(f"fused.{kind}_seconds").inc(dt)
        telemetry.counter(events.CTR_FUSED_MINIBATCHES).inc(k)
        telemetry.counter(f"fused.{kind}_images").inc(images)

    def _run_resident(self, ld, k, indices, mask, train: bool) -> None:
        dataset = ld.original_data.unmap()
        targets = self._target_store()
        if self.mesh is not None:
            # Vectors upload replicated (MeshJaxDevice.put); the batch
            # args must enter the step sharded over the data axis —
            # replicated->sharded is a local slice, no communication.
            indices = self._core.put(indices, self._batch_sharding)
            mask = self._core.put(mask, self._batch_sharding)
        if train:
            self._params, self._opt, self._acc, self._conf = \
                self._train_step(
                    self._params, self._opt, self._acc, self._conf,
                    dataset, targets, indices, mask,
                    self._lr_rates_array(k), self._rng_counter)
            self._scatter_params(self._params, self._opt)
        else:
            self._acc, self._conf, out = self._eval_step(
                self._params, self._acc, self._conf, dataset, targets,
                indices, mask, self._rng_counter)
            self.forwards[-1].output.devmem = out

    def _run_streaming(self, ld, k, mask, train: bool) -> None:
        """Dispatch over the loader's host-assembled superstep batch.
        The dispatch is async: while the device chews on this group the
        loader's prefetch thread is already assembling the next one —
        that concurrency IS the input pipeline (no resident dataset).

        The upload is an explicit double-buffered ``device_put``: at
        most two superstep batches are in flight, so a device that
        falls behind the host (or a slow tunnel that falls behind the
        dispatch loop) back-pressures the loop instead of piling
        unsent host batches into RAM without bound."""
        import time
        xb = ld.superstep_data
        tb = ld.superstep_targets if self._has_targets() \
            else ld.superstep_labels
        if xb is None or tb is None:
            raise RuntimeError(
                f"{self.name}: streaming mode but the loader produced "
                f"no superstep batch (superstep_data/"
                f"{'targets' if self._has_targets() else 'labels'})")
        dst = self._batch_sharding if self.mesh is not None \
            else self.device.jax_device
        # wire-byte accounting BEFORE the upload rebinds xb/tb: what
        # the codec actually ships per sample (uint8 ingest = 1
        # byte/pixel; bf16 = 2; f32 = 4) — bench.py and the codec
        # tests divide this by processed images
        n_wire = int(xb.nbytes) + int(tb.nbytes)
        self._stream_bytes += n_wire
        telemetry.counter(
            events.CTR_FUSED_STREAM_TRANSFER_BYTES).inc(n_wire)
        t_transfer = time.perf_counter()
        for attempt in (1, 2):
            try:
                from veles_tpu import faults
                if faults.fire("device.oom_on_put", site="stream"):
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: fault-injected OOM on "
                        "the streaming upload")
                xb_dev = self._core.put(xb, dst)
                tb_dev = self._core.put(tb, dst)
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — transient HBM
                # pressure: the double-buffer may still hold two
                # superstep batches worth of HBM — drain it and retry
                # ONCE before giving up (bounded degradation, not an
                # unbounded retry loop)
                if "RESOURCE_EXHAUSTED" not in str(e) or attempt == 2:
                    raise
                self.warning(
                    "streaming upload hit device OOM (%s); draining "
                    "the in-flight double-buffer and retrying once", e)
                self.stream_oom_retries += 1
                telemetry.counter(
                    events.CTR_FUSED_STREAM_OOM_RETRIES).inc()
                telemetry.event(events.EV_DEVICE_OOM_RETRY, site="stream")
                while self._inflight:
                    for buf in self._inflight.popleft():
                        buf.block_until_ready()
        xb, tb = xb_dev, tb_dev
        if self.mesh is not None:
            mask = self._core.put(mask, self._batch_sharding)
        self._inflight.append((xb, tb))
        if len(self._inflight) > 2:
            for buf in self._inflight.popleft():
                buf.block_until_ready()
        dt_transfer = time.perf_counter() - t_transfer
        self.stream_transfer_seconds += dt_transfer
        telemetry.counter(
            events.CTR_FUSED_STREAM_TRANSFER_SECONDS).inc(
            dt_transfer)
        if train:
            self._params, self._opt, self._acc, self._conf = \
                self._train_step(
                    self._params, self._opt, self._acc, self._conf,
                    xb, tb, mask, self._lr_rates_array(k),
                    self._rng_counter)
            self._scatter_params(self._params, self._opt)
        else:
            self._acc, self._conf, out = self._eval_step(
                self._params, self._acc, self._conf, xb, tb, mask,
                self._rng_counter)
            self.forwards[-1].output.devmem = out

    def _lr_rates_array(self, k: int) -> np.ndarray:
        """``lr_rates`` as the (k, n_gd, 2) scanned input.  With no
        schedule installed, read the gd units' live rates (constant
        within the superstep, mutable between firings without retrace);
        a 3-D array (per-iteration schedule, written by
        LearningRateAdjust) must match the superstep exactly."""
        if self.lr_rates is None:
            row = np.asarray(
                [[gd.learning_rate, gd.learning_rate_bias]
                 if gd is not None else [0.0, 0.0] for gd in self.gds],
                np.float32)
            return np.broadcast_to(row, (k,) + row.shape)
        lr = np.asarray(self.lr_rates, np.float32)
        if lr.ndim == 2:
            return np.broadcast_to(lr, (k,) + lr.shape)
        if lr.shape[0] != k:
            raise ValueError(
                f"lr_rates has {lr.shape[0]} rows but the superstep "
                f"has {k} minibatches — the schedule and loader "
                f"disagree")
        return lr

    def invalidate_trace(self) -> None:
        """Drop the traced steps and cached pytrees — required after
        anything changes a parameter SHAPE (ResizableAll2All.resize).
        Current param values are synced back to the unit Vectors first
        so nothing is lost; the next firing re-collects and re-jits."""
        self.sync_params_to_vectors()
        self._params = None
        self._opt = None
        self._train_step = None
        self._eval_step = None

    # -- metric intake (Decision / zmq slave) --------------------------

    def stop(self) -> None:
        self._record_telemetry_summary()
        self._inflight.clear()  # release the upload double-buffer
        super().stop()

    def _record_telemetry_summary(self) -> None:
        """End-of-run throughput gauges: wall-clock images/sec since
        the first firing and — where the device's peak is known —
        achieved MFU via profiling.py.  Wall includes host time between
        dispatches, so this is the run's DELIVERED rate (a lower bound
        on engine efficiency), the number an operator reads off
        obs_report; bench.py's barriered windows remain the measured
        engine rate."""
        import time
        if self._first_run_ts is None or not telemetry.enabled():
            return
        elapsed = time.monotonic() - self._first_run_ts
        self._first_run_ts = None   # stop() may run more than once
        images = self.processed_images
        if elapsed <= 0 or images <= 0:
            return
        rate = images / elapsed
        telemetry.gauge(
            events.GAUGE_FUSED_TRAIN_IMAGES_PER_SEC_WALL).set(
            round(rate, 3))
        try:
            from veles_tpu import profiling
            flops = profiling.model_flops_per_sample(
                self.forwards)["train"]
            telemetry.gauge(
                events.GAUGE_FUSED_TRAIN_GFLOPS_PER_IMAGE).set(
                round(flops / 1e9, 4))
            jdev = getattr(self.device, "jax_device", None)
            u = profiling.mfu(rate, flops, jdev) \
                if jdev is not None else None
            if u is not None:
                telemetry.gauge(events.GAUGE_FUSED_MFU).set(round(u, 5))
            telemetry.event(
                events.EV_FUSED_SUMMARY, images=images,
                images_per_sec_wall=round(rate, 2),
                mfu=round(u, 5) if u is not None else None,
                streaming=bool(self.streaming))
        except Exception:  # noqa: BLE001 — summary is best-effort
            pass

    def release_device_state(self, sync: bool = False) -> None:
        """Drop every device buffer this runner (and its forwards)
        holds — params, optimizer state, metric carries, the upload
        double-buffer, and the units' param/output device copies.  For
        callers that build several workflows in one process (bench.py
        measures resident then streaming): the unit graph is cyclic,
        so dropping the workflow reference alone frees nothing until
        a gc cycle collection, and the chip OOMs first.  Kept HERE so
        new device-resident fields get added to the release next to
        their definitions.

        ``sync=True`` pulls the live params to host first, so the
        runner keeps training correctly after the release (a later
        run() re-uploads); ``sync=False`` skips the device->host fetch
        for workflows about to be discarded."""
        if sync:
            self.sync_params_to_vectors()
            for gd in self.gds:
                if gd is not None:  # momentum must survive the release
                    for v in gd.accumulated_grads.values():
                        v.map_read()
        self._params = self._opt = None
        self._acc = self._conf = None
        self._inflight.clear()
        if self._core is not None:
            self._core.release()
        for f in self.forwards:
            for v in f.param_vectors().values():
                if v:
                    v.drop_devmem()
            f.output.drop_devmem()
        for gd in self.gds:
            if gd is None:
                continue
            # optimizer velocity is as large as the params themselves
            for v in gd.accumulated_grads.values():
                v.drop_devmem()

    def take_class_metrics(self) -> Tuple[float, float, float,
                                          Optional[np.ndarray]]:
        """(n_err, loss_sum, count, confusion) accumulated since the
        last call — ONE small device fetch, then reset."""
        if self._acc is None:
            return 0.0, 0.0, 0.0, None
        acc = np.asarray(self._acc)
        conf = np.asarray(self._conf) if self._want_confusion() else None
        self._acc, self._conf = self._fresh_acc()
        return float(acc[0]), float(acc[1]), float(acc[2]), conf

    # -- zmq DCN compat mode (server.py / client.py) -------------------

    def _ensure_params(self) -> None:
        if self._params is None:
            self._params = self._collect_params()
            self._opt = self._collect_opt()
            if self._core is not None:
                # params + optimizer velocities are this runner's HBM
                # footprint: ledger it in the arbiter's train pool
                from veles_tpu.engine.core import tree_nbytes
                self._core.charge(tree_nbytes(self._params)
                                  + tree_nbytes(self._opt))

    def host_params(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Current parameters as host numpy arrays (slave -> diff)."""
        self._ensure_params()
        return {fn: {pn: np.asarray(v) for pn, v in d.items()}
                for fn, d in self._params.items()}

    def set_host_params(self, params) -> None:
        """Adopt master-provided parameters (device upload; velocities
        stay local, as in the reference's slave)."""
        self._ensure_params()
        self._params = {
            fn: {pn: self.device.put(np.asarray(params[fn][pn]))
                 for pn in d}
            for fn, d in self._params.items()}
        self._scatter_params(self._params, self._opt or {})

    # -- snapshot support ---------------------------------------------

    def sync_params_to_vectors(self) -> None:
        """Pull the current param pytree into host Vectors (snapshot)."""
        if self._params is None:
            return
        self._scatter_params(self._params, self._opt or {})
        for f in self.forwards:
            for v in f.param_vectors().values():
                if v:
                    v.map_read()

    def __getstate__(self) -> dict:
        self.sync_params_to_vectors()
        d = super().__getstate__()
        # the wire-byte count snapshots under its public name (older
        # snapshots carried the plain attribute); dispatch bookkeeping
        # is process-local
        d.pop("_stream_bytes", None)
        d.pop("_dispatch_seen", None)
        d.pop("_first_run_ts", None)
        d["stream_transfer_bytes"] = self.stream_transfer_bytes
        # the on-device metric/confusion accumulators are device
        # buffers (hence _unpicklable), but their VALUES are run state:
        # a graceful-stop snapshot taken mid-class (Phoenix preemption
        # lands at any iteration boundary, not only at class ends)
        # would otherwise silently zero the partial class metrics and
        # the resumed epoch's history row undercounts — the
        # chaos-drill hist-parity flake.  Host-materialize them into
        # the snapshot; __setstate__ feeds them back as the carry.
        if self._acc is not None:
            d["_acc_carry"] = np.asarray(self._acc)
            d["_conf_carry"] = np.asarray(self._conf)
        return d

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        # attrs added after a snapshot was written must default
        self.__dict__.setdefault("processed_images", 0.0)
        self.__dict__.setdefault("processed_eval_images", 0.0)
        self.__dict__.pop("lr_scales", None)  # pre-rename snapshots
        self.__dict__.setdefault("lr_rates", None)
        self.__dict__.setdefault("streaming", False)
        self.__dict__.setdefault("data_sharded", False)
        self.__dict__.setdefault("stream_transfer_seconds", 0.0)
        self.__dict__.setdefault("stream_oom_retries", 0)
        # the snapshotted byte count (0 for pre-field snapshots):
        # `stream_transfer_bytes` is a property now, so the plain dict
        # entry the pickle carried must be consumed here
        restored = self.__dict__.pop("stream_transfer_bytes", 0) or 0
        self._stream_bytes = int(restored)
        self._dispatch_seen = set()
        self._first_run_ts = None
        # mid-class metric carry written by __getstate__ (absent in
        # pre-fix snapshots): plain numpy arrays are exactly what
        # run() hands a fresh dispatch, so resume continues the class
        # accumulation where the stop left it
        acc = self.__dict__.pop("_acc_carry", None)
        conf = self.__dict__.pop("_conf_carry", None)
        if acc is not None:
            self._acc, self._conf = acc, conf
        from collections import deque
        if self.__dict__.get("_inflight") is None:  # dropped by pickle
            self._inflight = deque()
        self.__dict__.setdefault("_core", None)


class EnsembleEvalEngine:
    """Device-resident multi-member fused inference.

    The host predictor (ensemble/core.py) iterates members x layers
    calling ``apply_fwd`` with numpy arrays — N x L Python dispatches
    per batch, bypassing the execution engine entirely.  This engine
    stacks every member's param pytree along a leading MEMBER axis,
    ``jax.vmap``s the same pure forward chain over that axis, and
    averages the member probability outputs ON DEVICE, so an N-member
    ensemble prediction is ONE jitted dispatch.  Matmuls/convs run in
    the device's compute dtype (bf16 on TPU) against the f32 stacked
    params, exactly like the fused eval step; probabilities accumulate
    in f32.

    Two data paths, mirroring the training engine's residency split:

    - **streaming**: :meth:`predict_proba` / :meth:`error_pct` upload
      each host batch through ``device.put`` (so ``Device.h2d_bytes``
      accounting stays live) and dispatch once per batch;
    - **resident**: :meth:`attach_dataset` uploads the split ONCE; the
      ``*_resident`` methods then gather minibatch rows from HBM by
      index — repeated evaluation (GA scoring, sweeps) never re-ships
      pixels.

    Error scoring accumulates ``[n_wrong, count]`` in a donated device
    carry across fixed-shape chunks (one compile, no retraces from a
    ragged tail — the tail is mask-padded), and the host fetches 8
    bytes at the end.
    """

    def __init__(self, forwards: List[Any],
                 member_params: List[Dict[str, Dict[str, Any]]],
                 device: Any, compute_dtype: Any = None,
                 shard_members: bool = False) -> None:
        if not member_params:
            raise ValueError("empty ensemble")
        if device is None or not getattr(device, "is_jax", False):
            raise ValueError(
                "EnsembleEvalEngine needs a jax device (TPU or "
                "XLA:CPU); use the host predictor path on numpy")
        mesh = getattr(device, "mesh", None)
        if shard_members and (mesh is None
                              or int(mesh.devices.size) < 2):
            raise ValueError(
                "shard_members needs a mesh device (MeshJaxDevice) "
                "with >= 2 devices")
        self.forwards = list(forwards)
        self.device = device
        self.n_members = len(member_params)
        self.compute_dtype = compute_dtype
        #: True = the stacked member axis is split P/N over the
        #: replica's mesh (the Prism serving placement): each device
        #: holds a whole tile of members, request rows replicate, and
        #: an over-one-device's-budget ensemble serves RESIDENT at
        #: padded/N bytes per device instead of LRU-spilling
        self.member_sharded = bool(shard_members)
        from veles_tpu.engine import core as engine_core
        #: the Keel core: placement + compile seam (the arbiter charge
        #: for served models stays with ResidencyManager, which owns
        #: the admission decision — pool `serve` is ITS ledger row)
        self._core = engine_core.ExecutionCore(
            device, mesh if self.member_sharded else None,
            pool="serve")
        if self.member_sharded:
            n = int(mesh.devices.size)
            pad = (-(-self.n_members // n) * n) - self.n_members
            # padded members repeat member 0: computed harmlessly
            # under vmap, never read by the fixed-order mean below
            member_params = list(member_params) + \
                [member_params[0]] * pad
        #: stacked member-axis length including mesh padding
        self._n_stacked = len(member_params)
        #: stacked params: {fwd_name: {pname: (n_members, ...)}} in HBM
        self._params = batching.stack_member_params(
            self.forwards, member_params, device,
            put=self._put_members if self.member_sharded else None)
        #: HBM bytes the stacked f32 params occupy — the serving
        #: tier's residency-budget accounting (real members, unpadded)
        self.param_bytes = batching.stacked_param_bytes(
            member_params[:self.n_members])
        #: the residency charge PER DEVICE: a member-sharded stack
        #: costs padded/N on each device, a replicated one costs the
        #: full stack everywhere
        if self.member_sharded:
            self.param_bytes_per_device = batching.stacked_param_bytes(
                member_params) // int(mesh.devices.size)
        else:
            self.param_bytes_per_device = self.param_bytes
        self._dataset = None
        self._labels = None
        #: real (unpadded) attached rows; row-sharded attachment pads
        #: the device store to a whole per-device tile
        self._dataset_rows = 0
        #: True = the attached split is row-sharded over the device's
        #: mesh (attach_dataset(shard=...)): per-device HBM is
        #: total/N and the resident gather runs through the shared
        #: shard_map local-gather + psum seam
        self._dataset_sharded = False
        self._predict = None
        self._score = None
        self._predict_resident = None
        self._score_resident = None
        #: request-level serving facade (attach_batcher); dispatch
        #: shapes seen so far split the compile firing out of the
        #: steady-state latency histogram (the PR-7 convention)
        self._batcher = None
        self._served_shapes: set = set()
        #: one-shot post-promotion hook: called (and cleared) by the
        #: next _serve_dispatch — the online.time_to_serve probe
        self._on_next_dispatch = None
        self._build()

    def _resolved_dtype(self):
        return batching.resolve_compute_dtype(self.compute_dtype,
                                              self.device)

    def _put_members(self, array):
        """Member-sharded stacked-param upload: P_pad/N members per
        device through the ONE sharding seam, charging the padded
        total once (not xN like a replicated put)."""
        return self._core.put_members(array)

    def _build(self) -> None:
        import jax.numpy as jnp

        from veles_tpu.engine import core as engine_core

        cd = self._resolved_dtype()
        # the shared Keel body: vmapped member forward + the
        # fixed-order f32 member average (bitwise across placements —
        # on a mesh the replicated constraint all_gathers the member
        # axis first, so both programs run the identical add chain)
        mean_probs = engine_core.build_mean_probs(
            self.forwards, self.n_members, cd,
            replicated=self._core.replicated
            if self.member_sharded else None)

        def score(params, acc, x, labels, mask):
            p = mean_probs(params, x)
            pred = jnp.argmax(p, axis=-1)
            wrong = jnp.sum((pred != labels).astype(jnp.float32) * mask)
            return acc + jnp.stack([wrong, jnp.sum(mask)])

        self._predict = self._core.jit(mean_probs)
        self._score = self._core.jit(score, donate=(1,))
        self._mean_probs = mean_probs
        self._score_fn = score
        self._build_resident(sharded=False)

    def _build_resident(self, sharded: bool) -> None:
        """The resident-gather dispatchers, for either placement.
        Replicated: a plain on-device take.  Row-sharded: the shared
        shard_map local-gather + psum seam
        (batching.make_sharded_row_gather) — each device holds 1/N of
        the attached rows and the assembled minibatch is f32-exact vs
        the replicated gather, so scoring parity is bitwise."""
        import jax.numpy as jnp

        mean_probs, score = self._mean_probs, self._score_fn
        if sharded:
            gather = batching.make_sharded_row_gather(self.device.mesh)
        else:
            def gather(indices, *stores):
                out = tuple(jnp.take(s, indices, axis=0)
                            for s in stores)
                return out[0] if len(stores) == 1 else out

        def predict_resident(params, dataset, indices):
            return mean_probs(params, gather(indices, dataset))

        def score_resident(params, acc, dataset, label_store, indices,
                           mask):
            x, labels = gather(indices, dataset, label_store)
            return score(params, acc, x, labels, mask)

        self._dataset_sharded = sharded
        self._predict_resident = self._core.jit(predict_resident)
        self._score_resident = self._core.jit(score_resident,
                                              donate=(1,))

    # -- streaming path ------------------------------------------------

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """Mean member probabilities for a host batch — one vmapped
        dispatch (a distinct batch shape compiles once)."""
        import time
        t0 = time.perf_counter()
        xb = self.device.put(np.asarray(x, np.float32))
        out = np.asarray(self._predict(self._params, xb))
        self._record_dispatch(time.perf_counter() - t0, len(out))
        return out

    def _record_dispatch(self, dt: float, images: int) -> None:
        """One fetched (host-synchronous) ensemble dispatch: the
        np.asarray/acc fetch IS the barrier, so this wall time covers
        upload + the full vmapped member sweep."""
        if not telemetry.enabled():
            return
        telemetry.histogram(
            events.HIST_ENSEMBLE_DISPATCH_SECONDS).record(dt)
        telemetry.counter(events.CTR_ENSEMBLE_CHUNKS).inc()
        telemetry.counter(events.CTR_ENSEMBLE_SECONDS).inc(dt)
        telemetry.counter(events.CTR_ENSEMBLE_IMAGES).inc(images)
        telemetry.counter(events.CTR_ENSEMBLE_MEMBER_IMAGES).inc(
            images * self.n_members)

    def error_pct(self, x: np.ndarray, labels: np.ndarray,
                  chunk: int = 256) -> float:
        """Classification error % of the averaged ensemble over a host
        split, chunked at a fixed shape with a donated [wrong, count]
        device carry."""
        import time
        x = np.asarray(x, np.float32)
        labels = np.asarray(labels, np.int32)
        chunk = max(1, min(chunk, len(x)))
        acc = self.device.zeros(2, np.float32)
        t0 = time.perf_counter()
        n_chunks = 0
        for i in range(0, len(x), chunk):
            xb, lb, mask = batching.pad_chunk(x[i:i + chunk],
                                              labels[i:i + chunk],
                                              chunk)
            acc = self._score(self._params, acc, self.device.put(xb),
                              self.device.put(lb),
                              self.device.put(mask))
            n_chunks += 1
        acc = np.asarray(acc)
        self._record_score(time.perf_counter() - t0, n_chunks, len(x))
        return 100.0 * float(acc[0]) / max(float(acc[1]), 1.0)

    def _record_score(self, dt: float, chunks: int,
                      images: int) -> None:
        """One whole scoring pass (chunks are dispatched async; the
        donated-carry fetch at the end is the sync, so only the
        pass-level wall is honest)."""
        if not telemetry.enabled():
            return
        telemetry.histogram(
            events.HIST_ENSEMBLE_SCORE_SECONDS).record(dt)
        telemetry.counter(events.CTR_ENSEMBLE_CHUNKS).inc(chunks)
        telemetry.counter(events.CTR_ENSEMBLE_SECONDS).inc(dt)
        telemetry.counter(events.CTR_ENSEMBLE_IMAGES).inc(images)
        telemetry.counter(events.CTR_ENSEMBLE_MEMBER_IMAGES).inc(
            images * self.n_members)

    # -- resident path -------------------------------------------------

    def attach_dataset(self, x: np.ndarray,
                       labels: Optional[np.ndarray] = None,
                       shard: Any = "auto") -> None:
        """Upload an evaluation split ONCE; the ``*_resident`` methods
        gather rows from HBM by index afterwards.

        ``shard``: on a mesh device, ``True`` row-shards the split
        (1/N rows per device — N x the attachable split at the same
        per-device budget), ``False`` replicates it, and ``"auto"``
        follows ``$VELES_MESH_SHARD_DATA`` + the per-device
        ``$VELES_MAX_RESIDENT_BYTES`` budget exactly like the training
        loaders' streaming-vs-resident decision."""
        x = np.asarray(x, np.float32)
        labels = None if labels is None else np.asarray(labels,
                                                       np.int32)
        mesh = getattr(self.device, "mesh", None)
        if mesh is None or int(mesh.devices.size) < 2:
            sharded = False
        elif shard == "auto":
            from veles_tpu import knobs
            from veles_tpu.parallel.mesh import shard_mode
            mode = shard_mode(knobs.get(knobs.MESH_SHARD_DATA))
            sharded = mode == "always" or (
                mode == "auto"
                and x.nbytes > knobs.get(knobs.MAX_RESIDENT_BYTES))
        else:
            sharded = bool(shard)
        if sharded != self._dataset_sharded:
            self._build_resident(sharded=sharded)
        self._dataset_rows = len(x)
        if sharded:
            self._dataset = self.device.put_sharded(x)
            self._labels = None if labels is None else \
                self.device.put_sharded(labels)
        else:
            self._dataset = self.device.put(x)
            self._labels = None if labels is None else \
                self.device.put(labels)

    def predict_proba_resident(self, indices) -> np.ndarray:
        if self._dataset is None:
            raise RuntimeError("attach_dataset() first")
        import time
        t0 = time.perf_counter()
        idx = self.device.put(np.asarray(indices, np.int32))
        out = np.asarray(self._predict_resident(
            self._params, self._dataset, idx))
        self._record_dispatch(time.perf_counter() - t0, len(out))
        return out

    def error_pct_resident(self, n: Optional[int] = None,
                           chunk: int = 256) -> float:
        """Error % over the first ``n`` attached rows (default: all),
        gathered on device — zero pixel re-upload per call."""
        if self._dataset is None or self._labels is None:
            raise RuntimeError("attach_dataset(x, labels) first")
        import time
        # the REAL attached row count — a row-sharded store is padded
        # to a whole per-device tile and the tail must never score
        total = self._dataset_rows if n is None else int(n)
        chunk = max(1, min(chunk, total))
        acc = self.device.zeros(2, np.float32)
        t0 = time.perf_counter()
        n_chunks = 0
        for i in range(0, total, chunk):
            idx, mask = batching.padded_index_chunk(
                i, min(i + chunk, total), chunk)
            acc = self._score_resident(
                self._params, acc, self._dataset, self._labels,
                self.device.put(idx), self.device.put(mask))
            n_chunks += 1
        acc = np.asarray(acc)
        self._record_score(time.perf_counter() - t0, n_chunks, total)
        return 100.0 * float(acc[0]) / max(float(acc[1]), 1.0)

    # -- request-level serving (Hive) ----------------------------------

    def attach_batcher(self, max_batch: int, max_wait_s: float,
                       label: str = "ensemble", sample_shape=None):
        """Arm the request-level API: concurrent :meth:`submit` calls
        coalesce into ONE fixed-shape mask-padded dispatch of up to
        ``max_batch`` rows, flushed after ``max_wait_s`` at the
        latest.  The serving tier (veles_tpu/serve) drives every model
        through this facade; ``submit`` raises until it is armed."""
        from veles_tpu.serve.batcher import MicroBatcher
        if self._batcher is not None:
            return self._batcher
        self._batcher = MicroBatcher(self._serve_dispatch,
                                     max_batch=max_batch,
                                     max_wait_s=max_wait_s,
                                     label=label,
                                     sample_shape=sample_shape)
        return self._batcher

    def submit(self, rows: np.ndarray, deadline_ms=None, ctx=None):
        """Request-level inference: enqueue ``rows`` (one request of
        one or more samples) and return a ``concurrent.futures.Future``
        resolving to the mean member probabilities for exactly those
        rows.  The micro-batching loop coalesces concurrent requests —
        this is the serving tier's whole-dataset-free entry point.
        ``deadline_ms`` (absolute unix-epoch ms) lets the batcher drop
        the request unanswered once nobody is waiting for it; ``ctx``
        (a Flightline :class:`~veles_tpu.trace.TraceContext`) rides
        through so the batcher can attribute queue wait vs device
        dispatch to the request's trace."""
        if self._batcher is None:
            raise RuntimeError("attach_batcher() first — submit() is "
                               "the micro-batched serving API")
        return self._batcher.submit(rows, deadline_ms=deadline_ms,
                                    ctx=ctx)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted request has resolved (the
        SIGTERM drain path).  Returns False on timeout."""
        if self._batcher is None:
            return True
        return self._batcher.drain(timeout)

    def _serve_dispatch(self, xb: np.ndarray) -> np.ndarray:
        """One fixed-shape serving dispatch (the batcher's flush
        callback).  The FIRST firing of each batch shape traces +
        compiles and lands in its own gauge/journal entry (the PR-7
        compile split), so the steady-state latency histogram stays
        clean — and a nonzero ``serve.compiles`` delta across a warm
        window is a recompile regression."""
        import time
        t0 = time.perf_counter()
        out = np.asarray(self._predict(self._params,
                                       self.device.put(xb)))
        dt = time.perf_counter() - t0
        if telemetry.enabled():
            shape = tuple(xb.shape)
            if shape not in self._served_shapes:
                self._served_shapes.add(shape)
                telemetry.counter(events.CTR_SERVE_COMPILES).inc()
                telemetry.gauge(
                    events.GAUGE_SERVE_FIRST_DISPATCH_SECONDS).set(
                    round(dt, 4))
                telemetry.event(events.EV_SERVE_FIRST_DISPATCH,
                                rows=int(shape[0]),
                                seconds=round(dt, 4))
            else:
                telemetry.histogram(
                    events.HIST_SERVE_DISPATCH_SECONDS).record(dt)
            telemetry.counter(events.CTR_SERVE_MEMBER_ROWS).inc(
                int(xb.shape[0]) * self.n_members)
        cb, self._on_next_dispatch = self._on_next_dispatch, None
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a probe must never
                pass           # fail the dispatch it observed
        return out

    def spill_params(self) -> None:
        """Drop the stacked device params (LRU residency spill) while
        keeping the compiled dispatchers — :meth:`restore_params`
        re-uploads without retracing, so a restored model's first
        request pays one H2D transfer, not a recompile."""
        self._params = None

    @property
    def stacked_params(self):
        """The live stacked param pytree (None while spilled) — the
        online promotion gate scores the incumbent through this and
        the shadow trainer seeds its working copy from it."""
        return self._params

    def adopt_stacked_params(self, stacked) -> None:
        """The HBM-to-HBM promotion handoff: replace the served params
        with an already-device-resident pytree of the same structure.
        ONE attribute store — a dispatch that already read the old
        tree finishes on it, every later dispatch reads the new one;
        no request ever sees torn params, and the compiled dispatchers
        (keyed on shapes, which are identical) never retrace.  Callers
        go through ResidencyManager.swap_params, which serializes this
        against spill decisions under the residency lock."""
        self._params = stacked

    def notify_next_dispatch(self, callback) -> None:
        """Arm a one-shot hook fired right after the next serving
        dispatch completes (the last-step-to-first-served-request
        clock of ``online.time_to_serve``)."""
        self._on_next_dispatch = callback

    @property
    def busy(self) -> bool:
        """Rows queued or in flight on the serving facade (plain int
        reads — safe from any thread).  The residency manager's spill
        victim selection skips busy engines: a spill mid-dispatch
        would pull the params out from under the flush thread."""
        b = self._batcher
        return b is not None and b.pending_rows > 0

    def restore_params(self, member_params: List[Dict[str, Dict[
            str, Any]]]) -> None:
        """Re-upload spilled member params (the residency manager
        keeps the host copies — model params are immutable while
        serving).  A member-sharded engine restores to the SAME
        sharded placement, so the compiled dispatchers never retrace."""
        if self.member_sharded:
            pad = self._n_stacked - len(member_params)
            member_params = list(member_params) + \
                [member_params[0]] * pad
            self._params = batching.stack_member_params(
                self.forwards, member_params, self.device,
                put=self._put_members)
        else:
            self._params = batching.stack_member_params(
                self.forwards, member_params, self.device)

    @property
    def resident(self) -> bool:
        return self._params is not None

    def release(self) -> None:
        """Drop every device buffer (stacked params + attached split)
        — same hygiene contract as release_device_state above."""
        if self._batcher is not None:
            self._batcher.close()
            self._batcher = None
        self._params = None
        self._dataset = None
        self._labels = None
        self._predict = self._score = None
        self._predict_resident = self._score_resident = None


#: back-compat alias — the stacking helper moved to the shared
#: fixed-shape machinery module (ops/batching.py)
_stack_member_params = batching.stack_member_params


class PopulationTrainEngine:
    """Population-batched GA training: P same-shape-signature genomes
    trained in ONE vmapped fused scan per loader firing.

    The chip-owning GA evaluator (genetics/worker.py --serve) trains
    genomes strictly one at a time, and GA-scale genome nets (Wine /
    MNIST-FC shapes) leave the MXU almost idle per dispatch.  This
    engine applies the EnsembleEvalEngine move to TRAINING: the init
    param pytree (identical across a cohort — same seed, same shapes)
    is stacked P times along a leading member axis, optimizer state
    and the metric accumulator gain the same axis, per-genome
    hyperparameters (learning rates via the ``lr_rates`` contract,
    weight decay via ``update_params(decays=...)``) become per-member
    vectors, and the fused ``train_body`` chain is ``jax.vmap``ed over
    the member axis inside one jitted donated dispatch.  The dataset
    stays UNBATCHED: gather/ingest run before any batched array flows
    in, so vmap broadcasts them and HBM cost is params x P, not
    data x P.

    Parity contract (pinned in tests/test_ga_cohort.py): the engine
    drives the workflow's OWN loader exactly like the fused control
    loop does (same superstep grouping, same shuffle stream, same
    rng_counter advance on every firing) and mirrors DecisionGD's
    min-error / max_epochs / fail_iterations bookkeeping PER MEMBER on
    the host, so each member's fitness equals what the per-genome
    oracle (a full workflow run of that genome) produces, to f32
    tolerance.  Members that complete early stop updating their
    fitness bookkeeping (their params keep training harmlessly until
    the whole cohort is done — vmap has no per-member early exit).

    The workflow must be built+initialized in fused mode on a jax
    device with a device-resident loader; anything else raises
    ValueError and the caller falls back to the per-genome oracle.

    **Member sharding (Lattice)**: handed a ``mesh`` (or built on a
    workflow whose fused runner carries one), the stacked MEMBER axis
    is sharded over the mesh's data axis — P/N members per device, so
    the HBM cohort cap scales with the device count instead of one
    chip's budget.  Members are embarrassingly parallel (no
    cross-member reduction anywhere in the train body), so the
    partitioner moves nothing between devices inside the dispatch and
    per-member math is bit-identical to the unsharded stacking —
    fitness parity vs the unsharded engine is f32-EXACT.  The cohort
    is padded to a whole per-device tile by repeating member 0
    (padded members train harmlessly; their fitness rows are sliced
    off).  The dataset/targets are placed REPLICATED over the mesh
    (GA-scale datasets are small — sharding capacity is the
    row-sharded residency path's job, not this one's).

    **Zoo long tail (Menagerie)**: the step body is composed from the
    shared Keel builders (``build_forward`` / ``build_backward``), so
    any unit the fused trace supports cohorts for free — including
    CD-k RBM pretraining workflows (binarization + rbm layers): the
    CD chain's Bernoulli keys thread through the same (seed,
    rng_counter) contract the per-genome fused run uses, so a CD
    cohort's member params are BITWISE-equal to per-genome runs
    (pinned in tests/test_ga_cohort.py).  The SOM, which has no
    gradient chain, gets its own engine —
    :class:`veles_tpu.ops.kohonen.SOMPopulationEngine` — with the
    same member-axis contract (``_params``, fitness vector, handoff
    adoption).
    """

    def __init__(self, workflow, member_rates: np.ndarray,
                 member_decays: np.ndarray,
                 compute_dtype: Any = None, mesh: Any = None) -> None:
        fused = getattr(workflow, "fused", None)
        if fused is None or fused.loader is None or \
                fused._train_step is None:
            raise ValueError("PopulationTrainEngine needs a workflow "
                             "initialized in fused mode")
        device = fused.device
        if device is None or not getattr(device, "is_jax", False):
            raise ValueError(
                "PopulationTrainEngine needs a jax device (TPU or "
                "XLA:CPU); per-genome evaluation is the numpy path")
        self.workflow = workflow
        self.fused = fused
        self.loader = fused.loader
        self.forwards = list(fused.forwards)
        self.gds = list(fused.gds)
        self.evaluator = fused.evaluator
        self.decision = workflow.decision
        self.lr_adjust = getattr(workflow, "lr_adjust", None)
        self.device = device
        self.compute_dtype = compute_dtype
        #: True = the loader's dataset is not HBM-resident: the cohort
        #: consumes the loader's host-assembled superstep batches
        #: (per-firing uploads through the Keel seam, broadcast over
        #: the member axis) instead of gathering from a resident
        #: store.  This LIFTS the dataset-must-fit constraint — HBM
        #: holds params x P plus two in-flight batches, never the
        #: dataset — with fitness parity exact vs the resident path
        #: (same rows, same order, same trace bodies).
        self.streaming = bool(fused.streaming or not getattr(
            fused.loader, "device_resident", True))
        rates = np.asarray(member_rates, np.float32)
        decays = np.asarray(member_decays, np.float32)
        n_gd = len(self.gds)
        if rates.shape != decays.shape or rates.ndim != 3 or \
                rates.shape[1:] != (n_gd, 2):
            raise ValueError(
                f"member hyperparameters must be (P, {n_gd}, 2) "
                f"[lr, lr_bias] / [wd, wd_bias] arrays; got "
                f"{rates.shape} / {decays.shape}")
        self.n_members = int(rates.shape[0])
        # -- member sharding (Lattice): resolve the mesh + knob ------
        if mesh is None:
            mesh = getattr(fused, "mesh", None)
        self.mesh = mesh if (mesh is not None
                             and int(mesh.devices.size) > 1) else None
        if self.mesh is not None:
            from veles_tpu import knobs
            from veles_tpu.parallel.mesh import shard_mode
            if shard_mode(knobs.get(knobs.MESH_SHARD_MEMBERS)) \
                    == "never":
                self.mesh = None
        self.member_sharded = self.mesh is not None
        from veles_tpu.engine import core as engine_core
        #: the Keel core: all member/replicated placement, donation,
        #: and the cohort-pool arbiter charge route through it
        self._core = engine_core.ExecutionCore(
            device, self.mesh, pool="cohort")
        if self.member_sharded:
            n_dev = int(self.mesh.devices.size)
            (rates, decays), self._n_stacked = batching.pad_members(
                [rates, decays], n_dev)
        else:
            self._n_stacked = self.n_members
        self._rates = rates
        self._wd = self._put_members(decays)
        # P copies of the single init pytree (Vectors hold the host
        # master copy after initialize) stacked on the member axis
        # (padded members are more copies of the same init)
        host = {f.name: {pn: np.asarray(v.map_read(), np.float32)
                         for pn, v in f.param_vectors().items()}
                for f in self.forwards}
        self._params = batching.stack_member_params(
            self.forwards, [host] * self._n_stacked, device,
            put=self._put_members)
        self._opt = {}
        for gd in self.gds:
            if gd is None or not gd.accumulated_grads:
                continue
            self._opt[gd.name] = {
                k: self._zeros_members((self._n_stacked,)
                                       + tuple(v.shape))
                for k, v in gd.accumulated_grads.items()}
        self._acc = self._fresh_cohort_acc()
        self._rng_counter = 0
        self._la_iteration = 0
        self._train_step = None
        self._eval_step = None
        self._build()
        # stacked params + velocities + decays are the cohort's whole
        # HBM footprint (the dataset never stacks, and in streaming
        # mode never even uploads): ledger it in the arbiter's
        # cohort pool so GA pressure is visible next to serving's
        self._core.charge(
            engine_core.tree_nbytes(self._params)
            + engine_core.tree_nbytes(self._opt)
            + engine_core.tree_nbytes(self._wd))

    # -- member-axis placement (Lattice) ------------------------------

    def _put_members(self, array: np.ndarray):
        """Upload a member-axis-leading array: sharded P/N per device
        on a mesh, a plain device put otherwise."""
        return self._core.put_members(array)

    def _put_replicated(self, array: np.ndarray):
        """Replicate a host array over the engine's mesh (dataset,
        targets, superstep indices/masks — multihost-safe placement),
        or hand it through untouched off-mesh (the single-device jit
        consumes host numpy directly, as before)."""
        return self._core.put_replicated(array)

    def _zeros_members(self, shape):
        return self._core.zeros_members(shape)

    def _fresh_cohort_acc(self):
        if not self.member_sharded:
            return np.zeros((self._n_stacked, 3), np.float32)
        return self._zeros_members((self._n_stacked, 3))

    def _fetch_members(self, acc) -> np.ndarray:
        """One (P, 3) metric fetch, REAL members only.  On a mesh the
        member-sharded accumulator is first re-laid-out replicated (a
        fully-replicated global array is host-fetchable from every
        process — the multihost-safe materialization)."""
        acc = self._core.replicate_for_fetch(acc)
        return np.asarray(acc)[:self.n_members]

    # -- trace construction -------------------------------------------

    def _resolved_dtype(self):
        return batching.resolve_compute_dtype(self.compute_dtype,
                                              self.device)

    def _build(self) -> None:
        import jax.numpy as jnp
        from jax import lax

        from veles_tpu.engine import core as engine_core

        evaluator = self.evaluator
        seed = prng.get(self.fused.rng_stream).seed
        cd = self._resolved_dtype()
        core = self._core
        # the same shared Keel bodies FusedStepRunner composes —
        # cohort members share the per-genome oracle's seed, so
        # dropout masks match it (and each other) exactly; the
        # backward walk takes the per-member decays row
        ingest = engine_core.build_ingest(
            getattr(self.loader, "dequant", None))
        forward_pass = engine_core.build_forward(self.forwards, seed,
                                                cd)
        backward_update = engine_core.build_backward(self.forwards,
                                                     self.gds, cd)

        cast = batching.make_caster(cd)

        def metrics_of(out, target, mask):
            # no confusion matrix: the GA consumes n_err only
            return evaluator.metrics_fn(out.astype(jnp.float32),
                                        target, mask)

        def train_iter(carry, x, target, msk, lrow, wd):
            # one minibatch of one member's train scan — shared by the
            # resident (gathered) and streaming (host-assembled) paths
            params, opt, acc, rc = carry
            x = ingest(x)
            cparams = cast(params)
            out, residuals = forward_pass(cparams, x, rc, True)
            m = metrics_of(out, target, msk)
            err = m.pop("err_output")
            new_params, new_opt = backward_update(
                cparams, params, opt, residuals, err, lrow, wd)
            acc = acc + jnp.stack([m["n_err"], m["loss_sum"],
                                   m["count"]])
            return (new_params, new_opt, acc, rc + 1)

        def member_train(params, opt, acc, lr, wd, dataset,
                         target_store, indices, mask, rc0):
            # ONE member's superstep scan — the same body shape the
            # fused train_step scans, with per-member (lr, wd) closed
            # in via vmapped arguments instead of unit attributes
            def body(carry, xs):
                idx, msk, lrow = xs
                x = jnp.take(dataset, idx, axis=0)
                target = jnp.take(target_store, idx, axis=0)
                return train_iter(carry, x, target, msk, lrow, wd), \
                    None

            (params, opt, acc, _), _ = lax.scan(
                body, (params, opt, acc, rc0), (indices, mask, lr))
            return params, opt, acc

        def member_train_stream(params, opt, acc, lr, wd, xb, tb,
                                mask, rc0):
            # the streaming-cohort scan: batch rows ride the scan
            # directly (broadcast over the member axis by vmap — HBM
            # holds ONE copy of each batch, params x P, zero dataset
            # residency)
            def body(carry, xs):
                x, target, msk, lrow = xs
                return train_iter(carry, x, target, msk, lrow, wd), \
                    None

            (params, opt, acc, _), _ = lax.scan(
                body, (params, opt, acc, rc0), (xb, tb, mask, lr))
            return params, opt, acc

        def eval_iter(acc, cparams, x, target, msk, rc):
            out, _ = forward_pass(cparams, ingest(x), rc, False)
            m = metrics_of(out, target, msk)
            m.pop("err_output")
            return acc + jnp.stack([m["n_err"], m["loss_sum"],
                                    m["count"]])

        def member_eval(params, acc, dataset, target_store, indices,
                        mask, rc0):
            cparams = cast(params)

            def body(carry, xs):
                acc, rc = carry
                idx, msk = xs
                x = jnp.take(dataset, idx, axis=0)
                target = jnp.take(target_store, idx, axis=0)
                return (eval_iter(acc, cparams, x, target, msk, rc),
                        rc + 1), None

            (acc, _), _ = lax.scan(body, (acc, rc0), (indices, mask))
            return acc

        def member_eval_stream(params, acc, xb, tb, mask, rc0):
            cparams = cast(params)

            def body(carry, xs):
                acc, rc = carry
                x, target, msk = xs
                return (eval_iter(acc, cparams, x, target, msk, rc),
                        rc + 1), None

            (acc, _), _ = lax.scan(body, (acc, rc0), (xb, tb, mask))
            return acc

        # member axis on params/opt/acc/lr/wd; dataset, targets,
        # indices, mask, rng_counter broadcast — x stays UNBATCHED
        # through gather+ingest (vmap only batches where member-axis
        # arrays flow in, i.e. from the first matmul on), so the
        # cohort's HBM cost is params x P, not data x P.  The
        # streaming variants broadcast the host-assembled batch the
        # same way: one batch copy serves every member.
        if self.streaming:
            self._train_step = core.jit(
                core.vmap_members(
                    member_train_stream,
                    in_axes=(0, 0, 0, 0, 0, None, None, None, None)),
                donate=(0, 1, 2))
            self._eval_step = core.jit(
                core.vmap_members(
                    member_eval_stream,
                    in_axes=(0, 0, None, None, None, None)),
                donate=(1,))
        else:
            self._train_step = core.jit(
                core.vmap_members(
                    member_train,
                    in_axes=(0, 0, 0, 0, 0, None, None, None, None,
                             None)),
                donate=(0, 1, 2))
            self._eval_step = core.jit(
                core.vmap_members(
                    member_eval,
                    in_axes=(0, 0, None, None, None, None, None)),
                donate=(1,))

    # -- per-member learning-rate schedule ----------------------------

    def _member_lr(self, k: int) -> np.ndarray:
        """(P, k, n_gd, 2) absolute rates for this train firing —
        the member bases run through the workflow's LR policy with the
        SAME (epoch/iteration) argument logic LearningRateAdjust.run
        uses, so scheduled cohorts track the oracle exactly."""
        P, n_gd = self._rates.shape[:2]
        la = self.lr_adjust
        if la is None:
            return np.ascontiguousarray(np.broadcast_to(
                self._rates[:, None], (P, k, n_gd, 2)))
        ld = self.loader
        e = ld.epoch_number
        ended = bool(ld.epoch_ended)

        def t_of(j: int) -> int:
            if la.by == "epoch":
                return e - 1 if (ended and j < k - 1) else e
            return self._la_iteration + j

        out = np.empty((P, k, n_gd, 2), np.float32)
        for j in range(k):
            t = t_of(j)
            for m in range(P):
                for gi in range(n_gd):
                    out[m, j, gi, 0] = la.policy(
                        float(self._rates[m, gi, 0]), t)
                    out[m, j, gi, 1] = la.policy(
                        float(self._rates[m, gi, 1]), t)
        self._la_iteration += k
        return out

    # -- the run loop --------------------------------------------------

    def run(self) -> np.ndarray:
        """Train the whole cohort; returns the (P,) fitness vector —
        each member's min validation n_err (train n_err for valid-less
        configs), the exact quantity ``workflow_fitness`` reads off a
        per-genome run's DecisionGD."""
        from veles_tpu import faults
        from veles_tpu.loader.base import TRAIN, VALID

        with telemetry.span(events.SPAN_GA_COHORT_TRAIN, journal=True,
                            members=self.n_members):
            telemetry.counter(events.CTR_GA_COHORTS).inc()
            telemetry.counter(
                events.CTR_GA_COHORT_MEMBERS).inc(self.n_members)
            return self._run_inner(faults, TRAIN, VALID)

    def _run_inner(self, faults, TRAIN, VALID) -> np.ndarray:
        if faults.fire("device.oom_on_put", site="cohort",
                       members=self.n_members):
            # surfaces exactly like a real cohort OOM: the serve-mode
            # evaluator's chunk trainer catches it, halves the cohort
            # and retries (genetics/worker.py _evaluate_cohort)
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: fault-injected OOM on the "
                "cohort dispatch")
        ld = self.loader
        dec = self.decision
        P = self.n_members
        max_epochs = dec.max_epochs
        fail_iters = dec.fail_iterations
        has_valid = ld.class_lengths[VALID] > 0
        min_valid = np.full(P, np.inf)
        min_valid_epoch = np.full(P, -1, np.int64)
        min_train = np.full(P, np.inf)
        complete = np.zeros(P, bool)
        streaming = self.streaming
        if streaming:
            # streaming cohort: ZERO dataset residency — the only data
            # on device is each firing's host-assembled superstep
            # batch, broadcast across the member axis by the vmap (one
            # copy serves every member)
            dataset = targets = None
        elif self.member_sharded:
            # the engine owns its data placement on the mesh: the
            # replicated copy lives next to the member-sharded stacks
            # regardless of which single device built the workflow
            dataset = self._put_replicated(ld.original_data.map_read())
            tvec = ld.original_targets if self.fused._has_targets() \
                else ld.original_labels
            targets = self._put_replicated(tvec.map_read())
        else:
            dataset = ld.original_data.unmap()
            targets = self.fused._target_store()
        params, opt, acc = self._params, self._opt, self._acc
        while not complete.all():
            ld.run()
            idxs, mask = ld.superstep_indices, ld.superstep_mask
            k = idxs.shape[0]
            klass = ld.minibatch_class
            if klass == TRAIN or klass == VALID:
                mask_dev = self._put_replicated(mask)
                if streaming:
                    xb = ld.superstep_data
                    tb = ld.superstep_targets \
                        if self.fused._has_targets() \
                        else ld.superstep_labels
                    if xb is None or tb is None:
                        raise RuntimeError(
                            "cohort streaming mode but the loader "
                            "produced no superstep batch "
                            "(superstep_data/targets)")
                    xb_dev = self._put_replicated(xb)
                    tb_dev = self._put_replicated(tb)
                else:
                    idx_dev = self._put_replicated(idxs)
            if klass == TRAIN:
                lr = self._put_members(self._member_lr(k))
                if streaming:
                    params, opt, acc = self._train_step(
                        params, opt, acc, lr, self._wd, xb_dev,
                        tb_dev, mask_dev, self._rng_counter)
                else:
                    params, opt, acc = self._train_step(
                        params, opt, acc, lr, self._wd, dataset,
                        targets, idx_dev, mask_dev, self._rng_counter)
            elif klass == VALID:
                if streaming:
                    acc = self._eval_step(params, acc, xb_dev, tb_dev,
                                          mask_dev, self._rng_counter)
                else:
                    acc = self._eval_step(params, acc, dataset,
                                          targets, idx_dev, mask_dev,
                                          self._rng_counter)
            # TEST firings never feed fitness: skip the dispatch but
            # keep the rng_counter advance so dropout streams stay
            # aligned with the oracle's firing count
            self._rng_counter += k
            if not bool(ld.class_ended):
                continue
            a = self._fetch_members(acc)  # one (P, 3) fetch per class
            acc = self._fresh_cohort_acc()
            err = a[:, 0].astype(np.float64)
            live = ~complete
            if klass == VALID:
                # DecisionGD.on_validation_ended, per member: strict
                # improvement, epoch BEFORE the train class increments
                better = live & (err < min_valid)
                min_valid = np.where(better, err, min_valid)
                min_valid_epoch = np.where(better, ld.epoch_number,
                                           min_valid_epoch)
            if klass == TRAIN:
                if not has_valid:
                    better = live & (err < min_train)
                    min_train = np.where(better, err, min_train)
                # DecisionGD.on_train_ended: epoch_number has already
                # incremented past the ended epoch by now
                epoch = ld.epoch_number
                if max_epochs is not None and epoch >= max_epochs:
                    complete[:] = True
                if has_valid:
                    complete |= (min_valid_epoch >= 0) & \
                        (epoch - min_valid_epoch > fail_iters)
        self._params, self._opt, self._acc = params, opt, acc
        return min_valid if has_valid else min_train

    def release(self) -> None:
        """Drop the stacked device state (params + velocities + wd) —
        same hygiene contract as release_device_state: a serve-mode
        evaluator lives across many cohorts and HBM must not
        accumulate."""
        self._params = None
        self._opt = None
        self._acc = None
        self._wd = None
        self._train_step = self._eval_step = None
        self._core.release()


#: back-compat alias — the chunk/pad helper moved to ops/batching.py
_pad_chunk = batching.pad_chunk
