"""Pooling units.

Reference parity: veles/znicz/pooling.py (``MaxPooling``,
``AvgPooling``, ``StochasticPooling``; max stores argmax offsets for
the backward pass) and veles/znicz/gd_pooling.py (error routing through
stored offsets / uniform spread).

TPU path: ``lax.reduce_window`` (max/avg) — backward derived with
``jax.vjp`` (XLA's select-and-scatter).  Stochastic pooling samples a
window element with probability proportional to its magnitude via the
Gumbel-max trick inside the trace (deterministic per step key); its
eval mode is the reference's probability-weighted average.  The numpy
golden path stores explicit argmax offsets like the reference kernels.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from veles_tpu.ops.conv import _pair, conv_out_size, im2col, col2im
from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


class PoolingBase(ForwardUnit):
    has_params = False

    def __init__(self, workflow=None, kx: int = 2, ky: int = 2,
                 sliding: Any = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = kx, ky
        self.sliding = _pair(sliding) if sliding is not None else (ky, kx)

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        sy, sx = self.sliding
        return (b, conv_out_size(h, self.ky, 0, sy),
                conv_out_size(w, self.kx, 0, sx), c)

    def param_shapes(self, input_shape):
        return {}

    def _windows(self, x: np.ndarray) -> np.ndarray:
        """(B,OH,OW,ky*kx,C) numpy window view."""
        p = im2col(x, self.ky, self.kx, (0, 0), self.sliding)
        b, oh, ow, ky, kx, c = p.shape
        return p.reshape(b, oh, ow, ky * kx, c)


class MaxPooling(PoolingBase):
    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        if isinstance(x, np.ndarray):
            return {"output": self._windows(x).max(axis=3)}
        from jax import lax
        return {"output": lax.reduce_window(
            x, -np.inf, lax.max,
            (1, self.ky, self.kx, 1),
            (1,) + tuple(self.sliding) + (1,), "VALID")}

    def apply_fwd(self, params, x, rng=None, train=True):
        if isinstance(x, np.ndarray):
            w = self._windows(x)
            idx = w.argmax(axis=3)          # offsets, reference-style
            y = np.take_along_axis(w, idx[:, :, :, None, :],
                                   axis=3)[:, :, :, 0, :]
            return y, (x, idx)
        y = self.apply(params, {"input": x})["output"]
        return y, (x, y)


class AvgPooling(PoolingBase):
    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        if isinstance(x, np.ndarray):
            return {"output": self._windows(x).mean(axis=3)}
        from jax import lax
        s = lax.reduce_window(
            x, 0.0, lax.add, (1, self.ky, self.kx, 1),
            (1,) + tuple(self.sliding) + (1,), "VALID")
        return {"output": s / float(self.ky * self.kx)}


class StochasticPooling(PoolingBase):
    """Train: sample a window element with p ∝ magnitude (Gumbel-max on
    log|x|); eval: probability-weighted average (Zeiler & Fergus 2013,
    the scheme the reference implements)."""

    stochastic = True

    def _probs(self, xp, w):
        a = xp.abs(w)
        s = a.sum(axis=3, keepdims=True)
        return a / xp.maximum(s, 1e-12)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        # eval mode: weighted average
        x = inputs["input"]
        if isinstance(x, np.ndarray):
            w = self._windows(x)
            return {"output": (w * self._probs(np, w)).sum(axis=3)}
        import jax.numpy as jnp
        w = self._jax_windows(x)
        return {"output": (w * self._probs(jnp, w)).sum(axis=3)}

    def _jax_windows(self, x):
        """(B,OH,OW,ky*kx,C) via gather-free slicing (static k)."""
        import jax.numpy as jnp
        sy, sx = self.sliding
        b, h, w, c = x.shape
        oh = conv_out_size(h, self.ky, 0, sy)
        ow = conv_out_size(w, self.kx, 0, sx)
        parts = []
        for iy in range(self.ky):
            for ix in range(self.kx):
                parts.append(x[:, iy:iy + oh * sy:sy,
                               ix:ix + ow * sx:sx, :])
        return jnp.stack(parts, axis=3)

    def apply_fwd(self, params, x, rng=None, train=True):
        if not train:
            y = self.apply(params, {"input": x})["output"]
            return y, (x, y)
        if isinstance(x, np.ndarray):
            # numpy path draws from the named stream — rng-gating this
            # branch would silently run EVAL pooling during eager
            # training and hand the backward float "indices"
            from veles_tpu import prng as prng_mod
            gen = prng_mod.get("stochastic_pooling").numpy
            w = self._windows(x)
            g = gen.gumbel(size=w.shape).astype(np.float32)
            idx = (np.log(np.abs(w) + 1e-12) + g).argmax(axis=3)
            y = np.take_along_axis(w, idx[:, :, :, None, :],
                                   axis=3)[:, :, :, 0, :]
            return y, (x, idx)
        if rng is None:
            raise ValueError(f"{self.name}: traced train mode needs "
                             "an rng key")
        import jax
        import jax.numpy as jnp
        w = self._jax_windows(x)
        g = jax.random.gumbel(rng, w.shape, w.dtype)
        idx = (jnp.log(jnp.abs(w) + 1e-12) + g).argmax(axis=3)
        y = jnp.take_along_axis(w, idx[:, :, :, None, :],
                                axis=3)[:, :, :, 0, :]
        return y, (x, idx)

    def eager_rng(self):
        if self.device is not None and self.device.is_jax:
            from veles_tpu import prng as prng_mod
            return prng_mod.get("stochastic_pooling").next_key()
        return None


class GDMaxPooling(GradientUnit):
    """Routes err_output to the stored argmax offsets (numpy) or via
    vjp of reduce_window (jax select-and-scatter)."""

    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, res = saved
        if isinstance(err_output, np.ndarray):
            b, oh, ow, c = err_output.shape
            ky, kx = f.ky, f.kx
            idx = res  # argmax offsets saved by apply_fwd
            cols = np.zeros((b, oh, ow, ky * kx, c), err_output.dtype)
            np.put_along_axis(cols, idx[:, :, :, None, :],
                              err_output[:, :, :, None, :], axis=3)
            err_input = col2im(cols.reshape(b, oh, ow, ky, kx, c),
                               x.shape, (0, 0), f.sliding)
            return err_input, {}
        import jax

        def fwd(xx):
            if isinstance(f, StochasticPooling):
                # backward treats the sampled/weighted value like max:
                # route via the saved indices
                return self._jax_gather(xx, res)
            return f.apply({}, {"input": xx})["output"]

        _, vjp = jax.vjp(fwd, x)
        (err_input,) = vjp(err_output)
        return err_input, {}

    def _jax_gather(self, x, idx):
        import jax.numpy as jnp
        f = self.forward
        w = f._jax_windows(x) if hasattr(f, "_jax_windows") else None
        if w is None:
            raise RuntimeError("stochastic routing needs _jax_windows")
        return jnp.take_along_axis(w, idx[:, :, :, None, :],
                                   axis=3)[:, :, :, 0, :]


class GDAvgPooling(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, _ = saved
        scale = 1.0 / float(f.ky * f.kx)
        if isinstance(err_output, np.ndarray):
            b, oh, ow, c = err_output.shape
            cols = np.broadcast_to(
                (err_output * scale)[:, :, :, None, :],
                (b, oh, ow, f.ky * f.kx, c))
            err_input = col2im(
                cols.reshape(b, oh, ow, f.ky, f.kx, c),
                x.shape, (0, 0), f.sliding)
            return err_input, {}
        import jax

        def fwd(xx):
            return f.apply({}, {"input": xx})["output"]

        _, vjp = jax.vjp(fwd, x)
        (err_input,) = vjp(err_output)
        return err_input, {}
