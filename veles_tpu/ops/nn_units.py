"""Bases for neural-network units.

Reference parity: veles/znicz/nn_units.py — ``ForwardBase`` (input,
output, weights, bias Vectors; weight filling from config) and
``GradientDescentBase`` (err_output -> err_input routing, learning
rate / weight decay / momentum, in-place weight update), plus
``NNWorkflow``.

TPU-first contract:

- ``ForwardUnit.apply(params, inputs, rng)`` is PURE and traceable; the
  same Python code usually serves numpy (golden) and jax (TPU) because
  the two share the array API; ops that need backend-specific code
  (conv, pooling) dispatch on array type via ``is_host_array``.
- ``GradientUnit.backward(params, inputs, err_output)`` returns
  ``(err_input, param_grads)``.  The default jax path derives it with
  ``jax.vjp`` of the forward's apply (activation derivative handled by
  the ``activation_mode`` contract for softmax+CE fusion); the numpy
  path is explicit hand-written math — an independent oracle the tests
  compare against.
- Weight update is xp-agnostic: ``w -= lr * (grad + weight_decay * w)``
  with optional momentum buffers, matching the reference's SGD.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from veles_tpu import prng
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.backends import NumpyDevice
from veles_tpu.memory import Vector
from veles_tpu.workflow import Workflow


def is_host_array(x: Any) -> bool:
    """True when ``x`` is a plain numpy array (golden path); False for
    jax arrays and tracers."""
    return isinstance(x, np.ndarray)


class ForwardUnit(AcceleratedUnit):
    """Base forward unit: input -> output, optional weights/bias."""

    #: how a GradientUnit must treat this unit's nonlinearity:
    #: "linear" | "tanh" | "relu" | "sigmoid" | "softmax" (softmax's
    #: derivative is fused into the evaluator's err_output contract).
    activation_mode = "linear"
    has_params = True
    _unpicklable = AcceleratedUnit._unpicklable + ("_last_residual",)

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        #: input is usually an alias to the producer's Vector — set via
        #: link_attrs(prev, ("input", "output")) or direct assignment.
        self.input = Vector(name=f"{self.name}.input")
        self.output = Vector(name=f"{self.name}.output")
        self.weights = Vector(name=f"{self.name}.weights")
        self.bias = Vector(name=f"{self.name}.bias")
        self.include_bias = kwargs.get("include_bias", True)
        self.weights_filling = kwargs.get("weights_filling", "uniform")
        self.weights_stddev = kwargs.get("weights_stddev", None)
        self.bias_filling = kwargs.get("bias_filling", "constant")
        self.bias_stddev = kwargs.get("bias_stddev", 0.0)
        self.declare_output("output", self.output)

    # -- shapes & params ----------------------------------------------

    def output_shape_for(self, input_shape: Tuple[int, ...]) \
            -> Tuple[int, ...]:
        raise NotImplementedError

    def param_shapes(self, input_shape: Tuple[int, ...]) \
            -> Dict[str, Tuple[int, ...]]:
        """{} when the unit has no parameters."""
        return {}

    def weight_fan_in(self, shape: Tuple[int, ...]) -> int:
        """Inputs contributing to one output element (default: all axes
        but the last are input-side; Deconv overrides — its out-channel
        axis is not last)."""
        return int(np.prod(shape[:-1]))

    def fill_params(self, input_shape: Tuple[int, ...]) -> None:
        """Deterministic init through the 'weights' PRNG stream — both
        backends see identical initial parameters."""
        shapes = self.param_shapes(input_shape)
        if not shapes:
            return
        gen = prng.get("weights").numpy
        for pname, shape in shapes.items():
            filling = self.weights_filling if pname == "weights" \
                else self.bias_filling
            stddev = self.weights_stddev if pname == "weights" \
                else self.bias_stddev
            if stddev is None:
                stddev = 1.0 / np.sqrt(self.weight_fan_in(shape) or 1)
            if filling == "uniform":
                arr = gen.uniform(-stddev * np.sqrt(3), stddev * np.sqrt(3),
                                  shape)
            elif filling == "gaussian":
                arr = gen.normal(0.0, stddev, shape)
            elif filling == "constant":
                arr = np.full(shape, stddev)
            else:
                raise ValueError(f"unknown filling {filling!r}")
            getattr(self, pname).mem = arr.astype(np.float32)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        in_shape = tuple(self.input.shape)
        if not self.weights and self.param_shapes(in_shape):
            self.fill_params(in_shape)
        out_shape = self.output_shape_for(in_shape)
        if not self.output or tuple(self.output.shape) != out_shape:
            self.output.mem = np.zeros(out_shape, np.float32)
        # input/output are scratch on jax devices: input is written by
        # the producer (loader fill / previous unit's devmem rebind)
        # and output by this unit's own firing, always before a read —
        # eagerly uploading their just-allocated zeros costs gigabytes
        # of tunnel traffic + HBM at AlexNet scale and serves nothing
        self.input.initialize(device, upload=False)
        self.output.initialize(device, upload=False)
        for v in self.param_vectors().values():
            if v:
                v.initialize(device)

    def gather_inputs(self) -> Dict[str, Any]:
        return {"input": self.input.unmap()}

    # -- pure compute --------------------------------------------------

    def param_vectors(self) -> Dict[str, Vector]:
        """name -> Vector for every populated parameter.  Subclasses
        with extra parameters (RBM's visible bias) extend the base
        dict — the fused runner and momentum allocation iterate THIS,
        never a hard-coded weights/bias pair."""
        p = {}
        if self.weights:
            p["weights"] = self.weights
        if self.bias and self.include_bias:
            p["bias"] = self.bias
        return p

    def gather_params(self) -> Dict[str, Any]:
        return {k: v.unmap() for k, v in self.param_vectors().items()}

    def apply(self, params: Dict[str, Any], inputs: Dict[str, Any],
              rng: Any = None) -> Dict[str, Any]:
        raise NotImplementedError

    #: True when apply() consumes a PRNG key in training mode (dropout).
    stochastic = False

    def apply_fwd(self, params: Dict[str, Any], x: Any, rng: Any = None,
                  train: bool = True) -> Tuple[Any, Any]:
        """(output, residual) — the fused-step forward contract.
        ``residual`` is whatever the matching GradientUnit's
        ``backward_from_saved`` needs; default (input, output)."""
        y = self.apply(params, {"input": x}, rng)["output"]
        return y, (x, y)

    @property
    def in_training(self) -> bool:
        """True while the current minibatch is a TRAIN one (dropout &co
        switch behaviour); resolved through the owning workflow's
        loader when present."""
        ld = getattr(self.workflow, "loader", None)
        if ld is None:
            return True
        from veles_tpu.loader.base import TRAIN
        return ld.minibatch_class == TRAIN

    # -- eager firing --------------------------------------------------

    def numpy_run(self) -> None:
        params = {k: np.asarray(v) for k, v in self.gather_params().items()}
        x = self.input.map_read()
        y, res = self.apply_fwd(params, x, rng=self.eager_rng(),
                                train=self.in_training)
        self._last_residual = res
        self.output.map_invalidate()[:] = np.asarray(y)

    def jax_run(self) -> None:
        params = self.gather_params()
        x = self.input.unmap()
        y, res = self.apply_fwd(params, x, rng=self.eager_rng(),
                                train=self.in_training)
        self._last_residual = res
        self.output.devmem = y

    def eager_rng(self) -> Any:
        """Per-firing randomness for eager modes; stochastic subclasses
        override (fused mode threads keys explicitly)."""
        return None


class GradientUnit(AcceleratedUnit):
    """Backward + SGD update for one ForwardUnit.

    Reference parity: veles/znicz/gd*.py — consumes ``err_output``
    (dL/d output), produces ``err_input`` (dL/d input), computes
    weight/bias gradients and applies the update in place.
    """

    def __init__(self, workflow=None, forward: Optional[ForwardUnit] = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.forward = forward
        self.err_output = Vector(name=f"{self.name}.err_output")
        self.err_input = Vector(name=f"{self.name}.err_input")
        self.learning_rate = kwargs.get("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.get("learning_rate_bias",
                                             kwargs.get("learning_rate", 0.01))
        self.weight_decay = kwargs.get("weight_decay", 0.0)
        self.weight_decay_bias = kwargs.get("weight_decay_bias", 0.0)
        self.gradient_moment = kwargs.get("gradient_moment", 0.0)
        #: momentum buffers, allocated lazily
        self.accumulated_grads: Dict[str, Vector] = {}
        self.declare_input("err_output", self.err_output)
        self.declare_output("err_input", self.err_input)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        f = self.forward
        if f is not None and not self.err_input:
            # raises AttributeError until the forward is initialized ->
            # Workflow.initialize retries us later.  Scratch: every
            # consumer rebinds/overwrites before reading (upload=False).
            self.err_input.mem = np.zeros(f.input.shape, np.float32)
            self.err_input.initialize(device, upload=False)
        if self.gradient_moment and f is not None:
            for pname, vec in f.param_vectors().items():
                if vec and pname not in self.accumulated_grads:
                    acc = Vector(name=f"{self.name}.vel_{pname}")
                    acc.initialize(device)
                    if device is not None and device.is_jax:
                        # zeros are born on the device (XLA generates
                        # them) — uploading host zeros the size of the
                        # params wastes tunnel bandwidth and wall clock
                        acc.devmem = device.zeros(vec.shape, np.float32)
                    else:
                        acc.mem = np.zeros(vec.shape, np.float32)
                    self.accumulated_grads[pname] = acc

    def reconcile_velocities(self) -> None:
        """Re-shape momentum buffers whose parameter changed shape
        (ResizableAll2All.resize): the overlapping region keeps its
        history, new entries start at zero.  No-op when shapes agree."""
        f = self.forward
        if f is None:
            return
        pvecs = f.param_vectors()
        for pname, vec in self.accumulated_grads.items():
            pvec = pvecs.get(pname)
            if pvec is None or tuple(vec.shape) == tuple(pvec.shape):
                continue
            old = np.asarray(vec.map_read())
            new = np.zeros(pvec.shape, np.float32)
            overlap = tuple(slice(0, min(a, b))
                            for a, b in zip(old.shape, new.shape))
            new[overlap] = old[overlap]
            vec.mem = new
            vec.initialize(self.device)

    # -- backward ------------------------------------------------------

    def act_deriv(self, output, err_output):
        """dL/d(pre-activation) from dL/d(output), using the forward's
        activation_mode contract (softmax: the evaluator already folded
        the jacobian into err_output — the softmax+CE fusion)."""
        mode = self.forward.activation_mode
        if mode in ("linear", "softmax"):
            return err_output
        if mode == "tanh":
            return err_output * (1.0 - output * output)
        if mode == "relu":
            return err_output * (output > 0).astype(output.dtype)
        if mode == "sigmoid":
            return err_output * output * (1.0 - output)
        raise ValueError(f"unknown activation_mode {mode!r}")

    #: True when backward_from_saved accepts need_err_input=False and
    #: can skip the err_input computation entirely — the fused step
    #: passes it for the FIRST gd in the chain, whose err_input nothing
    #: consumes (for conv1 the saving is outsized: a stride-s dgrad is
    #: an input-dilated transposed conv, the worst-mapped op on the
    #: MXU relative to its FLOPs).
    can_skip_err_input = False

    def backward_from_saved(self, params: Dict[str, Any],
                            saved: Tuple[Any, Any], err_output: Any) \
            -> Tuple[Any, Dict[str, Any]]:
        """(err_input, param_grads) from residuals ``saved = (input,
        output)`` of the forward pass.  Written against the shared
        numpy/jax array API so one implementation serves the numpy
        golden path, eager jax, and the fused whole-step trace."""
        raise NotImplementedError

    # -- update --------------------------------------------------------

    def update_params(self, params: Dict[str, Any],
                      grads: Dict[str, Any],
                      velocities: Dict[str, Any],
                      rates: Any = None,
                      decays: Any = None) -> Tuple[Dict[str, Any],
                                                   Dict[str, Any]]:
        """Pure xp-agnostic SGD(+momentum) update; returns (new_params,
        new_velocities).  ``rates=(lr_weights, lr_bias)`` overrides the
        unit's own rates — the fused step threads per-minibatch rates
        through the scan this way, so the trace never bakes a
        schedule-mutated ``self.learning_rate``.  ``decays=(wd_weights,
        wd_bias)`` overrides the unit's weight decay the same way — the
        population-batched GA engine threads PER-MEMBER decays through
        its vmapped trace (a python ``self.weight_decay`` would bake
        one genome's decay into every member's update)."""
        new_p, new_v = {}, {}
        lr_w, lr_b = rates if rates is not None else (
            self.learning_rate, self.learning_rate_bias)
        wd_w, wd_b = decays if decays is not None else (
            self.weight_decay, self.weight_decay_bias)
        for pname, w in params.items():
            g = grads[pname]
            lr = lr_w if pname == "weights" else lr_b
            wd = wd_w if pname == "weights" else wd_b
            g = g + wd * w
            if self.gradient_moment:
                v = velocities[pname]
                v = self.gradient_moment * v - lr * g
                new_v[pname] = v
                new_p[pname] = w + v
            else:
                new_p[pname] = w - lr * g
        return new_p, new_v

    # -- eager firing (numpy / per-unit jax graph mode) ---------------

    def run(self) -> None:
        f = self.forward
        numpy_mode = isinstance(self.device, NumpyDevice) or \
            self.device is None
        saved = getattr(f, "_last_residual", None)
        if numpy_mode:
            params = {k: np.asarray(v) for k, v in f.gather_params().items()}
            if saved is None:
                saved = (f.input.map_read(), f.output.map_read())
            err_out = self.err_output.map_read()
            vel = {k: v.map_read() for k, v in self.accumulated_grads.items()}
        else:
            params = f.gather_params()
            if saved is None:
                saved = (f.input.unmap(), f.output.unmap())
            err_out = self.err_output.unmap()
            vel = {k: v.unmap() for k, v in self.accumulated_grads.items()}
        err_in, grads = self.backward_from_saved(params, saved, err_out)
        new_p, new_v = self.update_params(params, grads, vel)
        if numpy_mode:
            for pname, arr in new_p.items():
                getattr(f, pname).map_invalidate()[:] = arr
            for pname, arr in new_v.items():
                self.accumulated_grads[pname].map_invalidate()[:] = arr
            if self.err_input:
                self.err_input.map_invalidate()[:] = err_in
        else:
            for pname, arr in new_p.items():
                getattr(f, pname).devmem = arr
            for pname, arr in new_v.items():
                self.accumulated_grads[pname].devmem = arr
            if self.err_input:
                self.err_input.devmem = err_in


class NNWorkflow(Workflow):
    """Workflow with the conventional NN roles bound by name
    (reference: veles/znicz/nn_units.py NNWorkflow)."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.loader = None
        self.forwards: list = []
        self.gds: list = []
        self.evaluator = None
        self.decision = None
        self.snapshotter = None

    def on_workflow_finished(self) -> None:
        super().on_workflow_finished()
        self.report_mfu()

    def report_mfu(self) -> None:
        """One honest throughput line after the timing table: analytic
        FLOPs (train images cost fwd+bwd, eval images fwd only) over the
        run's WALL-CLOCK time -> achieved FLOP/s and MFU against the
        chip's peak.  Wall clock is used deliberately: per-unit
        run_time measures only async dispatch on TPU (round-1 VERDICT
        weak #1), while the run loop's metric fetches block on the
        device, so wall time brackets the real compute.  The figure is
        therefore conservative (host overhead included); bench.py is
        the precise instrument."""
        fused = getattr(self, "fused", None)
        if fused is None or not fused.run_count or not self.forwards \
                or not self.wall_time:
            return
        train_im = getattr(fused, "processed_images", 0.0)
        eval_im = getattr(fused, "processed_eval_images", 0.0)
        if not train_im and not eval_im:
            return
        from veles_tpu import profiling
        flops = profiling.model_flops_per_sample(self.forwards)
        total = train_im * flops["train"] + eval_im * flops["forward"]
        rate = total / self.wall_time
        line = (f"wall-clock: {train_im:,.0f} train + {eval_im:,.0f} "
                f"eval images in {self.wall_time:.1f}s = "
                f"{rate / 1e12:.2f} TFLOP/s achieved "
                f"({flops['train'] / 1e9:.3f} train GFLOP/image)")
        jdev = getattr(self.device, "jax_device", None)
        u = (rate / profiling.device_peak_flops(jdev)
             if jdev is not None and profiling.device_peak_flops(jdev)
             else None)
        if u is not None:
            line += f" ({u * 100:.1f}% MFU)"
        self.info("%s", line)
