"""Learning-rate schedules.

Reference parity: veles/znicz/lr_adjust.py — policies (fixed, step,
exponential, inverse) applied to the GD units' learning rates as
training progresses (BASELINE config #3 "CIFAR-10 ... LR policy").

TPU adaptation: in fused mode the schedule flows through the runner's
``lr_rates`` — one (n_gd, 2) row of ABSOLUTE (weights, bias) rates per
minibatch of the superstep, threaded through the scan as a traced
argument — so per-iteration policies stay exact inside a superstep and
no retrace ever happens.  In eager mode the unit also writes the rates
into the GD units directly, like the reference.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

from veles_tpu.units import Unit

PolicyFn = Callable[[float, int], float]  # (base_lr, t) -> lr

_policies: Dict[str, Callable[..., PolicyFn]] = {}


def policy(name: str):
    def deco(fn):
        _policies[name] = fn
        return fn
    return deco


@policy("fixed")
def fixed_policy() -> PolicyFn:
    return lambda base, t: base


@policy("step")
def step_policy(gamma: float = 0.1, step: int = 10) -> PolicyFn:
    return lambda base, t: base * gamma ** (t // step)


@policy("exp")
def exp_policy(gamma: float = 0.95) -> PolicyFn:
    return lambda base, t: base * gamma ** t


@policy("inv")
def inv_policy(gamma: float = 1e-4, power: float = 0.75) -> PolicyFn:
    return lambda base, t: base * (1.0 + gamma * t) ** (-power)


@policy("arbitrary")
def arbitrary_policy(points: List = ()) -> PolicyFn:
    """Piecewise-constant: points = [(t_from, lr), ...] sorted."""
    pts = sorted(points)

    def fn(base, t):
        lr = base
        for t0, v in pts:
            if t >= t0:
                lr = v
        return lr
    return fn


def make_policy(name: str, **kwargs: Any) -> PolicyFn:
    if name not in _policies:
        raise ValueError(f"unknown lr policy {name!r}; "
                         f"have {sorted(_policies)}")
    return _policies[name](**kwargs)


class LearningRateAdjust(Unit):
    """Applies a schedule to all (or selected) GD units each epoch or
    iteration.  Sits between the loader and the compute step."""

    def __init__(self, workflow=None, policy_name: str = "fixed",
                 policy_kwargs: Optional[dict] = None,
                 by: str = "epoch", **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.policy = make_policy(policy_name, **(policy_kwargs or {}))
        self.by = by
        self.loader = None
        self.gds: list = []
        self.fused = None
        self._iteration = 0
        self._base_rates: Optional[list] = None

    def run(self) -> None:
        from veles_tpu.loader.base import TRAIN
        if self._base_rates is None:
            self._base_rates = [(gd.learning_rate, gd.learning_rate_bias)
                                for gd in self.gds]
        if self.loader is not None and \
                self.loader.minibatch_class != TRAIN:
            return
        # The loader may have grouped k same-class minibatches into one
        # superstep firing; a per-iteration schedule must advance once
        # per MINIBATCH, so emit one (n_gd, 2) absolute-rate row per
        # minibatch — the fused scan threads them as a scanned input
        # (eager mode always has k=1).
        k = int(getattr(self.loader, "superstep_k", 1) or 1)
        # by="epoch": the loader already advanced through the group, so
        # when it crossed the epoch boundary (always on the group's
        # LAST train minibatch — train is the last class) epoch_number
        # is the NEW epoch; the first k-1 minibatches belong to the old
        # one.  Reconstructing this keeps fused identical to eager,
        # where only the last firing of an epoch sees the incremented
        # number.
        e = self.loader.epoch_number
        ended = bool(self.loader.epoch_ended)

        def t_of(j: int) -> int:
            if self.by == "epoch":
                return e - 1 if (ended and j < k - 1) else e
            return self._iteration + j

        rows = [[[self.policy(base_w, t_of(j)),
                  self.policy(base_b, t_of(j))]
                 for (base_w, base_b) in self._base_rates]
                for j in range(k)]
        self._iteration += k
        # eager GD units (and snapshots) see the absolute rates of the
        # LAST minibatch in the group
        for gd, row in zip(self.gds, rows[-1]):
            gd.learning_rate, gd.learning_rate_bias = row
        if self.fused is not None:
            self.fused.lr_rates = rows
