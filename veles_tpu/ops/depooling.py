"""Depooling: the decoder-side inverse of pooling.

Reference parity: veles/znicz/depooling.py — upsamples by spreading
each input value uniformly over its (ky, kx) window (the adjoint of
AvgPooling scaled by the window size), used by MnistAE's decoder.
Param-less, xp-agnostic.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.conv import _pair
from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


class Depooling(ForwardUnit):
    has_params = False

    def __init__(self, workflow=None, kx: int = 2, ky: int = 2,
                 sliding: Any = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = kx, ky
        self.sliding = _pair(sliding) if sliding is not None else (ky, kx)
        if tuple(self.sliding) != (self.ky, self.kx):
            raise ValueError("Depooling supports non-overlapping "
                             "windows only (sliding == kernel)")

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        return (b, h * self.ky, w * self.kx, c)

    def param_shapes(self, input_shape):
        return {}

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        if isinstance(x, np.ndarray):
            y = np.repeat(np.repeat(x, self.ky, axis=1), self.kx, axis=2)
        else:
            import jax.numpy as jnp
            y = jnp.repeat(jnp.repeat(x, self.ky, axis=1),
                           self.kx, axis=2)
        return {"output": y}


class GDDepooling(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, _y = saved
        b, h, w, c = x.shape
        e = err_output.reshape(b, h, f.ky, w, f.kx, c)
        return e.sum(axis=(2, 4)), {}
