"""StandardWorkflow: build a full training workflow from a declarative
``layers`` config.

Reference parity: veles/znicz/standard_workflow.py — the API all five
BASELINE.json configs go through: a list of layer dicts
``{"type": "conv", "->": {forward params}, "<-": {gd params}}`` becomes
loader -> forwards -> evaluator -> gd chain (reversed) -> decision ->
loop, plus snapshotter and plotters (SURVEY.md §4.5).

TPU-first: on a jax device the forwards/evaluator/gds are NOT linked
into the control graph — a single FusedStepRunner node executes the
whole iteration as one jitted call (ops/fused.py).  On the numpy
backend the classic unit-by-unit graph runs, serving as the golden
path.  The same StandardWorkflow instance can be re-wired for either
mode at initialize() time (snapshot on TPU, resume on numpy, etc.).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from veles_tpu.backends import Device
from veles_tpu.loader.base import TRAIN, Loader
from veles_tpu.mutable import Bool
from veles_tpu.ops.decision import DecisionGD
from veles_tpu.ops.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.ops.fused import FusedStepRunner
from veles_tpu.ops.nn_units import NNWorkflow
from veles_tpu.ops.registry import forward_registry
from veles_tpu.workflow import Repeater


class StandardWorkflow(NNWorkflow):
    def __init__(self, workflow=None,
                 loader: Optional[Loader] = None,
                 loader_factory: Optional[Callable[..., Loader]] = None,
                 layers: Optional[List[Dict[str, Any]]] = None,
                 loss_function: str = "softmax",
                 decision_config: Optional[Dict[str, Any]] = None,
                 snapshotter_config: Optional[Dict[str, Any]] = None,
                 lr_adjust_config: Optional[Dict[str, Any]] = None,
                 superstep: int = 8,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.loss_function = loss_function
        self.layers_config = layers or []
        #: fused mode runs up to this many same-class minibatches per
        #: device dispatch (lax.scan) — amortizes dispatch latency
        self.superstep = max(1, superstep)

        self.repeater = Repeater(self, name="repeater")
        if loader is None:
            if loader_factory is None:
                raise ValueError("need loader or loader_factory")
            loader = loader_factory(self)
        elif loader.workflow is not self:
            self.add_unit(loader)
        self.loader = loader

        self._create_forwards()
        self._create_evaluator()
        self._create_gds()
        self._create_decision(decision_config or {})
        self._create_snapshotter(snapshotter_config)
        self.fused = FusedStepRunner(
            self, loader=self.loader, forwards=self.forwards,
            evaluator=self.evaluator, gds=self.gds, name="fused_step")
        self.lr_adjust = None
        if lr_adjust_config:
            from veles_tpu.ops.lr_adjust import LearningRateAdjust
            self.lr_adjust = LearningRateAdjust(
                self, name="lr_adjust", **lr_adjust_config)
            self.lr_adjust.loader = self.loader
            self.lr_adjust.gds = self.gds
            self.lr_adjust.fused = self.fused
        self._extra_after_decision: list = []
        self.plotters: list = []

    # -- plotters ------------------------------------------------------

    def link_plotters(self) -> None:
        """Attach the reference's standard plotters (error curves,
        confusion matrix, first-layer weight images); they fire once per
        epoch after Decision and render through the graphics bus
        (reference: StandardWorkflow.link_plotters)."""
        if self.plotters:
            return
        from veles_tpu.plotting_units import (AccumulatingPlotter,
                                              MatrixPlotter, Weights2D)
        ps = [AccumulatingPlotter(self, name="plt_error"),
              AccumulatingPlotter(self, field="loss", name="plt_loss")]
        # the confusion Vector is allocated at initialize(), after this
        # runs — gate on the evaluator's intent, not the buffer
        if getattr(self.evaluator, "compute_confusion", False):
            ps.append(MatrixPlotter(self, evaluator=self.evaluator,
                                    name="plt_confusion"))
        ps.append(Weights2D(self, unit=self.forwards[0],
                            name="plt_weights"))
        for p in ps:
            p.link_decision(self.decision)
        self._extra_after_decision.extend(ps)
        self.plotters = ps

    def link_status_reporter(self, url: str,
                             mode: str = "standalone") -> None:
        """Attach a per-epoch POST to a web-status dashboard
        (reference: veles/web_status.py client side)."""
        from veles_tpu.web_status import StatusReporter
        if any(type(u) is StatusReporter
               for u in self._extra_after_decision):
            return  # snapshot resume: the pickled reporter stays
        rep = StatusReporter(self, url=url, mode=mode,
                             name="status_reporter")
        rep.link_decision(self.decision)
        self._extra_after_decision.append(rep)

    # -- unit creation -------------------------------------------------

    def _create_forwards(self) -> None:
        self.forwards = []
        prev = None
        for i, cfg in enumerate(self.layers_config):
            kind = cfg["type"]
            if kind not in forward_registry:
                raise ValueError(f"unknown layer type {kind!r}; have "
                                 f"{sorted(forward_registry)}")
            fwd_cls, _ = forward_registry[kind]
            fwd_kwargs = dict(cfg.get("->", {}))
            unit = fwd_cls(self, name=f"fwd{i}_{kind}", **fwd_kwargs)
            if prev is None:
                unit.link_attrs(self.loader, ("input", "minibatch_data"))
            else:
                unit.link_attrs(prev, ("input", "output"))
            self.forwards.append(unit)
            prev = unit

    def _create_evaluator(self) -> None:
        last = self.forwards[-1]
        if self.loss_function == "softmax":
            ev = EvaluatorSoftmax(self, name="evaluator")
            ev.link_attrs(last, ("input", "output"))
            ev.link_attrs(self.loader, ("labels", "minibatch_labels"),
                          ("mask", "minibatch_mask"))
        elif self.loss_function == "mse":
            ev = EvaluatorMSE(self, name="evaluator")
            ev.link_attrs(last, ("input", "output"))
            ev.link_attrs(self.loader, ("target", "minibatch_targets"),
                          ("mask", "minibatch_mask"))
        else:
            raise ValueError(f"unknown loss {self.loss_function!r}")
        self.evaluator = ev

    def _create_gds(self) -> None:
        self.gds = []
        loader = self.loader
        for i, (cfg, fwd) in enumerate(zip(self.layers_config,
                                           self.forwards)):
            kind = cfg["type"]
            _, gd_cls = forward_registry[kind]
            gd_kwargs = dict(cfg.get("<-", {}))
            gd = gd_cls(self, forward=fwd, name=f"gd{i}_{kind}",
                        **gd_kwargs)
            self.gds.append(gd)

    def _create_decision(self, cfg: Dict[str, Any]) -> None:
        self.decision = DecisionGD(self, name="decision", **cfg)
        self.decision.loader = self.loader
        self.decision.evaluator = self.evaluator

    def _create_snapshotter(self, cfg: Optional[Dict[str, Any]]) -> None:
        self.snapshotter = None
        if cfg is None:
            return
        from veles_tpu.snapshotter import Snapshotter
        self.snapshotter = Snapshotter(self, name="snapshotter", **cfg)
        self.snapshotter.decision = self.decision

    # -- wiring --------------------------------------------------------

    def _clear_control_links(self) -> None:
        for u in self.units:
            u.links_from.clear()
            u.links_to.clear()
        # Derived Bool gates hold closures, which pickling flattens to
        # their momentary values (mutable.Bool.__getstate__) — every
        # expression gate must therefore be re-established at wiring
        # time, or a resumed run trains on validation minibatches.
        loader = self.loader
        for gd in self.gds:
            gd.gate_skip = Bool.from_expr(
                lambda ld=loader: ld.minibatch_class != TRAIN)
        # plotters / status reporters carry the same pickled-frozen-gate
        # hazard — re-derive their gates from the live decision too
        for extra in self._extra_after_decision:
            if hasattr(extra, "link_decision"):
                extra.link_decision(self.decision)

    def _wire_common_tail(self, before_decision) -> None:
        self.decision.link_from(before_decision)
        tail = self.decision
        if self.snapshotter is not None:
            # fire on the validation-improved firing (weights at that
            # moment are the end-of-previous-train-epoch weights the
            # improvement was measured with; reference: Decision
            # triggers Snapshotter on improvement)
            self.snapshotter.link_from(self.decision)
            self.snapshotter.gate_skip = Bool.from_expr(
                lambda d=self.decision: not bool(d.improved))
            tail = self.snapshotter
        for extra in self._extra_after_decision:
            extra.link_from(tail)
            tail = extra
        self.repeater.link_from(tail)          # loop back edge
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(tail)
        self.end_point.gate_block = ~self.decision.complete

    def wire_eager(self) -> None:
        """Classic per-unit graph (numpy golden path)."""
        self._clear_control_links()
        self.loader.host_fill_enabled = True
        self.loader.superstep = 1
        self.decision.metrics_source = None
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        prev = self.loader
        if self.lr_adjust is not None:
            self.lr_adjust.link_from(prev)
            prev = self.lr_adjust
        for f in self.forwards:
            f.link_from(prev)
            prev = f
        self.evaluator.link_from(prev)
        # backward chain, reversed; err chains via link_attrs
        prev = self.evaluator
        last_gd = None
        for i in range(len(self.gds) - 1, -1, -1):
            gd = self.gds[i]
            if last_gd is None:
                gd.link_attrs(self.evaluator, "err_output")
            else:
                gd.link_attrs(last_gd, ("err_output", "err_input"))
            gd.link_from(prev)
            prev = gd
            last_gd = gd
        self._wire_common_tail(prev)

    def wire_fused(self) -> None:
        """Single fused jitted scan per iteration (TPU path)."""
        self._clear_control_links()
        self.loader.host_fill_enabled = False
        self.loader.superstep = self.superstep
        self.decision.metrics_source = self.fused
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        prev = self.loader
        if self.lr_adjust is not None:
            self.lr_adjust.link_from(prev)
            prev = self.lr_adjust
        self.fused.link_from(prev)
        self._wire_common_tail(self.fused)

    # -- lifecycle -----------------------------------------------------

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        # attrs introduced after a snapshot was written must default,
        # or pre-existing snapshots become unresumable
        self.__dict__.setdefault("_extra_after_decision", [])
        self.__dict__.setdefault("plotters", [])

    def initialize(self, device: Optional[Device] = None, **kwargs) -> None:
        use_fused = device is not None and device.is_jax \
            and kwargs.pop("fused", True)
        if use_fused:
            self.wire_fused()
        else:
            self.wire_eager()
        super().initialize(device=device, **kwargs)
