"""znicz-equivalent neural-network op layer (reference: veles/znicz/).

Each op family is a ForwardUnit plus a matching GradientUnit; compute is
a pure ``apply`` function traceable by XLA (TPU path) with a hand-written
numpy twin (golden path).  The production training path fuses every
unit's apply into one jitted step — see veles_tpu/ops/fused.py.
"""

from veles_tpu.ops.nn_units import (  # noqa: F401
    ForwardUnit, GradientUnit, NNWorkflow,
)
from veles_tpu.ops import all2all, evaluator, decision  # noqa: F401
from veles_tpu.ops.registry import forward_registry, gd_for  # noqa: F401
