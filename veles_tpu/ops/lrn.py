"""Local response normalization (across channels).

Reference parity: veles/znicz/normalization.py — AlexNet's LRN:
``y_i = x_i / (k + alpha * sum_{j in window(i)} x_j^2) ^ beta`` with a
channel window of size n centered on i, plus its analytic backward.

TPU-first implementation notes (this op was HALF the AlexNet step time
when written naively — docs/perf.md):

- ``den^-0.75`` as a pow lowers to exp(log(x)) — two VPU
  transcendentals per element over the largest activations in the
  net, forward AND backward.  For the standard beta=3/4 it is instead
  computed as ``r*sqrt(r)`` with ``r = rsqrt(den)`` (hardware rsqrt +
  one sqrt); the backward's ``den^-1.75`` is ``d34 * r * r`` — zero
  transcendentals anywhere on the hot path.
- the windowed channel sum is a BANDED MATMUL on the jax path:
  ``sum_window(v) = v @ B`` with ``B[c, d] = 1 iff |c - d| <= n//2``
  (a C x C constant).  The extra FLOPs are negligible (2*C^2 per
  pixel, <1% of the conv FLOPs around it) and they run on the MXU,
  while the elementwise alternative (pad + n shifted adds) cost ~n
  materialized passes over the largest activations in the net.  The
  numpy oracle keeps the explicit shifted-adds form — an independent
  implementation the tests compare against.
- residual policy: by default the forward saves ``den`` so the
  backward skips the windowed reduction; the opt-in variants (pallas
  kernels via VELES_TPU_LRN_PALLAS, x-only residual via
  VELES_TPU_LRN_RECOMPUTE) save just ``x`` and re-derive ``den`` in
  the backward.  Measured on a v5e the two policies tie — fwd and bwd
  share one scan body, so XLA schedules the residual freely either
  way (docs/perf.md).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _xp(x):
    if isinstance(x, np.ndarray):
        return np
    import jax.numpy as jnp
    return jnp


def band_matrix(c: int, n: int, transpose: bool = False) -> np.ndarray:
    """The n-tap window as a C x C 0/1 matrix:
    ``(v @ band)[d] = sum_{j=-half}^{n-1-half} v[d+j]`` — eye-offset
    ``off`` contributes v[d-off], hence the negated range.  EXACTLY n
    taps for both parities of n (a symmetric -half..+half band would
    sum n+1 taps for even n).  ``transpose=True`` gives the adjoint
    window (taps j in [-(n-1-half), half]) — equal to the forward
    window only for ODD n; the backward pass needs the adjoint.
    Single source of truth for lrn.py and lrn_pallas.py."""
    half = n // 2
    band = np.zeros((c, c), np.float32)
    for off in range(half - n + 1, half + 1):
        band += np.eye(c, c, off, dtype=np.float32)
    return np.ascontiguousarray(band.T) if transpose else band


def _window_sum(xp, v, n: int, transpose: bool = False):
    """Sum of v over the n-wide channel window (same shape).
    jax: one banded matmul over the channel axis (MXU); numpy: explicit
    shifted adds (the independent oracle).  ``transpose`` selects the
    adjoint window — required in the backward pass; for even n the two
    differ (the window is centered only for odd n)."""
    half = n // 2
    c = v.shape[-1]
    if xp is not np:
        band = band_matrix(c, n, transpose)
        return v @ xp.asarray(band, dtype=v.dtype)
    # taps j in [-half, n-1-half] (forward) or the negated set
    # (adjoint): left-pad by -min_tap, right-pad by max_tap
    lo = (n - 1 - half) if transpose else half
    pad = [(0, 0)] * (v.ndim - 1) + [(lo, n - 1 - lo)]
    vp = np.pad(v, pad)
    out = vp[..., 0:c]
    for i in range(1, n):
        out = out + vp[..., i:i + c]
    return out


def _neg_beta_pow(xp, den, beta: float):
    """den**(-beta) without transcendentals for the quarter-multiples
    every real config uses (0.75 is AlexNet's; 0.5/1.0 appear in
    variants).  Falls back to pow otherwise."""
    if beta == 0.75:
        r = den ** -0.5 if xp is np else _rsqrt(xp, den)
        return r * xp.sqrt(r), r
    if beta == 0.5:
        r = den ** -0.5 if xp is np else _rsqrt(xp, den)
        return r, r
    if beta == 1.0:
        inv = 1.0 / den
        return inv, None
    return den ** (-beta), None


def _rsqrt(xp, v):
    from jax import lax
    return lax.rsqrt(v)


class LRNormalizer(ForwardUnit):
    has_params = False

    def __init__(self, workflow=None, alpha: float = 1e-4,
                 beta: float = 0.75, n: int = 5, k: float = 2.0,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.alpha, self.beta, self.n, self.k = alpha, beta, n, k

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def param_shapes(self, input_shape):
        return {}

    def _den(self, xp, x):
        return self.k + self.alpha * _window_sum(xp, x * x, self.n)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        xp = _xp(x)
        d, _ = _neg_beta_pow(xp, self._den(xp, x), self.beta)
        return {"output": x * d}

    def _use_pallas(self, x) -> bool:
        """Whether the hand kernels (ops/lrn_pallas.py) take the hot
        fused path.  OPT-IN via VELES_TPU_LRN_PALLAS=1: measured on a
        v5e chip with a data-fetch barrier, XLA's banded-matmul form
        BEATS the hand kernels at AlexNet's shapes (docs/perf.md
        records the shootout), so the default stays XLA.  The kernels
        remain for other shapes/platforms and as tuning
        infrastructure.  Further requirements: a real TPU (not the
        XLA:CPU test platform), no sharded mesh (XLA partitions
        poorly around custom calls — ``force_xla`` is set by the
        fused runner), beta=3/4, and a tileable shape."""
        if not os.environ.get("VELES_TPU_LRN_PALLAS"):
            return False
        if getattr(self, "force_xla", False):
            return False
        dev = getattr(self, "device", None)
        if dev is None or not getattr(dev, "is_jax", False) or \
                getattr(dev, "platform", "cpu") == "cpu":
            return False
        if getattr(dev, "mesh", None) is not None:
            # a MeshJaxDevice reaches here on the eager path too, where
            # the fused runner's force_xla loop never runs
            return False
        from veles_tpu.ops import lrn_pallas
        return lrn_pallas.available() and \
            lrn_pallas.usable(x.shape, self.n, self.beta)

    def apply_fwd(self, params, x, rng=None, train=True):
        """Residual policy: pallas path and the recompute variant save
        only ``x`` — the backward re-derives ``den`` (a cheap banded
        MXU matmul) instead of storing/loading an f32 array the size
        of the largest activations in the net.  Default XLA path
        carries ``den``; VELES_TPU_LRN_RECOMPUTE=1 switches (both
        measured in docs/perf.md — fwd+bwd live in ONE scan body, so
        XLA schedules the residual freely either way)."""
        xp = _xp(x)
        if xp is not np and self._use_pallas(x):
            from veles_tpu.ops import lrn_pallas
            return lrn_pallas.lrn_fwd(x, self.n, self.k,
                                      self.alpha), (x, None)
        den = self._den(xp, x)
        d, _ = _neg_beta_pow(xp, den, self.beta)
        if xp is not np and os.environ.get("VELES_TPU_LRN_RECOMPUTE"):
            return x * d, (x, None)
        return x * d, (x, den)


class GDLRNormalizer(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, den = saved
        xp = _xp(err_output)
        if den is None:  # x-only residual: recompute den here
            if f._use_pallas(x):
                from veles_tpu.ops import lrn_pallas
                return lrn_pallas.lrn_bwd(x, err_output, f.n, f.k,
                                          f.alpha), {}
            den = f._den(xp, x)
        d_nb, r = _neg_beta_pow(xp, den, f.beta)      # den^-beta
        if f.beta == 0.75 and r is not None:
            d_nb1 = d_nb * (r * r)                    # den^-(beta+1)
        elif f.beta == 0.5 and r is not None:
            d_nb1 = d_nb * (r * r)
        elif f.beta == 1.0:
            d_nb1 = d_nb * d_nb
        else:
            d_nb1 = den ** (-f.beta - 1.0)
        t = err_output * x * d_nb1
        # the backward needs the ADJOINT window (transpose=True): it
        # equals the forward window for odd n, but differs for even n
        # (fd-checked in tests/test_ops.py — an earlier "the window is
        # symmetric" shortcut was wrong for even n)
        err_input = (err_output * d_nb
                     - 2.0 * f.alpha * f.beta * x
                     * _window_sum(xp, t, f.n, transpose=True))
        return err_input, {}
