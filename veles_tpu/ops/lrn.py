"""Local response normalization (across channels).

Reference parity: veles/znicz/normalization.py — AlexNet's LRN:
``y_i = x_i / (k + alpha * sum_{j in window(i)} x_j^2) ^ beta`` with a
channel window of size n centered on i, plus its analytic backward.

TPU-first implementation notes (this op was HALF the AlexNet step time
when written naively — docs/perf.md):

- ``den^-0.75`` as a pow lowers to exp(log(x)) — two VPU
  transcendentals per element over the largest activations in the
  net, forward AND backward.  For the standard beta=3/4 it is instead
  computed as ``r*sqrt(r)`` with ``r = rsqrt(den)`` (hardware rsqrt +
  one sqrt); the backward's ``den^-1.75`` is ``d34 * r * r`` — zero
  transcendentals anywhere on the hot path.
- the windowed channel sum is a BANDED MATMUL on the jax path:
  ``sum_window(v) = v @ B`` with ``B[c, d] = 1 iff |c - d| <= n//2``
  (a C x C constant).  The extra FLOPs are negligible (2*C^2 per
  pixel, <1% of the conv FLOPs around it) and they run on the MXU,
  while the elementwise alternative (pad + n shifted adds) cost ~n
  materialized passes over the largest activations in the net.  The
  numpy oracle keeps the explicit shifted-adds form — an independent
  implementation the tests compare against.
- the forward saves ``den`` as its residual, so the backward does not
  recompute the windowed reduction at all.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _xp(x):
    if isinstance(x, np.ndarray):
        return np
    import jax.numpy as jnp
    return jnp


def _window_sum(xp, v, n: int):
    """Sum of v over a centered channel window of size n (same shape).
    jax: one banded matmul over the channel axis (MXU); numpy: explicit
    shifted adds (the independent oracle)."""
    half = n // 2
    c = v.shape[-1]
    if xp is not np:
        # (v @ band)[d] = sum_off v[d - off] for eye-offsets ``off``;
        # matching the numpy oracle's window sum_{j=-half}^{n-1-half}
        # v[d + j] needs off = -j — exactly n taps, both parities of n
        # (a symmetric -half..+half band would sum n+1 taps for even n)
        band = np.zeros((c, c), np.float32)
        for off in range(half - n + 1, half + 1):
            band += np.eye(c, c, off, dtype=np.float32)
        return v @ xp.asarray(band, dtype=v.dtype)
    pad = [(0, 0)] * (v.ndim - 1) + [(half, half)]
    vp = np.pad(v, pad)
    out = vp[..., 0:c]
    for i in range(1, n):
        out = out + vp[..., i:i + c]
    return out


def _neg_beta_pow(xp, den, beta: float):
    """den**(-beta) without transcendentals for the quarter-multiples
    every real config uses (0.75 is AlexNet's; 0.5/1.0 appear in
    variants).  Falls back to pow otherwise."""
    if beta == 0.75:
        r = den ** -0.5 if xp is np else _rsqrt(xp, den)
        return r * xp.sqrt(r), r
    if beta == 0.5:
        r = den ** -0.5 if xp is np else _rsqrt(xp, den)
        return r, r
    if beta == 1.0:
        inv = 1.0 / den
        return inv, None
    return den ** (-beta), None


def _rsqrt(xp, v):
    from jax import lax
    return lax.rsqrt(v)


class LRNormalizer(ForwardUnit):
    has_params = False

    def __init__(self, workflow=None, alpha: float = 1e-4,
                 beta: float = 0.75, n: int = 5, k: float = 2.0,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.alpha, self.beta, self.n, self.k = alpha, beta, n, k

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def param_shapes(self, input_shape):
        return {}

    def _den(self, xp, x):
        return self.k + self.alpha * _window_sum(xp, x * x, self.n)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        xp = _xp(x)
        d, _ = _neg_beta_pow(xp, self._den(xp, x), self.beta)
        return {"output": x * d}

    def apply_fwd(self, params, x, rng=None, train=True):
        """Residual carries ``den`` so the backward never recomputes
        the windowed reduction."""
        xp = _xp(x)
        den = self._den(xp, x)
        d, _ = _neg_beta_pow(xp, den, self.beta)
        return x * d, (x, den)


class GDLRNormalizer(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, den = saved
        xp = _xp(err_output)
        d_nb, r = _neg_beta_pow(xp, den, f.beta)      # den^-beta
        if f.beta == 0.75 and r is not None:
            d_nb1 = d_nb * (r * r)                    # den^-(beta+1)
        elif f.beta == 0.5 and r is not None:
            d_nb1 = d_nb * (r * r)
        elif f.beta == 1.0:
            d_nb1 = d_nb * d_nb
        else:
            d_nb1 = den ** (-f.beta - 1.0)
        t = err_output * x * d_nb1
        # the window is symmetric, so the transpose windowed sum is the
        # same windowed sum
        err_input = (err_output * d_nb
                     - 2.0 * f.alpha * f.beta * x
                     * _window_sum(xp, t, f.n))
        return err_input, {}
