"""Local response normalization (across channels).

Reference parity: veles/znicz/normalization.py — AlexNet's LRN:
``y_i = x_i / (k + alpha * sum_{j in window(i)} x_j^2) ^ beta`` with a
channel window of size n centered on i, plus its analytic backward.

Implemented once against the shared numpy/jax array API via a padded
cumulative-sum windowed reduction — no backend-specific code; both the
golden path and the fused trace run the same lines.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _xp(x):
    if isinstance(x, np.ndarray):
        return np
    import jax.numpy as jnp
    return jnp


def _window_sum(xp, v, n: int):
    """Sum of v over a centered channel window of size n (same shape).
    v: (..., C)."""
    half = n // 2
    pad = [(0, 0)] * (v.ndim - 1) + [(half + 1, half)]
    cs = xp.cumsum(xp.pad(v, pad), axis=-1)
    c = v.shape[-1]
    # windowed sum over [i-half, i+half]: cs[i+n] - cs[i]
    return cs[..., n:n + c] - cs[..., 0:c]


class LRNormalizer(ForwardUnit):
    has_params = False

    def __init__(self, workflow=None, alpha: float = 1e-4,
                 beta: float = 0.75, n: int = 5, k: float = 2.0,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.alpha, self.beta, self.n, self.k = alpha, beta, n, k

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def param_shapes(self, input_shape):
        return {}

    def _den(self, xp, x):
        return self.k + self.alpha * _window_sum(xp, x * x, self.n)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        xp = _xp(x)
        return {"output": x * self._den(xp, x) ** (-self.beta)}


class GDLRNormalizer(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, _y = saved
        xp = _xp(err_output)
        den = f._den(xp, x)
        t = err_output * x * den ** (-f.beta - 1.0)
        # the window is symmetric, so the transpose windowed sum is the
        # same windowed sum
        err_input = (err_output * den ** (-f.beta)
                     - 2.0 * f.alpha * f.beta * x
                     * _window_sum(xp, t, f.n))
        return err_input, {}
