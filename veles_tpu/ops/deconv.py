"""Deconvolution (transposed convolution) for autoencoders.

Reference parity: veles/znicz/deconv.py + gd_deconv.py — the decoder
halves of MnistAE.  A Deconv with kernel/stride/padding S inverts the
geometry of a Conv with the same S: output H = (OH-1)*stride + k - 2*pad.

TPU path: ``lax.conv_transpose``; numpy golden path reuses col2im (a
transposed conv IS col2im of per-position weighted patches).  Backward
of a transposed conv is a plain conv — derived via jax.vjp on the TPU
path, explicit im2col on the numpy path.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.conv import _pair, im2col, col2im
from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


class Deconv(ForwardUnit):
    """Transposed 2-D convolution, NHWC; weights HWOI-style
    (ky, kx, n_output_channels, n_input_channels) so a Conv's weight
    shape transposes naturally."""

    activation_mode = "linear"

    def __init__(self, workflow=None, n_kernels: int = None,  # type: ignore
                 kx: int = 3, ky: int = 3, padding: Any = 0,
                 sliding: Any = 1, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError(f"{self.name}: n_kernels required "
                             "(number of OUTPUT channels)")
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.padding = _pair(padding)
        self.sliding = _pair(sliding)

    def output_shape_for(self, input_shape):
        b, h, w, _c = input_shape
        py, px = self.padding
        sy, sx = self.sliding
        return (b, (h - 1) * sy + self.ky - 2 * py,
                (w - 1) * sx + self.kx - 2 * px, self.n_kernels)

    def param_shapes(self, input_shape):
        c_in = input_shape[-1]
        shapes = {"weights": (self.ky, self.kx, self.n_kernels, c_in)}
        if self.include_bias:
            shapes["bias"] = (self.n_kernels,)
        return shapes

    def weight_fan_in(self, shape):
        ky, kx, _n_out, c_in = shape
        return ky * kx * c_in

    def pre_activation(self, params, x):
        if isinstance(x, np.ndarray):
            b, h, w, c_in = x.shape
            wmat = params["weights"].reshape(
                self.ky * self.kx * self.n_kernels, c_in)
            cols = (x.reshape(-1, c_in) @ wmat.T).reshape(
                b, h, w, self.ky, self.kx, self.n_kernels)
            out_shape = self.output_shape_for(x.shape)
            v = col2im(cols, out_shape, self.padding, self.sliding)
        else:
            import jax.numpy as jnp
            from jax import lax
            py, px = self.padding
            sy, sx = self.sliding
            # transposed conv == input-dilated conv with the spatially
            # flipped, io-transposed kernel (textbook adjoint form)
            k2 = jnp.flip(params["weights"], (0, 1)).transpose(0, 1, 3, 2)
            v = lax.conv_general_dilated(
                x, k2, window_strides=(1, 1),
                padding=((self.ky - 1 - py, self.ky - 1 - py),
                         (self.kx - 1 - px, self.kx - 1 - px)),
                lhs_dilation=(sy, sx),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if "bias" in params:
            v = v + params["bias"]
        return v

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        return {"output": self.pre_activation(params, inputs["input"])}


class GradientDescentDeconv(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        f = self.forward
        x, out = saved
        err_pre = self.act_deriv(out, err_output)
        if isinstance(err_output, np.ndarray):
            # backward of col2im is im2col: gather patches of err_pre
            patches = im2col(err_pre, f.ky, f.kx, f.padding, f.sliding)
            b, oh, ow = patches.shape[:3]  # == x's spatial dims
            pf = patches.reshape(b * oh * ow, -1)   # (N, ky*kx*K)
            xf = x.reshape(b * oh * ow, -1)         # (N, C_in)
            grads = {"weights": (pf.T @ xf).reshape(
                f.ky, f.kx, f.n_kernels, x.shape[-1])}
            if "bias" in params:
                grads["bias"] = err_pre.sum(axis=(0, 1, 2))
            err_input = (pf @ params["weights"]
                         .reshape(-1, x.shape[-1])).reshape(x.shape)
            return err_input, grads
        import jax

        def pre(p, xx):
            return f.pre_activation(p, xx)

        _, vjp = jax.vjp(pre, params, x)
        grads, err_input = vjp(err_pre)
        return err_input, grads
