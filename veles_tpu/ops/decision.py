"""Decision: end-of-epoch control logic.

Reference parity: veles/znicz/decision.py — ``DecisionGD`` accumulates
per-class epoch metrics from the evaluator, tracks the best validation
error, raises ``improved`` (snapshot trigger), and decides when training
is ``complete`` (max epochs reached, or no validation improvement for
``fail_iterations`` epochs).  Its ``complete`` Bool gates the training
loop's back edge.  Distributable in the reference (aggregates slave
metrics); in SPMD mode metrics already arrive globally reduced.

TPU-first: per-minibatch metric reads would force a device sync each
step, so metric handles are accumulated lazily (device futures) and
summed once per class end — JAX async dispatch keeps the device busy.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from veles_tpu.distributable import Distributable
from veles_tpu.loader.base import CLASS_NAMES, TEST, TRAIN, VALID
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit


class DecisionGD(Unit, Distributable):
    def __init__(self, workflow=None,
                 max_epochs: Optional[int] = None,
                 fail_iterations: int = 100,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.epoch_ended_flag = Bool(False)
        # wired by link_attrs from the loader:
        #   minibatch_class, class_ended, epoch_ended, epoch_number,
        #   last_minibatch, class_lengths
        # and from the evaluator: n_err Vector, loss Vector, count Vector
        self.evaluator = None
        self.loader = None
        #: fused mode: metrics accumulate ON DEVICE in the runner; this
        #: points at it and Decision fetches once per class end
        #: (12 bytes) instead of 3 scalars per minibatch
        self.metrics_source = None
        # epoch stats
        self._acc_n_err: List[Any] = []
        self._acc_loss: List[Any] = []
        self._acc_count: List[Any] = []
        self.epoch_n_err = [0.0, 0.0, 0.0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.epoch_error_pct = [100.0, 100.0, 100.0]
        self.min_valid_error = float("inf")
        self.min_valid_epoch = -1
        self.min_train_error = float("inf")
        #: per-epoch history rows (epoch, class, n_err, loss, error%)
        self.history: List[dict] = []
        #: last COMPLETED class's confusion matrix, per class — the
        #: evaluator's accumulator is zeroed at each class end so the
        #: matrix is per-class-per-epoch, not a run-cumulative blur
        self.confusion_per_class: List[Any] = [None, None, None]

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        # keep snapshots from before these attrs existed resumable
        self.__dict__.setdefault("confusion_per_class", [None, None, None])
        self.__dict__.setdefault("metrics_source", None)

    # -- metric intake -------------------------------------------------

    def accumulate(self, n_err: Any, loss_sum: Any, count: Any) -> None:
        """Called per minibatch (directly or via run()); values may be
        device arrays — summed lazily at class end."""
        self._acc_n_err.append(n_err)
        self._acc_loss.append(loss_sum)
        self._acc_count.append(count)

    def _flush_class(self, klass: int) -> None:
        n_err = float(np.sum([np.asarray(x).sum() for x in self._acc_n_err]))
        loss = float(np.sum([np.asarray(x).sum() for x in self._acc_loss]))
        count = float(np.sum([np.asarray(x).sum() for x in self._acc_count]))
        self._acc_n_err.clear()
        self._acc_loss.clear()
        self._acc_count.clear()
        self.epoch_n_err[klass] = n_err
        self.epoch_loss[klass] = loss / max(count, 1.0)
        self.epoch_error_pct[klass] = 100.0 * n_err / max(count, 1.0)
        self.history.append({
            "epoch": self.loader.epoch_number, "class": CLASS_NAMES[klass],
            "n_err": n_err, "loss": self.epoch_loss[klass],
            "error_pct": self.epoch_error_pct[klass], "count": count})

    # -- firing --------------------------------------------------------

    def run(self) -> None:
        self.improved.set(False)
        self.epoch_ended_flag.set(False)
        ev = self.evaluator
        src = self.metrics_source
        if src is None and ev is not None:
            self.accumulate(ev.n_err.current(), ev.loss.current(),
                            ev.count.current())
        ld = self.loader
        if bool(ld.class_ended):
            klass = ld.minibatch_class
            if src is not None:
                n_err, loss, count, conf = src.take_class_metrics()
                self.accumulate(np.float32(n_err), np.float32(loss),
                                np.float32(count))
                if conf is not None:
                    self.confusion_per_class[klass] = conf
            else:
                conf = getattr(ev, "confusion", None) if ev else None
                if conf:
                    self.confusion_per_class[klass] = conf.mem.copy()
                    conf.mem[:] = 0
            self._flush_class(klass)
            self.info("epoch %d %s: n_err=%g loss=%.6f error=%.2f%%",
                      ld.epoch_number, CLASS_NAMES[klass],
                      self.epoch_n_err[klass], self.epoch_loss[klass],
                      self.epoch_error_pct[klass])
            if klass == VALID:
                self.on_validation_ended()
            if klass == TRAIN:
                self.on_train_ended()

    def on_validation_ended(self) -> None:
        err = self.epoch_n_err[VALID]
        if err < self.min_valid_error:
            self.min_valid_error = err
            self.min_valid_epoch = self.loader.epoch_number
            self.improved.set(True)

    def on_train_ended(self) -> None:
        self.epoch_ended_flag.set(True)
        # Workflows without a validation split improve on train error.
        if self.loader.class_lengths[VALID] == 0:
            err = self.epoch_n_err[TRAIN]
            if err < self.min_train_error:
                self.min_train_error = err
                self.improved.set(True)
        epoch = self.loader.epoch_number  # already incremented past end
        if self.max_epochs is not None and epoch >= self.max_epochs:
            self.info("complete: reached max_epochs=%d", self.max_epochs)
            self.complete.set(True)
        if (self.loader.class_lengths[VALID] > 0
                and self.min_valid_epoch >= 0
                and epoch - self.min_valid_epoch > self.fail_iterations):
            self.info("complete: no validation improvement in %d epochs",
                      self.fail_iterations)
            self.complete.set(True)

    # -- distribution (zmq DCN compat mode) ---------------------------

    def generate_data_for_master(self):
        return {"n_err": [float(np.asarray(x).sum())
                          for x in self._acc_n_err],
                "loss": [float(np.asarray(x).sum())
                         for x in self._acc_loss],
                "count": [float(np.asarray(x).sum())
                          for x in self._acc_count]}

    def apply_data_from_slave(self, data, slave=None) -> None:
        self._acc_n_err.extend(data["n_err"])
        self._acc_loss.extend(data["loss"])
        self._acc_count.extend(data["count"])
