"""Unit type registry: layer-config name -> (ForwardUnit, GradientUnit).

Reference parity: veles/znicz/standard_workflow.py resolves the
``layers = [{"type": ...}]`` declarative config through a name->class
mapping; this is that mapping.
"""

from __future__ import annotations

from typing import Dict, Tuple, Type

forward_registry: Dict[str, Tuple[type, type]] = {}


def register(name: str, forward_cls: type, gd_cls: type) -> None:
    forward_registry[name] = (forward_cls, gd_cls)


def gd_for(name: str) -> type:
    return forward_registry[name][1]


def _populate() -> None:
    """Import every op family and register its layer types.  A broken
    import fails HERE, loudly, with the family named — a silently
    missing family would otherwise surface as a baffling "unknown
    layer type" far from the real cause (round-1 VERDICT weak #5)."""
    families = []

    def family(name: str):
        def deco(fn):
            families.append((name, fn))
            return fn
        return deco

    @family("all2all")
    def _all2all():
        from veles_tpu.ops import all2all
        register("all2all", all2all.All2All, all2all.GradientDescent)
        register("all2all_tanh", all2all.All2AllTanh, all2all.GDTanh)
        register("all2all_relu", all2all.All2AllRELU, all2all.GDRELU)
        register("softmax", all2all.All2AllSoftmax, all2all.GDSoftmax)

    @family("conv")
    def _conv():
        from veles_tpu.ops import conv as conv_mod
        register("conv", conv_mod.Conv, conv_mod.GradientDescentConv)
        register("conv_tanh", conv_mod.ConvTanh,
                 conv_mod.GradientDescentConv)
        register("conv_relu", conv_mod.ConvRELU,
                 conv_mod.GradientDescentConv)

    @family("pooling")
    def _pooling():
        from veles_tpu.ops import pooling
        register("max_pooling", pooling.MaxPooling,
                 pooling.GDMaxPooling)
        register("avg_pooling", pooling.AvgPooling,
                 pooling.GDAvgPooling)
        register("stochastic_pooling", pooling.StochasticPooling,
                 pooling.GDMaxPooling)

    @family("activation")
    def _activation():
        from veles_tpu.ops import activation as act
        register("activation_tanh", act.ActivationTanh,
                 act.GDActivation)
        register("activation_relu", act.ActivationRELU,
                 act.GDActivation)
        register("activation_sigmoid", act.ActivationSigmoid,
                 act.GDActivation)
        register("activation_log", act.ActivationLog, act.GDActivation)
        register("activation_strict_relu", act.ActivationStrictRELU,
                 act.GDActivation)

    @family("dropout")
    def _dropout():
        from veles_tpu.ops import dropout
        register("dropout", dropout.Dropout, dropout.GDDropout)

    @family("lrn")
    def _lrn():
        from veles_tpu.ops import lrn
        register("norm", lrn.LRNormalizer, lrn.GDLRNormalizer)

    @family("rbm/cutter/resizable")
    def _rbm():
        from veles_tpu.ops import cutter, rbm, resizable_all2all
        from veles_tpu.ops import all2all
        register("all2all_sigmoid", all2all.All2AllSigmoid,
                 all2all.GDSigmoid)
        register("rbm", rbm.RBM, rbm.GDRBM)
        register("binarization", rbm.Binarization, rbm.GDBinarization)
        register("cutter", cutter.Cutter, cutter.GDCutter)
        register("resizable_all2all", resizable_all2all.ResizableAll2All,
                 resizable_all2all.GDResizableAll2All)

    @family("deconv/depooling")
    def _deconv():
        from veles_tpu.ops import deconv, depooling
        register("deconv", deconv.Deconv, deconv.GradientDescentDeconv)
        register("depooling", depooling.Depooling,
                 depooling.GDDepooling)

    for name, fn in families:
        try:
            fn()
        except ImportError as e:
            raise ImportError(
                f"op family {name!r} failed to import — its layer "
                f"types would be silently missing from the registry: "
                f"{e}") from e


_populate()
