"""Kohonen self-organizing map units (BASELINE config #5b).

Reference parity: veles/znicz/kohonen.py — a ``KohonenForward`` unit
(distances of each sample to every prototype on an sx*sy grid, winner
= argmin) and a trainer unit applying the classic SOM update with a
gaussian neighborhood and exponentially decaying learning rate/radius.
Unsupervised: no gradient-descent chain; the trainer IS the weight
update.

TPU path (Menagerie): a whole superstep group — one EPOCH at the
default grouping — is ONE donated ``lax.scan`` built through the Keel
trace builders (``engine_core.build_som_epoch``): the prototype matrix
is the donated scan carry, the (alpha, sigma) schedule rides the scan
xs so the decay applies per step inside the trace, and rows gather
in-trace from the resident dataset (or stream as host-assembled
superstep batches).  The eager per-minibatch dispatch survives as the
parity oracle (``VELES_SOM_FUSED=0``) and the numpy twin shares the
same array-API code.  :class:`SOMPopulationEngine` stacks P prototype
maps on a member axis and vmaps the same epoch body, so SOM
hyperparameter cohorts train population-batched like the supervised
cohorts of ops/fused.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu import events, prng, telemetry
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.memory import Vector


def grid_coords(sx: int, sy: int, xp=np):
    ys, xs = xp.meshgrid(xp.arange(sy), xp.arange(sx), indexing="ij")
    return xp.stack([ys.reshape(-1), xs.reshape(-1)], 1)  # (N, 2)


def som_step(weights, x_flat, coords, alpha, sigma):
    """Pure SOM update (shared numpy/jax array API).

    weights: (N, D) prototypes; x_flat: (B, D); coords: (N, 2) grid;
    returns (new_weights, winners, quantization_error_sum).
    """
    if isinstance(weights, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    d2 = ((x_flat * x_flat).sum(1, keepdims=True)
          - 2.0 * x_flat @ weights.T
          + (weights * weights).sum(1)[None, :])      # (B, N)
    winners = d2.argmin(1)                             # (B,)
    qe_sum = xp.sqrt(xp.maximum(
        d2[xp.arange(x_flat.shape[0]), winners], 0.0)).sum()
    wc = coords[winners]                               # (B, 2)
    gd2 = ((wc[:, None, :] - coords[None, :, :]) ** 2) \
        .sum(-1).astype(weights.dtype)                 # (B, N)
    h = xp.exp(-gd2 / (2.0 * sigma * sigma))           # (B, N)
    num = h.T @ x_flat                                 # (N, D)
    den = h.sum(0)[:, None]                            # (N, 1)
    delta = alpha * (num - den * weights) / x_flat.shape[0]
    return weights + delta, winners, qe_sum


def som_step_masked(weights, x_flat, coords, alpha, sigma, mask):
    """:func:`som_step` with a static-shape row-validity mask — the
    fused scan body (ragged final minibatches ride the scan padded to
    the fixed minibatch shape; the mask zeroes the padding rows out of
    the neighborhood sums and the QE).

    With an all-ones mask this is f32-BITWISE identical to
    :func:`som_step`: every masked term multiplies by 1.0 (IEEE-exact)
    and the update divides by ``mask.sum()``, which equals the batch
    size.  Returns ``(new_weights, winners, qe_sum, n_valid)``.
    """
    if isinstance(weights, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    d2 = ((x_flat * x_flat).sum(1, keepdims=True)
          - 2.0 * x_flat @ weights.T
          + (weights * weights).sum(1)[None, :])      # (B, N)
    winners = d2.argmin(1)                             # (B,)
    qe_sum = (xp.sqrt(xp.maximum(
        d2[xp.arange(x_flat.shape[0]), winners], 0.0)) * mask).sum()
    wc = coords[winners]                               # (B, 2)
    gd2 = ((wc[:, None, :] - coords[None, :, :]) ** 2) \
        .sum(-1).astype(weights.dtype)                 # (B, N)
    h = xp.exp(-gd2 / (2.0 * sigma * sigma)) \
        * mask[:, None]                                # (B, N)
    num = h.T @ x_flat                                 # (N, D)
    den = h.sum(0)[:, None]                            # (N, 1)
    n = xp.maximum(mask.sum(), 1.0)
    delta = alpha * (num - den * weights) / n
    return weights + delta, winners, qe_sum, mask.sum()


def som_qe_masked(weights, x_flat, mask):
    """Masked quantization error of one minibatch (the evaluation-
    class body): summed ``sqrt(min distance)`` over valid rows plus
    the valid-row count."""
    if isinstance(weights, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    d2 = ((x_flat * x_flat).sum(1, keepdims=True)
          - 2.0 * x_flat @ weights.T
          + (weights * weights).sum(1)[None, :])
    qe = (xp.sqrt(xp.maximum(d2.min(1), 0.0)) * mask).sum()
    return qe, mask.sum()


class KohonenForward(AcceleratedUnit):
    """Distances + winners for the current minibatch (inference side).

    Also the SOM's serving op: :meth:`apply_fwd` follows the fused
    forward contract (``(params, x, rng, train) -> (y, residual)``),
    returning the (B, N) squared-distance map — a Hive client reads
    ``argmin`` for the winner and ``sqrt(max(min, 0))`` for the
    per-sample quantization error, and the ensemble mean of member
    maps is the cohort-consensus distance field.
    """

    #: no dropout/sampling anywhere in the SOM forward — the fused
    #: builders skip the rng chain entirely
    stochastic = False

    def __init__(self, workflow=None, shape: Tuple[int, int] = (8, 8),
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.sy, self.sx = shape
        self.input = Vector(name=f"{self.name}.input")
        self.weights = Vector(name=f"{self.name}.weights")
        self.output = Vector(name=f"{self.name}.output")   # distances
        self.winners = Vector(name=f"{self.name}.winners")

    @property
    def n_neurons(self) -> int:
        return self.sx * self.sy

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        d = int(np.prod(self.input.shape[1:]))
        if not self.weights:
            gen = prng.get("weights").numpy
            self.weights.mem = gen.uniform(
                -0.1, 0.1, (self.n_neurons, d)).astype(np.float32)
        self.weights.initialize(device)
        self.input.initialize(device)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        x = x.reshape(x.shape[0], -1)
        w = params["weights"]
        d2 = ((x * x).sum(1, keepdims=True) - 2.0 * x @ w.T
              + (w * w).sum(1)[None, :])
        return {"output": d2, "winners": d2.argmin(1)}

    def gather_params(self):
        return {"weights": self.weights.unmap()}

    def param_vectors(self) -> Dict[str, Vector]:
        """The stacking contract the cohort engines and Forge
        packaging read (same shape as the supervised forwards')."""
        return {"weights": self.weights}

    def apply_fwd(self, params, x, rng=None, train=False):
        """Fused/serving forward: (B, ...) samples -> (B, N) squared
        distances to every prototype.  No residual (the SOM has no
        gradient chain) and no rng (``stochastic`` is False)."""
        x = x.reshape(x.shape[0], -1)
        w = params["weights"]
        d2 = ((x * x).sum(1, keepdims=True) - 2.0 * x @ w.T
              + (w * w).sum(1)[None, :])
        return d2, None

    def run(self) -> None:
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            out = self.apply({"weights": self.weights.map_read()},
                             {"input": self.input.map_read()})
            self.output.reset(out["output"])
            self.winners.reset(out["winners"])
        else:
            if self._compiled is None:
                self._compiled = self.device.compile(self.apply)
            out = self._compiled(self.gather_params(),
                                 {"input": self.input.unmap()})
            self.output.devmem = out["output"]
            self.winners.devmem = out["winners"]


class KohonenTrainer(AcceleratedUnit):
    """The SOM update; owns the schedule state.

    alpha(t) and sigma(t) decay exponentially from their initial values
    to their final values over ``decay_epochs`` (reference: gravity /
    radius decay in znicz kohonen trainer).
    """

    def __init__(self, workflow=None,
                 forward: Optional[KohonenForward] = None,
                 alpha0: float = 0.3, alpha_min: float = 0.01,
                 sigma0: Optional[float] = None, sigma_min: float = 0.5,
                 decay_epochs: int = 20, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.forward = forward
        self.alpha0, self.alpha_min = alpha0, alpha_min
        self.sigma0 = sigma0
        self.sigma_min = sigma_min
        self.decay_epochs = decay_epochs
        self.loader = None
        #: True = run whole superstep groups as ONE donated epoch scan
        #: through the Keel builders (set by the workflow's fused
        #: wiring; requires a jax device and the loader's host fill
        #: disabled).  False keeps the eager per-minibatch dispatch.
        self.fused = False
        #: optional mesh for the row-sharded resident gather (the
        #: loader's ``shard_resident`` placement)
        self.mesh = None
        # metrics published for Decision (same contract as evaluators)
        self.n_err = Vector(name=f"{self.name}.n_err")
        self.loss = Vector(name=f"{self.name}.loss")
        self.count = Vector(name=f"{self.name}.count")
        self._coords_host = None
        self._coords_dev = None
        self._core = None
        self._train_epoch = None
        self._eval_epoch = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        f = self.forward
        if self.sigma0 is None:
            self.sigma0 = max(f.sx, f.sy) / 2.0
        self._coords_host = grid_coords(f.sx, f.sy).astype(np.float32)
        if device is not None and device.is_jax:
            self._coords_dev = device.put(self._coords_host)

    def schedule(self) -> Tuple[float, float]:
        return self.schedule_at(getattr(self.loader, "epoch_number",
                                        0))

    def schedule_at(self, epoch: int) -> Tuple[float, float]:
        t = min(epoch, self.decay_epochs) / max(self.decay_epochs, 1)
        alpha = self.alpha0 * (self.alpha_min / self.alpha0) ** t
        sigma = self.sigma0 * (self.sigma_min / self.sigma0) ** t
        return alpha, sigma

    def schedule_steps(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """The (k,) per-step schedule of the current superstep group,
        mirroring the eager sequence EXACTLY: the loader increments
        ``epoch_number`` inside the same ``run()`` that emits the
        epoch's final minibatch, so an eager loop trains steps
        0..k-2 at the old epoch's (alpha, sigma) and only the last
        step at the new one."""
        ld = self.loader
        e = getattr(ld, "epoch_number", 0)
        ended = bool(ld.epoch_ended) if ld is not None else False
        a_bulk, s_bulk = self.schedule_at(e - 1 if ended else e)
        alphas = np.full((k,), a_bulk, np.float32)
        sigmas = np.full((k,), s_bulk, np.float32)
        if ended:
            a_last, s_last = self.schedule_at(e)
            alphas[k - 1] = a_last
            sigmas[k - 1] = s_last
        return alphas, sigmas

    def run(self) -> None:
        if self.fused and self.device is not None \
                and self.device.is_jax:
            self._run_fused()
            return
        if self.loader is not None and \
                self.loader.minibatch_class != TRAIN:
            # evaluation classes: quantization error only
            self._eval_only()
            return
        f = self.forward
        alpha, sigma = self.schedule()
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            x = f.input.map_read().reshape(len(f.input), -1)
            w, winners, qe = som_step(f.weights.map_read(), x,
                                      self._coords_host,
                                      np.float32(alpha),
                                      np.float32(sigma))
            f.weights.map_invalidate()[:] = w
            self.loss.reset(np.float32([qe]))
        else:
            # the eager jax dispatch runs the SAME masked body the
            # fused scan traces (fused-vs-eager parity is the
            # same-jaxpr argument) — a ragged final minibatch's
            # np.resize padding rows are masked out of the update
            # instead of training as duplicates
            if self._compiled is None:
                from veles_tpu.engine import core as engine_core
                self._compiled = engine_core.donating_jit(
                    som_step_masked, donate=(0,))
            mask = self._eager_mask()
            w, winners, qe, _ = self._compiled(
                f.weights.unmap(),
                f.input.unmap().reshape(len(f.input), -1),
                self._coords_dev,
                np.float32(alpha), np.float32(sigma), mask)
            f.weights.devmem = w
            self.loss.devmem = qe
            # real rows only (ragged tails publish the masked count,
            # matching the fused path's totals exactly)
            self.n_err.reset(np.float32([0.0]))
            self.count.reset(np.float32([mask.sum()]))
            return
        n = len(f.input)
        self.n_err.reset(np.float32([0.0]))
        self.count.reset(np.float32([n]))

    def _eager_mask(self) -> np.ndarray:
        """The current minibatch's row-validity mask for the eager jax
        dispatch (all ones when the loader exposes none)."""
        ld = self.loader
        if ld is not None and \
                getattr(ld, "superstep_mask", None) is not None:
            return np.ascontiguousarray(ld.superstep_mask[-1],
                                        np.float32)
        return np.ones(len(self.forward.input), np.float32)

    def _eval_only(self) -> None:
        f = self.forward
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            out = f.apply({"weights": f.weights.map_read()},
                          {"input": f.input.map_read()})
            d2 = out["output"]
            qe = np.sqrt(np.maximum(d2.min(1), 0)).sum()
            self.loss.reset(np.float32([qe]))
        else:
            # compiled through the Keel seam like the train path (the
            # engine-residency-seam contract: no stray device.compile
            # dispatchers outside engine loops)
            if getattr(self, "_eval_compiled", None) is None:
                from veles_tpu.engine import core as engine_core

                def eval_fn(wts, x, mask):
                    return som_qe_masked(
                        wts, x.reshape(x.shape[0], -1), mask)[0]

                self._eval_compiled = engine_core.donating_jit(eval_fn)
            mask = self._eager_mask()
            self.loss.devmem = self._eval_compiled(
                f.weights.unmap(), f.input.unmap(), mask)
            self.n_err.reset(np.float32([0.0]))
            self.count.reset(np.float32([mask.sum()]))
            return
        self.n_err.reset(np.float32([0.0]))
        self.count.reset(np.float32([len(f.input)]))

    # -- the fused epoch path (Menagerie) ------------------------------

    def _build_fused(self) -> None:
        from veles_tpu.engine import core as engine_core

        ld = self.loader
        resident = bool(getattr(ld, "device_resident", True))
        gather = None
        if resident and getattr(ld, "shard_resident", False) \
                and self.mesh is not None:
            from veles_tpu.ops import batching
            gather = batching.make_sharded_row_gather(self.mesh)
        self._core = engine_core.ExecutionCore(
            self.device, self.mesh, pool="train", name=self.name)
        self._train_epoch = self._core.jit(
            engine_core.build_som_epoch(self._coords_host,
                                        resident=resident,
                                        gather=gather),
            donate=(0,))
        self._eval_epoch = self._core.jit(
            engine_core.build_som_eval(self._coords_host,
                                       resident=resident,
                                       gather=gather))
        self._fused_resident = resident

    def _run_fused(self) -> None:
        ld = self.loader
        f = self.forward
        if self._core is None:
            self._build_fused()
        idxs = ld.superstep_indices
        mask = np.ascontiguousarray(ld.superstep_mask, np.float32)
        k = int(idxs.shape[0])
        train = ld.minibatch_class == TRAIN
        # the schedule rides the scan xs, applied PER STEP inside the
        # trace — per-minibatch schedules are a host array away,
        # never a retrace
        alphas, sigmas = self.schedule_steps(k)
        w = f.weights.unmap()
        if self._fused_resident:
            dataset = ld.original_data.unmap()
            idx = np.ascontiguousarray(idxs, np.int32)
            if train:
                w, stats = self._train_epoch(w, alphas, sigmas,
                                             dataset, idx, mask)
            else:
                stats = self._eval_epoch(w, dataset, idx, mask)
        else:
            xb = self._core.put(
                np.ascontiguousarray(ld.superstep_data))
            if train:
                w, stats = self._train_epoch(w, alphas, sigmas, xb,
                                             mask)
            else:
                stats = self._eval_epoch(w, xb, mask)
        if train:
            f.weights.devmem = w
        self.loss.devmem = stats[0]
        n = float(mask.sum())
        self.n_err.reset(np.float32([0.0]))
        self.count.reset(np.float32([n]))
        telemetry.counter(events.CTR_SOM_FUSED_DISPATCHES).inc()
        telemetry.counter(events.CTR_SOM_FUSED_IMAGES).inc(int(n))

    _unpicklable = AcceleratedUnit._unpicklable + (
        "_coords_dev", "_eval_compiled", "_core", "_train_epoch",
        "_eval_epoch")


class SOMPopulationEngine:
    """Population-batched SOM training: P prototype maps trained in
    ONE vmapped fused epoch scan per loader firing — the
    ``PopulationTrainEngine`` move applied to the zoo's unsupervised
    long tail.

    Each member is one (alpha0, alpha_min, sigma0, sigma_min)
    hyperparameter genome over the SAME map shape and init (the
    workflow's seeded prototype matrix), stacked on a leading member
    axis; the per-member schedule rides the scan xs, so one donated
    dispatch advances every member one superstep group.  The dataset
    stays UNBATCHED (vmap broadcasts the gather), so HBM holds
    prototypes x P, never data x P.

    On a ``mesh`` the member axis shards P/N per device exactly like
    the supervised cohort (padded to a whole per-device tile by
    repeating member 0; padding rows are sliced off every fetch), with
    per-member math bit-identical to the unsharded stacking — members
    never reduce across each other.

    :meth:`run` drives the workflow's OWN loader to
    ``decision.max_epochs`` and returns the (P,) fitness vector: each
    member's MINIMUM per-epoch mean quantization error, on the
    validation class when the loader has one, else on train — the
    quantity a per-member oracle run reads off its decision history.
    The stacked maps stay live in ``_params`` (keyed by the forward's
    name) for ``GAServingHandoff.adopt_cohort`` until
    :meth:`release`.
    """

    def __init__(self, workflow, member_hparams: np.ndarray,
                 mesh: Any = None) -> None:
        trainer = workflow.trainer
        device = trainer.device
        if device is None or not getattr(device, "is_jax", False):
            raise ValueError(
                "SOMPopulationEngine needs a jax device (TPU or "
                "XLA:CPU); per-member evaluation is the numpy path")
        if trainer._coords_host is None:
            raise ValueError("workflow must be initialized before "
                             "building a SOM cohort")
        self.workflow = workflow
        self.trainer = trainer
        self.forward = workflow.forward
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.device = device
        hp = np.asarray(member_hparams, np.float32)
        if hp.ndim != 2 or hp.shape[1] != 4:
            raise ValueError(
                "member hyperparameters must be a (P, 4) "
                "[alpha0, alpha_min, sigma0, sigma_min] array; got "
                f"{hp.shape}")
        self.n_members = int(hp.shape[0])
        self.streaming = not bool(getattr(self.loader,
                                          "device_resident", True))
        self.mesh = mesh if (mesh is not None
                             and int(mesh.devices.size) > 1) else None
        if self.mesh is not None:
            from veles_tpu import knobs
            from veles_tpu.parallel.mesh import shard_mode
            if shard_mode(knobs.get(knobs.MESH_SHARD_MEMBERS)) \
                    == "never":
                self.mesh = None
        self.member_sharded = self.mesh is not None
        from veles_tpu.engine import core as engine_core
        from veles_tpu.ops import batching
        self._core = engine_core.ExecutionCore(
            device, self.mesh, pool="cohort",
            name=f"som_cohort:{workflow.name}")
        if self.member_sharded:
            n_dev = int(self.mesh.devices.size)
            (hp,), self._n_stacked = batching.pad_members([hp], n_dev)
        else:
            self._n_stacked = self.n_members
        self._hp = hp
        w0 = np.asarray(self.forward.weights.map_read(), np.float32)
        self._params = {self.forward.name: {
            "weights": self._core.put_members(
                np.stack([w0] * self._n_stacked))}}
        epoch = engine_core.build_som_epoch(
            trainer._coords_host, resident=not self.streaming)
        evaluate = engine_core.build_som_eval(
            trainer._coords_host, resident=not self.streaming)
        if self.streaming:
            train_axes = (0, 0, 0, None, None)
            eval_axes = (0, None, None)
        else:
            train_axes = (0, 0, 0, None, None, None)
            eval_axes = (0, None, None, None)
        self._train_step = self._core.jit(
            self._core.vmap_members(epoch, in_axes=train_axes),
            donate=(0,))
        self._eval_step = self._core.jit(
            self._core.vmap_members(evaluate, in_axes=eval_axes))
        self._core.charge(engine_core.tree_nbytes(self._params))

    # -- per-member schedule -------------------------------------------

    def _schedule_at(self, epoch: int) -> Tuple[np.ndarray,
                                                np.ndarray]:
        decay = self.trainer.decay_epochs
        t = min(epoch, decay) / max(decay, 1)
        a0, amin = self._hp[:, 0], self._hp[:, 1]
        s0, smin = self._hp[:, 2], self._hp[:, 3]
        return ((a0 * (amin / a0) ** t).astype(np.float32),
                (s0 * (smin / s0) ** t).astype(np.float32))

    def _member_schedule(self, k: int) -> Tuple[np.ndarray,
                                                np.ndarray]:
        """(P_stacked, k) alpha/sigma arrays for this firing — each
        member's exponential decay evaluated with the SAME per-step
        epoch logic :meth:`KohonenTrainer.schedule_steps` uses (the
        loader increments ``epoch_number`` inside the run() that
        emits the epoch's final minibatch, so only the group's LAST
        step sees the new epoch)."""
        ld = self.loader
        e = getattr(ld, "epoch_number", 0)
        ended = bool(ld.epoch_ended)
        a_bulk, s_bulk = self._schedule_at(e - 1 if ended else e)
        alphas = np.repeat(a_bulk[:, None], k, axis=1)
        sigmas = np.repeat(s_bulk[:, None], k, axis=1)
        if ended:
            a_last, s_last = self._schedule_at(e)
            alphas[:, k - 1] = a_last
            sigmas[:, k - 1] = s_last
        return np.ascontiguousarray(alphas), \
            np.ascontiguousarray(sigmas)

    def _fetch(self, stats) -> np.ndarray:
        """One (P, 2) [qe_sum, count] fetch, REAL members only."""
        stats = self._core.replicate_for_fetch(stats)
        return np.asarray(stats)[:self.n_members].astype(np.float64)

    # -- the run loop --------------------------------------------------

    def run(self) -> np.ndarray:
        with telemetry.span(events.SPAN_SOM_COHORT_TRAIN,
                            journal=True, members=self.n_members):
            telemetry.counter(events.CTR_SOM_COHORTS).inc()
            telemetry.counter(
                events.CTR_SOM_COHORT_MEMBERS).inc(self.n_members)
            return self._run_inner()

    def _run_inner(self) -> np.ndarray:
        ld = self.loader
        P = self.n_members
        fwd_name = self.forward.name
        max_epochs = self.decision.max_epochs
        has_valid = ld.class_lengths[VALID] > 0
        fit_class = VALID if has_valid else TRAIN
        best = np.full(P, np.inf)
        class_acc = np.zeros((P, 2), np.float64)
        weights = self._params[fwd_name]["weights"]
        while True:
            ld.run()
            idxs, mask = ld.superstep_indices, ld.superstep_mask
            k = int(idxs.shape[0])
            klass = ld.minibatch_class
            if klass == fit_class or klass == TRAIN:
                mask_dev = self._core.put_replicated(
                    np.ascontiguousarray(mask, np.float32))
            if klass == TRAIN:
                alphas, sigmas = self._member_schedule(k)
                al_dev = self._core.put_members(alphas)
                si_dev = self._core.put_members(sigmas)
                if self.streaming:
                    xb = self._core.put_replicated(
                        np.ascontiguousarray(ld.superstep_data))
                    weights, stats = self._train_step(
                        weights, al_dev, si_dev, xb, mask_dev)
                else:
                    weights, stats = self._train_step(
                        weights, al_dev, si_dev,
                        self._dataset(), self._core.put_replicated(
                            np.ascontiguousarray(idxs, np.int32)),
                        mask_dev)
                self._params[fwd_name]["weights"] = weights
                if fit_class == TRAIN:
                    class_acc += self._fetch(stats)
            elif klass == fit_class:
                if self.streaming:
                    xb = self._core.put_replicated(
                        np.ascontiguousarray(ld.superstep_data))
                    stats = self._eval_step(weights, xb, mask_dev)
                else:
                    stats = self._eval_step(
                        weights, self._dataset(),
                        self._core.put_replicated(
                            np.ascontiguousarray(idxs, np.int32)),
                        mask_dev)
                class_acc += self._fetch(stats)
            if not bool(ld.class_ended):
                continue
            if klass == fit_class:
                mean_qe = class_acc[:, 0] / np.maximum(
                    class_acc[:, 1], 1.0)
                best = np.minimum(best, mean_qe)
                class_acc = np.zeros((P, 2), np.float64)
            if klass == TRAIN and max_epochs is not None and \
                    ld.epoch_number >= max_epochs:
                break
        return best

    def _dataset(self):
        if self.member_sharded:
            if getattr(self, "_dataset_dev", None) is None:
                # the engine owns its mesh placement: replicate the
                # host copy next to the member-sharded stacks
                self._dataset_dev = self._core.put_replicated(
                    self.loader.original_data.map_read())
            return self._dataset_dev
        return self.loader.original_data.unmap()

    def release(self) -> None:
        """Drop the stacked device state (serve-mode hygiene: a
        process lives across many cohorts and HBM must not
        accumulate)."""
        self._params = None
        self._train_step = self._eval_step = None
        self._dataset_dev = None
        self._core.release()
