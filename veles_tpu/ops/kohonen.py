"""Kohonen self-organizing map units (BASELINE config #5b).

Reference parity: veles/znicz/kohonen.py — a ``KohonenForward`` unit
(distances of each sample to every prototype on an sx*sy grid, winner
= argmin) and a trainer unit applying the classic SOM update with a
gaussian neighborhood and exponentially decaying learning rate/radius.
Unsupervised: no gradient-descent chain; the trainer IS the weight
update.

TPU path: one jitted step computes distances, winners, the
batch-summed neighborhood update and the new prototype matrix, with
the prototype buffer donated.  Numpy twin shares the same array-API
code.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu import prng
from veles_tpu.loader.base import TRAIN
from veles_tpu.memory import Vector


def grid_coords(sx: int, sy: int, xp=np):
    ys, xs = xp.meshgrid(xp.arange(sy), xp.arange(sx), indexing="ij")
    return xp.stack([ys.reshape(-1), xs.reshape(-1)], 1)  # (N, 2)


def som_step(weights, x_flat, coords, alpha, sigma):
    """Pure SOM update (shared numpy/jax array API).

    weights: (N, D) prototypes; x_flat: (B, D); coords: (N, 2) grid;
    returns (new_weights, winners, quantization_error_sum).
    """
    if isinstance(weights, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    d2 = ((x_flat * x_flat).sum(1, keepdims=True)
          - 2.0 * x_flat @ weights.T
          + (weights * weights).sum(1)[None, :])      # (B, N)
    winners = d2.argmin(1)                             # (B,)
    qe_sum = xp.sqrt(xp.maximum(
        d2[xp.arange(x_flat.shape[0]), winners], 0.0)).sum()
    wc = coords[winners]                               # (B, 2)
    gd2 = ((wc[:, None, :] - coords[None, :, :]) ** 2) \
        .sum(-1).astype(weights.dtype)                 # (B, N)
    h = xp.exp(-gd2 / (2.0 * sigma * sigma))           # (B, N)
    num = h.T @ x_flat                                 # (N, D)
    den = h.sum(0)[:, None]                            # (N, 1)
    delta = alpha * (num - den * weights) / x_flat.shape[0]
    return weights + delta, winners, qe_sum


class KohonenForward(AcceleratedUnit):
    """Distances + winners for the current minibatch (inference side)."""

    def __init__(self, workflow=None, shape: Tuple[int, int] = (8, 8),
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.sy, self.sx = shape
        self.input = Vector(name=f"{self.name}.input")
        self.weights = Vector(name=f"{self.name}.weights")
        self.output = Vector(name=f"{self.name}.output")   # distances
        self.winners = Vector(name=f"{self.name}.winners")

    @property
    def n_neurons(self) -> int:
        return self.sx * self.sy

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        d = int(np.prod(self.input.shape[1:]))
        if not self.weights:
            gen = prng.get("weights").numpy
            self.weights.mem = gen.uniform(
                -0.1, 0.1, (self.n_neurons, d)).astype(np.float32)
        self.weights.initialize(device)
        self.input.initialize(device)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        x = x.reshape(x.shape[0], -1)
        w = params["weights"]
        d2 = ((x * x).sum(1, keepdims=True) - 2.0 * x @ w.T
              + (w * w).sum(1)[None, :])
        return {"output": d2, "winners": d2.argmin(1)}

    def gather_params(self):
        return {"weights": self.weights.unmap()}

    def run(self) -> None:
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            out = self.apply({"weights": self.weights.map_read()},
                             {"input": self.input.map_read()})
            self.output.reset(out["output"])
            self.winners.reset(out["winners"])
        else:
            if self._compiled is None:
                self._compiled = self.device.compile(self.apply)
            out = self._compiled(self.gather_params(),
                                 {"input": self.input.unmap()})
            self.output.devmem = out["output"]
            self.winners.devmem = out["winners"]


class KohonenTrainer(AcceleratedUnit):
    """The SOM update; owns the schedule state.

    alpha(t) and sigma(t) decay exponentially from their initial values
    to their final values over ``decay_epochs`` (reference: gravity /
    radius decay in znicz kohonen trainer).
    """

    def __init__(self, workflow=None,
                 forward: Optional[KohonenForward] = None,
                 alpha0: float = 0.3, alpha_min: float = 0.01,
                 sigma0: Optional[float] = None, sigma_min: float = 0.5,
                 decay_epochs: int = 20, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.forward = forward
        self.alpha0, self.alpha_min = alpha0, alpha_min
        self.sigma0 = sigma0
        self.sigma_min = sigma_min
        self.decay_epochs = decay_epochs
        self.loader = None
        # metrics published for Decision (same contract as evaluators)
        self.n_err = Vector(name=f"{self.name}.n_err")
        self.loss = Vector(name=f"{self.name}.loss")
        self.count = Vector(name=f"{self.name}.count")
        self._coords_host = None
        self._coords_dev = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        f = self.forward
        if self.sigma0 is None:
            self.sigma0 = max(f.sx, f.sy) / 2.0
        self._coords_host = grid_coords(f.sx, f.sy).astype(np.float32)
        if device is not None and device.is_jax:
            self._coords_dev = device.put(self._coords_host)

    def schedule(self) -> Tuple[float, float]:
        t = min(getattr(self.loader, "epoch_number", 0),
                self.decay_epochs) / max(self.decay_epochs, 1)
        alpha = self.alpha0 * (self.alpha_min / self.alpha0) ** t
        sigma = self.sigma0 * (self.sigma_min / self.sigma0) ** t
        return alpha, sigma

    def run(self) -> None:
        if self.loader is not None and \
                self.loader.minibatch_class != TRAIN:
            # evaluation classes: quantization error only
            self._eval_only()
            return
        f = self.forward
        alpha, sigma = self.schedule()
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            x = f.input.map_read().reshape(len(f.input), -1)
            w, winners, qe = som_step(f.weights.map_read(), x,
                                      self._coords_host,
                                      np.float32(alpha),
                                      np.float32(sigma))
            f.weights.map_invalidate()[:] = w
            self.loss.reset(np.float32([qe]))
        else:
            if self._compiled is None:
                from veles_tpu.engine import core as engine_core
                self._compiled = engine_core.donating_jit(
                    som_step, donate=(0,))
            w, winners, qe = self._compiled(
                f.weights.unmap(),
                f.input.unmap().reshape(len(f.input), -1),
                self._coords_dev,
                np.float32(alpha), np.float32(sigma))
            f.weights.devmem = w
            self.loss.devmem = qe
        n = len(f.input)
        self.n_err.reset(np.float32([0.0]))
        self.count.reset(np.float32([n]))

    def _eval_only(self) -> None:
        f = self.forward
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            out = f.apply({"weights": f.weights.map_read()},
                          {"input": f.input.map_read()})
            d2 = out["output"]
            qe = np.sqrt(np.maximum(d2.min(1), 0)).sum()
            self.loss.reset(np.float32([qe]))
        else:
            import jax.numpy as jnp

            def eval_fn(wts, x):
                out = f.apply({"weights": wts}, {"input": x})
                return jnp.sqrt(jnp.maximum(out["output"].min(1), 0)).sum()

            if getattr(self, "_eval_compiled", None) is None:
                self._eval_compiled = self.device.compile(eval_fn)
            self.loss.devmem = self._eval_compiled(f.weights.unmap(),
                                                   f.input.unmap())
        self.n_err.reset(np.float32([0.0]))
        self.count.reset(np.float32([len(f.input)]))

    _unpicklable = AcceleratedUnit._unpicklable + (
        "_coords_dev", "_eval_compiled")
