"""Evaluators: loss, error counts, and the err_output seed for backward.

Reference parity: veles/znicz/evaluator.py — ``EvaluatorSoftmax``
(cross-entropy, n_err, confusion matrix; err_output = probs - onehot,
i.e. the fused softmax+CE gradient) and ``EvaluatorMSE``.

The pure ``metrics_fn`` is shared by the eager path and the fused step;
metrics come out as arrays so the fused TPU path can accumulate them
on-device without a host sync per minibatch (Decision reads them once
per class — SURVEY.md §7 "hard parts").

Padded minibatch rows (static-shape remainder handling) are excluded
everywhere via ``mask``; losses/gradients normalize by the REAL row
count ``mask.sum()``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Vector


class EvaluatorBase(AcceleratedUnit):
    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.input = Vector(name=f"{self.name}.input")      # net output
        self.err_output = Vector(name=f"{self.name}.err_output")
        self.n_err = Vector(name=f"{self.name}.n_err")      # scalar
        self.loss = Vector(name=f"{self.name}.loss")        # scalar (sum)
        self.count = Vector(name=f"{self.name}.count")      # scalar (rows)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        # .shape raises AttributeError while the producing forward is
        # uninitialized -> Workflow.initialize retries us later.
        in_shape = self.input.shape
        if not self.err_output:
            self.err_output.mem = np.zeros(in_shape, np.float32)
        for v in (self.err_output, self.n_err, self.loss, self.count):
            v.initialize(device)

    def metrics_fn(self, output: Any, target: Any, mask: Any) \
            -> Dict[str, Any]:
        """Pure: {err_output, n_err, loss_sum, count}."""
        raise NotImplementedError


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy over class probabilities.

    ``input`` holds the softmax unit's probabilities; ``labels`` are
    int32 class ids.  err_output = (probs - onehot) * mask / n_valid —
    d(mean CE)/d(logits), completing the softmax+CE fusion with the
    producing unit's ``activation_mode == 'softmax'`` contract.
    """

    def __init__(self, workflow=None, n_classes: int = None,  # type: ignore
                 compute_confusion: bool = True, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_classes = n_classes
        self.compute_confusion = compute_confusion
        self.labels = Vector(name=f"{self.name}.labels")
        self.mask = Vector(name=f"{self.name}.mask")
        self.confusion = Vector(name=f"{self.name}.confusion")
        self.max_idx = Vector(name=f"{self.name}.max_idx")

    def initialize(self, device=None, **kwargs) -> None:
        if self.n_classes is None:
            self.n_classes = int(self.input.shape[-1])
        super().initialize(device=device, **kwargs)
        if self.compute_confusion and not self.confusion:
            self.confusion.mem = np.zeros(
                (self.n_classes, self.n_classes), np.int64)
            self.confusion.initialize(None)  # host-side accumulator

    def metrics_fn(self, output, target, mask):
        eps = 1e-12
        if isinstance(output, np.ndarray):
            n = mask.sum()
            onehot = np.eye(output.shape[-1],
                            dtype=output.dtype)[target]
            err = (output - onehot) * mask[:, None] / np.maximum(n, 1.0)
            pred = output.argmax(-1)
            n_err = ((pred != target) * mask).sum()
            p = output[np.arange(len(target)), target]
            loss_sum = -(np.log(np.maximum(p, eps)) * mask).sum()
            return {"err_output": err.astype(np.float32),
                    "n_err": np.float32(n_err),
                    "loss_sum": np.float32(loss_sum),
                    "count": np.float32(n),
                    "max_idx": pred.astype(np.int32)}
        import jax.numpy as jnp
        n = mask.sum()
        onehot = jnp.eye(output.shape[-1], dtype=output.dtype)[target]
        err = (output - onehot) * mask[:, None] / jnp.maximum(n, 1.0)
        pred = output.argmax(-1)
        n_err = ((pred != target) * mask).sum()
        p = jnp.take_along_axis(output, target[:, None], axis=-1)[:, 0]
        loss_sum = -(jnp.log(jnp.maximum(p, eps)) * mask).sum()
        return {"err_output": err.astype(jnp.float32),
                "n_err": n_err.astype(jnp.float32),
                "loss_sum": loss_sum.astype(jnp.float32),
                "count": n.astype(jnp.float32),
                "max_idx": pred.astype(jnp.int32)}

    def run(self) -> None:
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            out = self.input.map_read()
            target = self.labels.map_read()
            mask = self.mask.map_read()
            m = self.metrics_fn(out, target, mask)
            self.err_output.reset(m["err_output"])
            self.n_err.reset(np.float32([m["n_err"]]))
            self.loss.reset(np.float32([m["loss_sum"]]))
            self.count.reset(np.float32([m["count"]]))
            self.max_idx.reset(m["max_idx"])
        else:
            if self._compiled is None:
                self._compiled = self.device.compile(self.metrics_fn)
            m = self._compiled(self.input.unmap(), self.labels.unmap(),
                               self.mask.unmap())
            self.err_output.devmem = m["err_output"]
            self.n_err.devmem = m["n_err"]
            self.loss.devmem = m["loss_sum"]
            self.count.devmem = m["count"]
            self.max_idx.devmem = m["max_idx"]
        if self.compute_confusion:
            # host-side confusion accumulation (read once per minibatch
            # in eager modes; the fused path accumulates on device)
            pred = np.asarray(self.max_idx.map_read()
                              if numpy_mode else self.max_idx.devmem)
            target = np.asarray(self.labels.map_read())
            mask = np.asarray(self.mask.map_read())
            valid = mask > 0
            np.add.at(self.confusion.mem, (target[valid], pred[valid]), 1)


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (autoencoders, regression).

    Normalized per ELEMENT, not per row: with D = features/sample,
    loss_sum = sum over valid rows of 0.5 * ||y - t||^2 / D and
    err_output = (y - t) * mask / (n_valid * D).  Per-row-only
    normalization makes the gradient scale with the sample size (784x
    for MNIST images) and blows up any reasonable learning rate.
    """

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.target = Vector(name=f"{self.name}.target")
        self.mask = Vector(name=f"{self.name}.mask")

    def metrics_fn(self, output, target, mask):
        diff = output - target
        bshape = (-1,) + (1,) * (diff.ndim - 1)
        m = mask.reshape(bshape)
        n = mask.sum()
        d = float(np.prod(output.shape[1:]))
        if isinstance(output, np.ndarray):
            err = diff * m / np.maximum(n * d, 1.0)
            per_row = 0.5 * (diff * diff).reshape(len(diff), -1).sum(-1) / d
            loss_sum = (per_row * mask).sum()
            return {"err_output": err.astype(np.float32),
                    "n_err": np.float32(0.0),
                    "loss_sum": np.float32(loss_sum),
                    "count": np.float32(n)}
        import jax.numpy as jnp
        err = diff * m / jnp.maximum(n * d, 1.0)
        per_row = 0.5 * (diff * diff).reshape(len(diff), -1).sum(-1) / d
        loss_sum = (per_row * mask).sum()
        return {"err_output": err.astype(jnp.float32),
                "n_err": jnp.float32(0.0),
                "loss_sum": loss_sum.astype(jnp.float32),
                "count": n.astype(jnp.float32)}

    def run(self) -> None:
        numpy_mode = self.device is None or not self.device.is_jax
        if numpy_mode:
            m = self.metrics_fn(self.input.map_read(),
                                self.target.map_read(),
                                self.mask.map_read())
            self.err_output.reset(m["err_output"])
            self.n_err.reset(np.float32([m["n_err"]]))
            self.loss.reset(np.float32([m["loss_sum"]]))
            self.count.reset(np.float32([m["count"]]))
        else:
            if self._compiled is None:
                self._compiled = self.device.compile(self.metrics_fn)
            m = self._compiled(self.input.unmap(), self.target.unmap(),
                               self.mask.unmap())
            self.err_output.devmem = m["err_output"]
            self.n_err.devmem = m["n_err"]
            self.loss.devmem = m["loss_sum"]
            self.count.devmem = m["count"]
