"""RBM pretraining family: Binarization, the RBM unit, and its CD-k
trainer (k Gibbs steps traced into the fused dispatch; k=1 default).

Reference parity: veles/znicz/rbm_units.py (SURVEY.md §3.2 "RBM /
other" row — reconstructed from the survey description, UNVERIFIED
against the reference mount, which is empty; SURVEY.md §0).  Upstream
decomposes contrastive divergence into many small units (Binarization,
BatchWeights, GradientsCalculator, WeightsUpdater) because each maps to
one OpenCL kernel.  That decomposition is kernel-shaped, not
math-shaped, so the TPU rebuild folds the whole CD-1 step into the
standard ForwardUnit/GradientUnit contract instead:

- ``RBM`` (forward): given visible v0, computes h0 = sigmoid(v0 W + b_h)
  and the mean-field reconstruction v1 = sigmoid(h0 W^T + b_v).  Its
  ``output`` is the RECONSTRUCTION, so the stock ``EvaluatorMSE``
  (with ``targets_from_data`` loaders) reports reconstruction error and
  Decision/Snapshotter/plotters work unchanged.  The hidden
  representation for stacking DBN layers is exposed as ``hidden_of()``
  / the ``hidden`` Vector (eager mode).
- ``GDRBM`` (gradient): IGNORES err_output — contrastive divergence is
  not backprop.  From the forward residuals it samples h0 ~
  Bernoulli(h0_prob), reconstructs v1 = sigmoid(h0 W^T + b_v), computes
  h1 = sigmoid(v1 W + b_h), and returns the CD-1 gradients
  ``-(positive - negative)/n`` so the shared SGD/momentum
  ``update_params`` (nn_units.py) ASCENDS the likelihood proxy.  One
  array-API implementation serves the numpy oracle and the fused jitted
  scan; the stochastic keys thread through the standard
  ``stochastic=True`` residual contract, so two seeded runs are
  bit-identical.
- ``Binarization``: stochastic Bernoulli sampling of [0,1] activations
  (upstream feeds binarized pixels to the RBM); deterministic >0.5
  threshold in eval mode; gradient passes through unchanged (straight
  -through estimator — upstream never backprops through it at all).

Known, documented divergence: CD statistics average over the full
static minibatch, including the padded remainder rows of the last
minibatch of an epoch (the evaluator masks them out of the METRICS;
the reference trained with fixed-size minibatches where the issue
cannot arise).  The pollution is bounded by pad_rows/minibatch for one
minibatch per epoch.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from veles_tpu.memory import Vector
from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _sigmoid(v):
    if isinstance(v, np.ndarray):
        return 1.0 / (1.0 + np.exp(-v))
    import jax
    return jax.nn.sigmoid(v)


def _flat(x):
    return x.reshape(x.shape[0], -1)


class Binarization(ForwardUnit):
    """output ~ Bernoulli(input) in training, input > 0.5 in eval.
    Input values must lie in [0, 1] (pixel intensities)."""

    has_params = False
    stochastic = True

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        if isinstance(x, np.ndarray):
            return {"output": (x > 0.5).astype(np.float32)}
        return {"output": (x > 0.5).astype(x.dtype)}

    def apply_fwd(self, params, x, rng=None, train=True):
        if not train:
            y = self.apply(params, {"input": x})["output"]
            return y, (x, None)
        if isinstance(x, np.ndarray):
            from veles_tpu import prng as prng_mod
            gen = prng_mod.get("binarization").numpy
            y = (gen.random(x.shape) < x).astype(np.float32)
        else:
            import jax
            if rng is None:
                raise ValueError(f"{self.name}: traced train mode "
                                 "needs an rng key")
            y = jax.random.bernoulli(rng, x).astype(x.dtype)
        return y, (x, None)

    def eager_rng(self):
        if self.device is not None and self.device.is_jax:
            from veles_tpu import prng as prng_mod
            return prng_mod.get("binarization").next_key()
        return None


class GDBinarization(GradientUnit):
    """Straight-through: err passes unchanged (upstream has no backward
    for Binarization — it only feeds RBM pretraining)."""

    def backward_from_saved(self, params, saved, err_output):
        return err_output, {}


class RBM(ForwardUnit):
    """Bernoulli-Bernoulli RBM layer.

    params: ``weights`` (n_visible, n_hidden), ``bias`` (n_hidden,)
    — the hidden bias, reusing the base attribute so weight-image
    plotters and Forge export see it — and ``vbias`` (n_visible,).
    ``output`` is the mean-field reconstruction (same shape as input);
    ``hidden`` holds h0 probabilities after an eager firing.
    """

    activation_mode = "linear"  # err routing is GDRBM's business
    stochastic = True           # CD sampling keys ride the residual

    def __init__(self, workflow=None, n_hidden: int = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if not n_hidden:
            raise ValueError(f"{self.name}: n_hidden required")
        self.n_hidden = int(n_hidden)
        self.vbias = Vector(name=f"{self.name}.vbias")
        self.hidden = Vector(name=f"{self.name}.hidden")

    def output_shape_for(self, input_shape):
        return tuple(input_shape)   # reconstruction

    def param_shapes(self, input_shape):
        n_vis = int(np.prod(input_shape[1:]))
        return {"weights": (n_vis, self.n_hidden),
                "bias": (self.n_hidden,),
                "vbias": (n_vis,)}

    def param_vectors(self) -> Dict[str, Vector]:
        p = super().param_vectors()
        if self.vbias:
            p["vbias"] = self.vbias
        return p

    # -- pure compute --------------------------------------------------

    def hidden_of(self, params, x):
        """h probabilities — the representation stacked DBN layers
        consume."""
        return _sigmoid(_flat(x) @ params["weights"] + params["bias"])

    def reconstruct(self, params, h):
        return _sigmoid(h @ params["weights"].T + params["vbias"])

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        h0 = self.hidden_of(params, x)
        v1 = self.reconstruct(params, h0).reshape(x.shape)
        return {"output": v1, "hidden": h0}

    def apply_fwd(self, params, x, rng=None, train=True):
        out = self.apply(params, {"input": x}, rng)
        # residual carries the rng key: GDRBM's CD sampling must be
        # deterministic per (seed, step) like dropout's mask
        return out["output"], (x, out["hidden"], rng)

    def jax_run(self) -> None:
        params = self.gather_params()
        x = self.input.unmap()
        out = self.apply(params, {"input": x}, rng=None)
        self._last_residual = (x, out["hidden"], self.eager_rng())
        self.output.devmem = out["output"]
        self.hidden.devmem = out["hidden"]

    def numpy_run(self) -> None:
        params = {k: np.asarray(v) for k, v in self.gather_params().items()}
        x = self.input.map_read()
        out = self.apply(params, {"input": x})
        self._last_residual = (x, out["hidden"], None)
        self.output.map_invalidate()[:] = out["output"]
        if not self.hidden:
            self.hidden.mem = np.zeros(out["hidden"].shape, np.float32)
            self.hidden.initialize(self.device)
        self.hidden.map_invalidate()[:] = np.asarray(out["hidden"])

    def eager_rng(self):
        if self.device is not None and self.device.is_jax:
            from veles_tpu import prng as prng_mod
            return prng_mod.get("rbm").next_key()
        return None


class GDRBM(GradientUnit):
    """CD-k trainer for :class:`RBM`.  err_output is ignored (CD is
    not backprop); err_input is zeros — an RBM is pretrained as the
    first layer of its workflow, nothing upstream consumes its error.

    ``cd_k`` (layer config ``"<-": {"cd_k": k}``) runs k Gibbs steps
    per update, all TRACED into the one fused dispatch: the chain's
    Bernoulli draws thread per (seed, step) keys — the residual's rng
    key for the first sample (bitwise-identical to the historical
    CD-1 at k=1) and ``fold_in(rng, t)`` for every later step t, so
    two seeded runs of any k are bit-identical.  The numpy twin draws
    the same chain sequentially from the ``rbm`` stream."""

    def __init__(self, workflow=None, forward=None, cd_k: int = 1,
                 **kwargs: Any) -> None:
        super().__init__(workflow, forward=forward, **kwargs)
        self.cd_k = int(cd_k)
        if self.cd_k < 1:
            raise ValueError(f"{self.name}: cd_k must be >= 1, got "
                             f"{self.cd_k}")

    def backward_from_saved(self, params, saved, err_output):
        x, h0_prob, rng = saved
        v0 = _flat(x)
        n = v0.shape[0]
        k = self.cd_k
        numpy_mode = isinstance(v0, np.ndarray)
        if numpy_mode:
            from veles_tpu import prng as prng_mod
            gen = prng_mod.get("rbm").numpy

            def sample(t, p):
                return (gen.random(p.shape) < p).astype(np.float32)
        else:
            import jax
            if rng is None:
                raise ValueError(f"{self.name}: traced CD-{k} needs "
                                 "the forward's rng key in the "
                                 "residual")

            def sample(t, p):
                key = rng if t == 0 else jax.random.fold_in(rng, t)
                return jax.random.bernoulli(key, p).astype(v0.dtype)

        f = self.forward
        # k Gibbs steps, unrolled into the trace: h_t ~ Bern(p(h_t)),
        # v_{t+1} = mean-field reconstruction, p(h_{t+1}) = sigmoid.
        # Only the LAST step's (v, h_prob) feeds the negative phase
        # (Hinton's CD-k estimator with a mean-field final half-step).
        h = sample(0, h0_prob)
        v1 = h1 = None
        for t in range(k):
            v1 = f.reconstruct(params, h)
            h1 = _sigmoid(v1 @ params["weights"] + params["bias"])
            if t + 1 < k:
                h = sample(t + 1, h1)
        # update_params does w -= lr*g: negate so SGD ASCENDS the
        # CD objective (positive phase - negative phase)
        grads = {
            "weights": -(v0.T @ h0_prob - v1.T @ h1) / n,
            "bias": -(h0_prob - h1).sum(axis=0) / n,
            "vbias": -(v0 - v1).sum(axis=0) / n,
        }
        if numpy_mode:
            err_in = np.zeros(x.shape, np.float32)
        else:
            import jax.numpy as jnp
            err_in = jnp.zeros(x.shape, x.dtype)
        return err_in, grads
