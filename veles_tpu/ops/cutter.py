"""Cutter: crop a spatial window out of NHWC input.

Reference parity: veles/znicz/cutter.py (SURVEY.md §3.2 "RBM / other"
row — reconstructed from the survey description, UNVERIFIED against
the reference mount, which is empty; SURVEY.md §0).  Upstream cuts a
fixed region from each sample (e.g. the center crop feeding an
autoencoder); backward scatters the error back into a zero canvas.

TPU-first: forward is a static slice, backward a static pad — both
shapes are compile-time constants, so XLA fuses them into neighbours
for free (no dynamic-shape hazard inside the scanned step).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


class Cutter(ForwardUnit):
    """output = input[:, top:top+height, left:left+width, :]."""

    has_params = False

    def __init__(self, workflow=None, padding=None, **kwargs: Any) -> None:
        """``padding=(left, top, right, bottom)`` — the amounts cut off
        each edge (the reference's convention)."""
        super().__init__(workflow, **kwargs)
        if padding is None or len(padding) != 4:
            raise ValueError(f"{self.name}: padding=(left, top, right, "
                             f"bottom) required")
        self.padding = tuple(int(p) for p in padding)

    def output_shape_for(self, input_shape):
        n, h, w = input_shape[0], input_shape[1], input_shape[2]
        left, top, right, bottom = self.padding
        oh, ow = h - top - bottom, w - left - right
        if oh <= 0 or ow <= 0:
            raise ValueError(f"{self.name}: padding {self.padding} "
                             f"consumes the whole {h}x{w} input")
        return (n, oh, ow) + tuple(input_shape[3:])

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        left, top, right, bottom = self.padding
        return {"output": x[:, top:x.shape[1] - bottom,
                            left:x.shape[2] - right]}


class GDCutter(GradientUnit):
    """err_input = err_output zero-padded back to the input canvas."""

    def backward_from_saved(self, params, saved, err_output):
        x, _out = saved
        left, top, right, bottom = self.forward.padding
        pad = [(0, 0), (top, bottom), (left, right)] + \
            [(0, 0)] * (err_output.ndim - 3)
        if isinstance(err_output, np.ndarray):
            err_in = np.pad(err_output, pad)
        else:
            import jax.numpy as jnp
            err_in = jnp.pad(err_output, pad)
        return err_in, {}
