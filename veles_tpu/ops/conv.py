"""Convolution units.

Reference parity: veles/znicz/conv.py (``Conv``, ``ConvTanh``,
``ConvRELU``) and veles/znicz/gd_conv.py (``GradientDescentConv`` +
variants).  The reference runs hand-written im2col-style OpenCL/CUDA
kernels; here the TPU path is a single ``lax.conv_general_dilated`` —
XLA tiles it onto the MXU directly — and the backward pass is derived
with ``jax.vjp`` of the pre-activation (XLA emits the transposed-conv
and filter-gradient convs; inside the fused trace CSE merges the
recomputed forward with the outer one).  The numpy golden path is an
explicit im2col / col2im implementation, giving the tests an
independent oracle for gradient checks.

Layout: NHWC activations, HWIO weights — the TPU-native choice.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def conv_out_size(n: int, k: int, pad: int, stride: int) -> int:
    return (n + 2 * pad - k) // stride + 1


# -- numpy im2col helpers (golden path) --------------------------------

def im2col(x: np.ndarray, ky: int, kx: int, pad: Tuple[int, int],
           stride: Tuple[int, int]) -> np.ndarray:
    """(B,H,W,C) -> (B,OH,OW,ky,kx,C) patch view (zero-padded copy)."""
    b, h, w, c = x.shape
    py, px = pad
    sy, sx = stride
    xp = np.pad(x, ((0, 0), (py, py), (px, px), (0, 0)))
    oh = conv_out_size(h, ky, py, sy)
    ow = conv_out_size(w, kx, px, sx)
    sb, sh, sw, sc = xp.strides
    shape = (b, oh, ow, ky, kx, c)
    strides = (sb, sh * sy, sw * sx, sh, sw, sc)
    return np.lib.stride_tricks.as_strided(xp, shape, strides,
                                           writeable=False)


def col2im(cols: np.ndarray, in_shape: Tuple[int, ...],
           pad: Tuple[int, int], stride: Tuple[int, int]) -> np.ndarray:
    """Scatter-add (B,OH,OW,ky,kx,C) patches back to (B,H,W,C)."""
    b, h, w, c = in_shape
    py, px = pad
    sy, sx = stride
    _, oh, ow, ky, kx, _ = cols.shape
    out = np.zeros((b, h + 2 * py, w + 2 * px, c), cols.dtype)
    for iy in range(ky):
        for ix in range(kx):
            out[:, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :] += \
                cols[:, :, :, iy, ix, :]
    return out[:, py:py + h, px:px + w, :]


class Conv(ForwardUnit):
    """2-D convolution, NHWC x HWIO -> NHWC."""

    activation_mode = "linear"

    def __init__(self, workflow=None, n_kernels: int = None,  # type: ignore
                 kx: int = 3, ky: int = 3,
                 padding: Any = 0, sliding: Any = 1,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError(f"{self.name}: n_kernels required")
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.padding = _pair(padding)   # (pad_y, pad_x)
        self.sliding = _pair(sliding)   # (stride_y, stride_x)

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        py, px = self.padding
        sy, sx = self.sliding
        return (b, conv_out_size(h, self.ky, py, sy),
                conv_out_size(w, self.kx, px, sx), self.n_kernels)

    def param_shapes(self, input_shape):
        c = input_shape[-1]
        shapes = {"weights": (self.ky, self.kx, c, self.n_kernels)}
        if self.include_bias:
            shapes["bias"] = (self.n_kernels,)
        return shapes

    # -- compute -------------------------------------------------------

    def _s2d_eligible(self, c_in: int) -> bool:
        """Space-to-depth rewrite pays only for strided convs over few
        channels (a first layer reading pixels): the MXU's reduction
        tile is 128+ deep and a C_in=3 conv leaves it almost empty.
        Blocking trades stride for channels (3 -> s*s*3)."""
        sy, sx = self.sliding
        return (sy > 1 or sx > 1) and c_in * sy * sx <= 128

    def _conv_s2d(self, w, x):
        """Strided conv as a stride-1 conv over space-to-depth blocks.

        Exact rewrite (not an approximation): pad the kernel to a
        multiple of the stride, then fold each (sy, sx) input block
        into channels; the zero-padded kernel rows/cols kill every
        contribution from pad junk, so outputs match
        ``lax.conv_general_dilated`` bit-for-bit in f32 and the vjp
        routes gradients back to the original HWIO layout through the
        (cheap, fusable) reshapes.  The standard TPU first-layer
        treatment (cf. MLPerf ResNet space-to-depth).
        """
        import jax.numpy as jnp
        from jax import lax
        b, h, wd, c = x.shape
        py, px = self.padding
        sy, sx = self.sliding
        ky, kx = self.ky, self.kx
        kyb, kxb = -(-ky // sy), -(-kx // sx)    # kernel size in blocks
        oh = conv_out_size(h, ky, py, sy)
        ow = conv_out_size(wd, kx, px, sx)
        th, tw = (oh + kyb - 1) * sy, (ow + kxb - 1) * sx
        xp = jnp.pad(x, ((0, 0), (py, max(0, th - h - py)),
                         (px, max(0, tw - wd - px)), (0, 0)))
        xp = xp[:, :th, :tw]
        xs = xp.reshape(b, th // sy, sy, tw // sx, sx, c) \
            .transpose(0, 1, 3, 2, 4, 5) \
            .reshape(b, th // sy, tw // sx, sy * sx * c)
        wp = jnp.pad(w, ((0, kyb * sy - ky), (0, kxb * sx - kx),
                         (0, 0), (0, 0)))
        ws = wp.reshape(kyb, sy, kxb, sx, c, self.n_kernels) \
            .transpose(0, 2, 1, 3, 4, 5) \
            .reshape(kyb, kxb, sy * sx * c, self.n_kernels)
        return lax.conv_general_dilated(
            xs, ws, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def pre_activation(self, params, x):
        if isinstance(x, np.ndarray):
            patches = im2col(x, self.ky, self.kx, self.padding,
                             self.sliding)
            b, oh, ow = patches.shape[:3]
            w2 = params["weights"].reshape(-1, self.n_kernels)
            v = patches.reshape(b, oh, ow, -1) @ w2
        elif (os.environ.get("VELES_TPU_CONV_S2D", "0") != "0"
              and self._s2d_eligible(x.shape[-1])):
            v = self._conv_s2d(params["weights"], x)
        else:
            from jax import lax
            py, px = self.padding
            v = lax.conv_general_dilated(
                x, params["weights"],
                window_strides=self.sliding,
                padding=((py, py), (px, px)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if "bias" in params:
            v = v + params["bias"]
        return v

    def activation(self, v):
        return v

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        return {"output": self.activation(
            self.pre_activation(params, inputs["input"]))}


class ConvTanh(Conv):
    activation_mode = "tanh"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            return np.tanh(v)
        import jax.numpy as jnp
        return jnp.tanh(v)


class ConvRELU(Conv):
    activation_mode = "relu"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            return np.maximum(v, 0)
        import jax.numpy as jnp
        return jnp.maximum(v, 0)


class GradientDescentConv(GradientUnit):
    """Backward for Conv* (reference: veles/znicz/gd_conv.py)."""

    can_skip_err_input = True

    def backward_from_saved(self, params, saved, err_output,
                            need_err_input=True):
        x, out = saved
        err_pre = self.act_deriv(out, err_output)
        f = self.forward
        if isinstance(err_output, np.ndarray):
            patches = im2col(x, f.ky, f.kx, f.padding, f.sliding)
            b, oh, ow = patches.shape[:3]
            pf = patches.reshape(b * oh * ow, -1)
            ef = err_pre.reshape(b * oh * ow, f.n_kernels)
            grads = {"weights": (pf.T @ ef).reshape(
                f.ky, f.kx, x.shape[-1], f.n_kernels)}
            if "bias" in params:
                grads["bias"] = err_pre.sum(axis=(0, 1, 2))
            if not need_err_input:
                return None, grads
            # err_input: scatter err_pre @ W^T back through the windows
            cols = (ef @ params["weights"].reshape(-1, f.n_kernels).T) \
                .reshape(b, oh, ow, f.ky, f.kx, x.shape[-1])
            err_input = col2im(cols, x.shape, f.padding, f.sliding)
            return err_input, grads
        import jax

        if not need_err_input:
            _, vjp = jax.vjp(lambda p: f.pre_activation(p, x), params)
            (grads,) = vjp(err_pre)
            return None, grads

        def pre(p, xx):
            return f.pre_activation(p, xx)

        _, vjp = jax.vjp(pre, params, x)
        grads, err_input = vjp(err_pre)
        return err_input, grads


GDConvTanh = GradientDescentConv
GDConvRELU = GradientDescentConv
