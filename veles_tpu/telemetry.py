"""Sightline: the unified telemetry core — metrics registry, span
tracing, and the run journal.

Until this module existed, every subsystem grew its own ad-hoc
telemetry attributes (``FusedStepRunner.stream_transfer_bytes``,
``ChipEvaluatorPool.hangs_detected``, ``GeneticOptimizer.eval_count``)
and bench.py scraped them field by field — nothing could answer the
questions the roadmap's serving/scaling items are graded on (p50/p99
latency, sustained throughput) without bespoke instrumentation per
experiment.  Sightline is the read-side twin of the Faultline
injection registry (veles_tpu/faults.py): NAMED instrumentation
points, armed through ONE inherited environment variable, near-free
when idle.

Three primitives, one process-wide registry:

- **Counter** — a monotonic number (``counter("fused.dispatches")
  .inc()``).  Float-valued counters accumulate seconds/bytes.
- **Gauge** — a last-write-wins level (``gauge("ga.last_hang_wait")
  .set(3.1)``).
- **Histogram** — fixed log-spaced buckets (32 per decade over
  [1e-7, 1e7)), so p50/p90/p99 are computed exactly from the bucket
  counts with geometric in-bucket interpolation — no sample retention,
  O(1) memory, <= ~7.5% worst-case relative quantile error (typically
  far less), bucket-exact min/max/count/sum.  ``record()`` costs one
  ``log10`` + a list increment.

**Spans** (``with span("fused.dispatch"):``) are nestable (a
thread-local stack) and feed the histogram of the same name; spans
opened with ``journal=True`` also append an event line.

**The journal** is an append-only JSONL file of notable run events
(``event("ga.hang_detected", kind=...)``): hang detections, restarts,
OOM degradations, snapshot fallbacks, epoch ends — the replayable
timeline a post-mortem reads next to the quantile tables.  Hot-path
metrics never journal; events are for state transitions.

**Persistence**: when ``$VELES_METRICS_DIR`` is set (or
``configure(dir)`` ran — which exports the variable so every child
process inherits it, exactly like ``VELES_FAULTS``), each process
appends its journal to ``journal-<pid>.jsonl`` and flushes a cumulative
registry snapshot to ``metrics-<pid>.json`` via the PR-6 tempfile +
``os.replace`` discipline (a reader NEVER sees a torn file).  Flushes
happen on journal events (throttled), at exit, and wherever a
subsystem calls ``flush()`` explicitly (the serve-mode GA evaluator
flushes after every job so a kill -9 loses at most one genome's
numbers).  ``ChipEvaluatorPool`` merges a dead/closed evaluator
child's snapshot back into the parent registry
(``adopt_child_snapshot``), so a GA run yields ONE aggregate view
across its process tree; ``scripts/obs_report.py`` renders a metrics
dir into the human-readable summary.

Telemetry must never take down a run: file errors drop the sink and
keep counting in memory; ``set_enabled(False)`` reduces every call to
one module-attribute load + falsy check (bench.py measures the on/off
delta as ``telemetry_overhead_pct``).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import math
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from veles_tpu.analysis import witness

ENV_DIR = "VELES_METRICS_DIR"

#: histogram bucket layout: log-spaced, 32 per decade over
#: [10^LOG_LO, 10^LOG_HI); bucket 0 is the underflow bin (x < lo,
#: including zero/negative), the last is overflow.  32/decade bounds
#: the relative quantile error at 10^(1/32)-1 ~ 7.5% worst case
#: (geometric interpolation typically lands within ~2%).
LOG_LO = -7
LOG_HI = 7
PER_DECADE = 32
NBUCKETS = (LOG_HI - LOG_LO) * PER_DECADE
_STEP = 10.0 ** (1.0 / PER_DECADE)

#: global kill switch — when False every record/inc/event is one
#: attribute load + falsy check (the bench's overhead probe)
_enabled = True


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class Counter:
    """Monotonic counter (int or float increments)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0
        self._lock = witness.lock("telemetry.counter")

    def inc(self, n: float = 1) -> None:
        if not _enabled:
            return
        with self._lock:
            self.value += n

    def _reset(self) -> None:
        with self._lock:
            self.value = 0


class Gauge:
    """Last-write-wins level; ``value`` is None until first set."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        if not _enabled:
            return
        self.value = v

    def _reset(self) -> None:
        self.value = None


class Histogram:
    """Fixed log-spaced buckets; exact-from-buckets quantiles.

    No samples are retained: ``record`` increments one bucket and the
    exact count/sum/min/max scalars.  ``quantile(q)`` walks the
    cumulative bucket counts to the target rank and interpolates
    geometrically inside the selected bucket, clamped to the exact
    observed [min, max] — deterministic, O(buckets), and mergeable
    across processes by bucket-wise addition.
    """

    __slots__ = ("name", "count", "sum", "min", "max", "buckets",
                 "exemplars", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: List[int] = [0] * (NBUCKETS + 2)
        #: bucket index -> last trace_id that landed there (tail
        #: exemplars: "why is p99 high" jumps straight to a trace)
        self.exemplars: Dict[int, str] = {}
        self._lock = witness.lock("telemetry.histogram")

    @staticmethod
    def _index(x: float) -> int:
        if x < 10.0 ** LOG_LO:
            return 0
        if x >= 10.0 ** LOG_HI:
            return NBUCKETS + 1
        i = int((math.log10(x) - LOG_LO) * PER_DECADE)
        return 1 + min(NBUCKETS - 1, max(0, i))

    def record(self, x: float,
               exemplar: Optional[str] = None) -> None:
        """Record one sample; ``exemplar`` (a trace_id) is retained
        per landing bucket, last-wins, so the tail buckets always
        name a renderable trace (Flightline's p99 exemplars)."""
        if not _enabled:
            return
        x = float(x)
        with self._lock:
            self.count += 1
            self.sum += x
            if x < self.min:
                self.min = x
            if x > self.max:
                self.max = x
            i = self._index(x)
            self.buckets[i] += 1
            if exemplar is not None:
                self.exemplars[i] = exemplar

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (q in [0, 1]) from the bucket counts."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.buckets):
            if not c:
                continue
            if cum + c >= target:
                if i == 0:
                    return self.min
                if i == NBUCKETS + 1:
                    return self.max
                e0 = 10.0 ** (LOG_LO + (i - 1) / PER_DECADE)
                frac = (target - cum) / c
                v = e0 * (_STEP ** frac)
                return min(max(v, self.min), self.max)
            cum += c
        return self.max

    def snapshot_buckets(self) -> Any:
        """An opaque (bucket counts, count) base for
        :meth:`delta_quantile` — take one per window edge."""
        with self._lock:
            return list(self.buckets), self.count

    def delta_quantile(self, base: Any, q: float,
                       min_count: int = 1) -> Optional[float]:
        """The q-quantile of ONLY the samples recorded since ``base``
        (a :meth:`snapshot_buckets` result) — the windowed read the
        fleet sentinel's adaptive hedge threshold uses: a cumulative
        quantile lags the live distribution badly when load shifts,
        so hedging against it fires on far more than the intended
        tail.  None when fewer than ``min_count`` samples landed in
        the window."""
        base_buckets, base_count = base
        with self._lock:
            delta = [b - p for b, p in zip(self.buckets,
                                           base_buckets)]
            n = self.count - base_count
        if n < max(1, min_count):
            return None
        target = max(1, math.ceil(q * n))
        cum = 0
        for i, c in enumerate(delta):
            if c <= 0:
                continue
            if cum + c >= target:
                if i == 0:
                    return 10.0 ** LOG_LO
                if i == NBUCKETS + 1:
                    return 10.0 ** LOG_HI
                e0 = 10.0 ** (LOG_LO + (i - 1) / PER_DECADE)
                frac = (target - cum) / c
                return e0 * (_STEP ** frac)
            cum += c
        return None

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            d: Dict[str, Any] = {
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "buckets": {str(i): c
                            for i, c in enumerate(self.buckets) if c},
            }
            if self.exemplars:
                d["exemplars"] = {str(i): t
                                  for i, t in self.exemplars.items()}
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            d[key] = self.quantile(q)
        return d

    def merge_dict(self, d: Dict[str, Any]) -> None:
        """Bucket-wise merge of a snapshot dict (quantile keys in the
        dict are ignored — they are recomputed from the buckets)."""
        with self._lock:
            self.count += int(d.get("count", 0))
            self.sum += float(d.get("sum", 0.0))
            if d.get("min") is not None:
                self.min = min(self.min, float(d["min"]))
            if d.get("max") is not None:
                self.max = max(self.max, float(d["max"]))
            for i, c in (d.get("buckets") or {}).items():
                self.buckets[int(i)] += int(c)
            for i, t in (d.get("exemplars") or {}).items():
                self.exemplars.setdefault(int(i), str(t))

    def _reset(self) -> None:
        with self._lock:
            self.count = 0
            self.sum = 0.0
            self.min = math.inf
            self.max = -math.inf
            self.buckets = [0] * (NBUCKETS + 2)
            self.exemplars = {}


class Registry:
    """A namespace of metrics.  The module-level singleton is the
    process registry; standalone instances serve offline merging
    (scripts/obs_report.py)."""

    def __init__(self) -> None:
        self._lock = witness.lock("telemetry.registry")
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            with self._lock:
                c = self.counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            with self._lock:
                g = self.gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.setdefault(name, Histogram(name))
        return h

    def snapshot(self) -> Dict[str, Any]:
        """The registry as one JSON-ready dict (cumulative totals)."""
        return {
            "counters": {n: c.value for n, c in
                         sorted(self.counters.items()) if c.value},
            "gauges": {n: g.value for n, g in
                       sorted(self.gauges.items())
                       if g.value is not None},
            "histograms": {n: h.to_dict() for n, h in
                           sorted(self.histograms.items()) if h.count},
        }

    def merge_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fold another process's cumulative snapshot in: counters and
        histogram buckets ADD; gauges fill only where this registry has
        no value (a level from another process must not clobber a live
        local one)."""
        for n, v in (snap.get("counters") or {}).items():
            self.counter(n).inc(v)
        for n, v in (snap.get("gauges") or {}).items():
            if self.gauge(n).value is None:
                self.gauge(n).value = v
        for n, d in (snap.get("histograms") or {}).items():
            self.histogram(n).merge_dict(d)

    def reset(self) -> None:
        """Zero every metric IN PLACE — object identity is preserved,
        so call sites holding a Counter/Histogram reference stay wired
        to the registry (tests reset between cases)."""
        for c in self.counters.values():
            c._reset()
        for g in self.gauges.values():
            g._reset()
        for h in self.histograms.values():
            h._reset()


#: the process registry
_registry = Registry()

#: in-memory ring of recent journal events (tests and drills read this
#: even with no metrics dir configured)
_recent: "deque[Dict[str, Any]]" = deque(maxlen=4096)

_dir: Optional[str] = os.environ.get(ENV_DIR) or None
_journal_file = None
_journal_lock = witness.lock("telemetry.journal")
_last_flush = 0.0
FLUSH_EVERY = 5.0

_tls = threading.local()


def counter(name: str) -> Counter:
    return _registry.counter(name)


def gauge(name: str) -> Gauge:
    return _registry.gauge(name)


def histogram(name: str) -> Histogram:
    return _registry.histogram(name)


def metrics_dir() -> Optional[str]:
    return _dir


def configure(metrics_dir: Optional[str]) -> None:
    """Point the persistence layer at ``metrics_dir`` (None tears it
    down).  Exports ``$VELES_METRICS_DIR`` so child processes (GA
    evaluators, multihost peers) inherit the arming — one flag covers
    the whole process tree, the Faultline convention."""
    global _dir, _journal_file
    with _journal_lock:
        if _journal_file is not None:
            try:
                _journal_file.close()
            except OSError:
                pass
            _journal_file = None
    _dir = metrics_dir or None
    if _dir:
        os.makedirs(_dir, exist_ok=True)
        os.environ[ENV_DIR] = _dir
    else:
        os.environ.pop(ENV_DIR, None)


def _journal(rec: Dict[str, Any]) -> None:
    global _journal_file
    if not _dir:
        return
    with _journal_lock:
        if _journal_file is None:
            try:
                os.makedirs(_dir, exist_ok=True)
                _journal_file = open(
                    os.path.join(_dir,
                                 f"journal-{os.getpid()}.jsonl"),
                    "a", buffering=1 << 16)
            except OSError:
                return
        try:
            _journal_file.write(json.dumps(rec) + "\n")
            # notable state transitions hit the disk IMMEDIATELY (the
            # pre-Flightline per-line behavior: a concurrent reader —
            # chaos drills poll live journals — sees them at once).
            # Only the per-request ``trace.*`` family rides the file
            # buffer: a write() syscall per hop is exactly the serving
            # overhead the tracing gate bounds, its readers are
            # offline assemblers, and the crash tail lives in the
            # flight-recorder ring anyway.  The buffer drains on the
            # next notable event, the periodic background flush, or
            # shutdown.
            if not str(rec.get("event", "")).startswith("trace."):
                _journal_file.flush()
        except (OSError, ValueError):
            # full/vanished disk or closed handle: observability must
            # never take down the run — drop the sink, keep the ring
            try:
                _journal_file.close()
            except OSError:
                pass
            _journal_file = None


#: the Flightline seam: a zero-arg callable returning (trace_id,
#: span_id) when a sampled trace context is parked on this thread,
#: else None — veles_tpu/trace.py registers it at import so every
#: journal event inside ``trace.use(ctx)`` auto-carries its trace
_trace_provider: Optional[Any] = None


def set_trace_provider(fn: Optional[Any]) -> None:
    global _trace_provider
    _trace_provider = fn


def event(name: str, **fields: Any) -> None:
    """Append one journal event (and keep it in the in-memory ring).
    Events are for notable state transitions, not per-dispatch data —
    histograms carry the hot-path distributions.  Every event carries
    a ``mono`` monotonic stamp next to ``ts`` so cross-process merges
    can skew-correct the interleaving (obs.py), plus ``trace``/
    ``span`` when the thread runs under a sampled trace context
    (explicit caller fields win over the provider's)."""
    if not _enabled:
        return
    rec: Dict[str, Any] = {"ts": round(time.time(), 3),
                           "mono": round(time.monotonic(), 6),
                           "event": name}
    rec.update(fields)
    if _trace_provider is not None:
        try:
            t = _trace_provider()
        except Exception:  # noqa: BLE001 — tracing must never break
            t = None       # the journal
        if t is not None:
            rec.setdefault("trace", t[0])
            rec.setdefault("span", t[1])
    _recent.append(rec)
    _journal(rec)
    _maybe_flush()


def recent_events(name: Optional[str] = None) -> List[Dict[str, Any]]:
    evs = list(_recent)
    if name is not None:
        evs = [e for e in evs if e.get("event") == name]
    return evs


@contextlib.contextmanager
def span(name: str, journal: bool = False, **fields: Any):
    """Time a block into ``histogram(name)``.  Spans nest through a
    thread-local stack (``span_stack()``); ``journal=True`` also emits
    an event at exit carrying the duration, the parent span, and the
    caller's fields."""
    if not _enabled:
        yield name
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    parent = stack[-1] if stack else None
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield name
    finally:
        dt = time.perf_counter() - t0
        if stack and stack[-1] == name:
            stack.pop()
        histogram(name).record(dt)
        if journal:
            event(name, seconds=round(dt, 6), parent=parent,
                  depth=len(stack), **fields)


def span_stack() -> List[str]:
    """The current thread's open spans, outermost first."""
    return list(getattr(_tls, "stack", []) or [])


def snapshot() -> Dict[str, Any]:
    snap = _registry.snapshot()
    snap["pid"] = os.getpid()
    snap["ts"] = round(time.time(), 3)
    return snap


def merge_snapshot(snap: Dict[str, Any]) -> None:
    _registry.merge_snapshot(snap)


def flush() -> Optional[str]:
    """Write this process's cumulative snapshot atomically to
    ``metrics-<pid>.json`` (tempfile + ``os.replace`` — a concurrent
    reader always parses a complete file).  No-op (None) when no
    metrics dir is configured; never raises."""
    global _last_flush
    d = _dir
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"metrics-{os.getpid()}.json")
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(snapshot(), f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        with _journal_lock:
            if _journal_file is not None:
                _journal_file.flush()
        witness.write_snapshot(d)
        _last_flush = time.monotonic()
        return path
    except OSError:
        return None


def _maybe_flush() -> None:
    # the throttled flush runs OFF the emitting thread: a full
    # snapshot write costs 1-3ms, and paying it synchronously inside
    # event() put a once-per-FLUSH_EVERY stall squarely into the
    # serving p99 whenever tracing (or any per-request journaling)
    # was on — the Flightline overhead gate caught it
    global _last_flush
    if _dir and time.monotonic() - _last_flush > FLUSH_EVERY:
        _last_flush = time.monotonic()   # even on failure: no storms

        def _bg() -> None:
            try:
                flush()
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
        threading.Thread(target=_bg, daemon=True,
                         name="telemetry-flush").start()


def maybe_flush() -> None:
    """Throttled flush (at most once per FLUSH_EVERY seconds): the
    periodic pulse long-lived serving processes call from their
    heartbeat loop, so the on-disk snapshot (and the lock-witness
    table riding on it) stays fresh even when no journal event fires
    — a SIGKILLed replica then leaves observations at most one
    heartbeat window stale."""
    _maybe_flush()


def adopt_child_snapshot(pid: int) -> bool:
    """Merge a child process's ``metrics-<pid>.json`` into this
    registry and rename it ``*.merged`` so offline aggregation
    (obs_report) cannot double-count it.  Returns True when a file was
    merged.  The parent's next flush then carries the aggregate."""
    d = _dir
    if not d:
        return False
    path = os.path.join(d, f"metrics-{pid}.json")
    if not os.path.isfile(path):
        return False
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return False
    merge_snapshot(snap)
    try:
        os.replace(path, path + ".merged")
    except OSError:
        pass
    return True


def reset() -> None:
    """Zero every metric in place, clear the event ring, drop the
    journal handle, and re-read the environment arming — the test
    fixture's clean-slate hook.  Live Counter/Histogram references
    held by long-lived objects stay valid (they are zeroed, not
    replaced)."""
    global _dir, _journal_file, _last_flush
    _registry.reset()
    _recent.clear()
    with _journal_lock:
        if _journal_file is not None:
            try:
                _journal_file.close()
            except OSError:
                pass
            _journal_file = None
    _dir = os.environ.get(ENV_DIR) or None
    _last_flush = 0.0
    if getattr(_tls, "stack", None):
        _tls.stack = []


atexit.register(flush)
