"""Vector: the twin host/device buffer abstraction.

Reference parity: veles/memory.py — ``Vector`` holds a numpy host array
(``.mem``) and a device buffer (``.devmem``), kept coherent through an
explicit protocol: ``map_read()`` (host needs to read), ``map_write()``
(host will read+write), ``map_invalidate()`` (host will fully
overwrite), ``unmap()`` (device needs the latest data).

TPU-first design: ``devmem`` is a ``jax.Array`` in HBM.  Unlike OpenCL
mapped pointers, JAX arrays are immutable — so "device writes" happen by
REBINDING ``devmem`` to a step function's output (with the input buffer
donated, giving in-place update semantics in HBM; SURVEY.md §7 "in-place
weight updates").  The map/unmap protocol survives as the host-coherence
contract, and its invariant checks catch stale-host-read bugs that the
reference's assertions caught.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

HOST = 1
DEVICE = 2


class Vector:
    """Host numpy array + optional device ``jax.Array``, explicitly
    synchronized."""

    def __init__(self, data: Optional[np.ndarray] = None,
                 name: str = "") -> None:
        self.name = name
        self._mem: Optional[np.ndarray] = None
        self._devmem: Any = None
        self._valid = 0
        self.device = None
        if data is not None:
            self.mem = data

    # -- allocation ----------------------------------------------------

    @property
    def mem(self) -> Optional[np.ndarray]:
        return self._mem

    @mem.setter
    def mem(self, value: Optional[np.ndarray]) -> None:
        if value is None:
            self._mem = None
            self._valid = 0
            return
        self._mem = np.ascontiguousarray(value)
        self._valid = HOST

    def reset(self, new_mem: Optional[np.ndarray] = None) -> None:
        self._devmem = None
        self.mem = new_mem

    def drop_devmem(self) -> None:
        """Free the HBM copy only.  Any VALID host copy survives; if
        the device held the only valid copy the vector truly reads as
        unallocated — the stale host array is dropped too, so nothing
        (pickling, plotters, __bool__ guards) can serve outdated
        values.  Callers that need the data must map_read() first."""
        self._devmem = None
        self._valid &= HOST
        if not self._valid:
            self._mem = None

    def __bool__(self) -> bool:
        return self._mem is not None or self._devmem is not None

    @property
    def shape(self) -> Tuple[int, ...]:
        if self._mem is not None:
            return self._mem.shape
        if self._devmem is not None:
            return tuple(self._devmem.shape)
        raise AttributeError(f"Vector '{self.name}' not allocated")

    @property
    def dtype(self):
        if self._mem is not None:
            return self._mem.dtype
        return np.dtype(self._devmem.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def nbytes(self) -> int:
        """Bytes of the freshest buffer — what residency budgeting and
        transfer accounting charge.  Dtype-preserving end to end: a
        quantized uint8 dataset reports 1 byte/element here, uploads
        at 1 byte/element (``Device.put``), and sits in HBM at 1
        byte/element — a quarter of its float32 view."""
        if self._mem is not None:
            return int(self._mem.nbytes)
        if self._devmem is not None:
            return int(np.dtype(self._devmem.dtype).itemsize
                       * int(np.prod(tuple(self._devmem.shape))))
        return 0

    @property
    def sample_size(self) -> int:
        """Elements per leading-axis sample (reference: Vector.sample_size)."""
        s = self.shape
        return int(np.prod(s[1:])) if len(s) > 1 else 1

    def __len__(self) -> int:
        return self.shape[0]

    # -- device attach -------------------------------------------------

    def initialize(self, device, upload: bool = True) -> None:
        """Attach to a device; pushes host data to HBM on jax devices.

        ``upload=False`` attaches WITHOUT the eager host->device push —
        for scratch buffers (unit outputs, err_inputs, host minibatch
        staging) that every consumer either rebinds (``devmem = step
        output``) or overwrites before reading.  Correctness is
        unchanged (``unmap()`` still uploads on demand); what it avoids
        is streaming gigabytes of just-allocated zeros through a thin
        tunnel at initialize time, which measured as the bulk of the
        benchmark's 239s build dead time (round-4 VERDICT next #4)."""
        self.device = device
        if upload and device is not None and device.is_jax \
                and self._mem is not None:
            self.unmap()

    def upload_row_sharded(self, device) -> None:
        """Attach + upload with the leading axis row-sharded 1/N per
        mesh device (``MeshJaxDevice.put_sharded``: rows zero-padded
        to a whole per-device tile).  The host copy STAYS valid —
        ``map_read`` keeps serving the unpadded host rows and
        snapshots carry them — while ``unmap``/``current`` hand
        consumers the sharded (padded) device buffer.  For read-only
        buffers (resident datasets): a later host write + ``unmap``
        would re-upload REPLICATED through the normal path."""
        self.device = device
        self._devmem = device.put_sharded(self._mem)
        self._valid = HOST | DEVICE

    @property
    def devmem(self) -> Any:
        return self._devmem

    @devmem.setter
    def devmem(self, value: Any) -> None:
        """Rebind the device buffer (a jitted step's output) and mark the
        host copy stale — the TPU analogue of a device-side write."""
        self._devmem = value
        self._valid = DEVICE if value is not None else (self._valid & HOST)

    # -- coherence protocol -------------------------------------------

    def map_read(self) -> np.ndarray:
        """Host is about to read: copy device->host if host is stale."""
        if not self._valid & HOST:
            if self._devmem is None:
                raise RuntimeError(f"Vector '{self.name}': nothing valid")
            self._mem = np.asarray(self._devmem)
            self._valid |= HOST
        return self._mem

    def map_write(self) -> np.ndarray:
        """Host will read and write: sync down, then device is stale."""
        m = self.map_read()
        self._valid = HOST
        return m

    def map_invalidate(self) -> np.ndarray:
        """Host will fully overwrite: no sync down, device is stale."""
        if self._mem is None:
            if self._devmem is None:
                raise RuntimeError(f"Vector '{self.name}': nothing valid")
            self._mem = np.empty(self.shape, self.dtype)
        self._valid = HOST
        return self._mem

    def current(self) -> Any:
        """Freshest buffer without forcing a transfer: the device array
        when one is bound (possibly an un-fetched step output), else the
        host array.  Callers that need numpy use ``np.asarray`` on the
        result (that is the sync point)."""
        return self._devmem if self._devmem is not None else self._mem

    def unmap(self) -> Any:
        """Device is about to compute: push host->device if device stale.
        Returns the device buffer (or host mem on numpy devices)."""
        if self.device is None or not self.device.is_jax:
            return self._mem
        if not self._valid & DEVICE:
            if self._mem is None:
                raise RuntimeError(f"Vector '{self.name}': nothing valid")
            self._devmem = self.device.put(self._mem)
            self._valid = HOST | DEVICE
        return self._devmem

    # -- snapshot support ---------------------------------------------

    def __getstate__(self) -> dict:
        if self._valid and not (self._valid & HOST):
            self.map_read()
        return {"name": self.name, "mem": self._mem}

    def __setstate__(self, state: dict) -> None:
        self.name = state["name"]
        self._mem = state["mem"]
        self._devmem = None
        self.device = None
        self._valid = HOST if self._mem is not None else 0

    def __repr__(self) -> str:
        shape = None
        try:
            shape = self.shape
        except AttributeError:
            pass
        return f"Vector('{self.name}', shape={shape}, valid={self._valid})"
