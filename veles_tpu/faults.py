"""Faultline: deterministic, seeded fault injection for long-run
rehearsal.

Every failure mode this framework has survived so far (evaluator
death mid-genome, the torn compile-cache entries, barren restart
loops) was discovered *by accident* after a crash.  This registry
turns them into drills: injection points are NAMED, armed from ONE
environment variable — so child evaluator / multihost processes
inherit the arming for free — and compile to a near-zero no-op when
unset (one module attribute load + falsy check per call site).

Arming syntax (``VELES_FAULTS``)::

    VELES_FAULTS="evaluator.hang@seq=1&silent=1,stream.corrupt_file@index=7"

- entries are comma-separated; each is ``point[@qual=val[&qual=val...]]``
- a qualifier matches when the call site passed a context key of that
  name whose ``str()`` equals the value; a qualifier the call site
  did not supply NEVER matches (so ``@gen=2`` is inert at call sites
  that do not know the generation)
- the KNOB names ``times``/``seconds``/``silent``/``after`` never
  participate in matching: ``times=N`` caps how often the entry fires
  (default 1 — one injection per process; ``times=*`` = unlimited)
  and the rest ride along on the returned payload dict for the call
  site to read (hang duration, heartbeat silencing, exit delay)

Registered points (the call sites document their context keys):

==========================  ==========================================
``evaluator.hang``          serve-mode evaluator stalls mid-genome
                            (``job``/``seq``/``gen``; knobs:
                            ``seconds`` sleep, ``silent`` stops
                            heartbeats too)
``evaluator.garbage_line``  evaluator emits a non-JSON protocol line
``stream.corrupt_file``     image decode raises as if the file were
                            torn (``index``/``path``)
``snapshot.torn_write``     save_workflow's temp file is truncated
                            before the atomic rename (``path``)
``checkpoint.corrupt``      the GA generation checkpoint is truncated
                            (``gen``)
``device.oom_on_put``       a device upload raises RESOURCE_EXHAUSTED
                            (``site`` = resident_dataset / stream /
                            cohort)
``multihost.peer_exit``     this process hard-exits after multihost
                            init (``process``; knob: ``after`` secs)
``preempt.sigterm``         this process sends ITSELF a real SIGTERM
                            (``attempt``/``mode``; knob: ``after``
                            secs) — rehearses a preemption notice; the
                            graceful-stop path must snapshot and exit
                            14 inside the grace deadline
``supervisor.child_crash``  this process hard-dies via SIGKILL
                            (``attempt``/``gen``/``site``) — rehearses
                            an unannounced crash the supervisor must
                            resume from the newest intact state
``hive.slow_dispatch``      a serving micro-batch dispatch stalls
                            (``label`` = model name; knob: ``seconds``
                            per dispatch) — the gray-failure replica
                            that drags fleet p99 without dying
``hive.wedge``              a serving request is swallowed unanswered
                            while heartbeats and stats keep flowing
                            (``model``) — wedged batcher, healthy-
                            looking process
``hive.garbage_response``   a serving response's probability payload
                            is replaced with deterministic garbage
                            AFTER the integrity checksum was computed
                            from the clean payload (``model``) — the
                            router's crc echo must catch it
``online.poison_batch``     tapped ground-truth labels are scrambled
                            deterministically before they enter the
                            replay buffer (``model``/``slot`` =
                            train / holdout) — the promotion gate's
                            held-out slice must catch the poisoned
                            shadow and never promote it
``online.swap_mid_request`` the promotion gate stalls between gate
                            decision and the atomic param swap
                            (``model``; knob: ``seconds``) while live
                            dispatches race it — every answer must
                            stay oracle-clean (old params or new,
                            never torn)
``fleet.replica_flap``      a hive replica SIGKILLs itself seconds
                            after sending hello — on EVERY respawn,
                            when armed with ``times=*`` (``replica``;
                            knob: ``after`` secs) — the flapping
                            replica that must drive the respawn
                            backoff up instead of hot-looping spawns,
                            and must never trick the scale controller
                            into a spawn storm
==========================  ==========================================

Determinism: the registry carries no clock and no global RNG — an
entry fires on exactly the calls its qualifiers select, in call
order, and ``garbage()``/``rng()`` derive their bytes from
``VELES_FAULTS_SEED`` (default 0) + the point name, so two armed runs
inject identical faults with identical garbage.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Any, Dict, List, Optional

ENV_VAR = "VELES_FAULTS"
SEED_ENV_VAR = "VELES_FAULTS_SEED"

#: every valid injection-point name — ``arm()`` rejects unknown points
#: so a typo'd drill fails loudly instead of silently injecting nothing
POINTS = frozenset((
    "evaluator.hang",
    "evaluator.garbage_line",
    "stream.corrupt_file",
    "snapshot.torn_write",
    "checkpoint.corrupt",
    "device.oom_on_put",
    "multihost.peer_exit",
    "preempt.sigterm",
    "supervisor.child_crash",
    "hive.slow_dispatch",
    "hive.wedge",
    "hive.garbage_response",
    "online.poison_batch",
    "online.swap_mid_request",
    "fleet.replica_flap",
))

_log = logging.getLogger("veles_tpu.faults")

#: qualifier names that are knobs for the call site, not matchers —
#: ``evaluator.hang@seq=1&silent=1`` matches on ``seq`` only and
#: hands ``silent`` to the injection site via the payload
KNOBS = frozenset(("times", "seconds", "silent", "after"))


class FaultSpec:
    """One armed entry: a point name, its match qualifiers, and a
    remaining-fire budget."""

    __slots__ = ("point", "quals", "remaining")

    def __init__(self, point: str, quals: Dict[str, str],
                 times: int) -> None:
        self.point = point
        self.quals = quals
        #: fires left; -1 = unlimited
        self.remaining = times

    def matches(self, ctx: Dict[str, Any]) -> bool:
        if self.remaining == 0:
            return False
        for k, v in self.quals.items():
            if k in KNOBS:
                continue
            if k not in ctx or str(ctx[k]) != v:
                return False
        return True

    def __repr__(self) -> str:
        qs = "&".join(f"{k}={v}" for k, v in self.quals.items())
        return f"FaultSpec({self.point}@{qs} remaining={self.remaining})"


#: armed specs by point name; EMPTY when disarmed — the fast path
_specs: Dict[str, List[FaultSpec]] = {}


def parse(spec_str: str) -> Dict[str, List[FaultSpec]]:
    """Parse an arming string into specs (see module docstring)."""
    specs: Dict[str, List[FaultSpec]] = {}
    for entry in spec_str.split(","):
        entry = entry.strip()
        if not entry:
            continue
        point, _, quals_s = entry.partition("@")
        point = point.strip()
        if point not in POINTS:
            raise ValueError(
                f"{ENV_VAR}: unknown injection point {point!r} "
                f"(known: {sorted(POINTS)})")
        quals: Dict[str, str] = {}
        times = 1
        if quals_s:
            for q in quals_s.split("&"):
                k, sep, v = q.partition("=")
                if not sep:
                    raise ValueError(
                        f"{ENV_VAR}: qualifier {q!r} of {point} is not "
                        f"key=value")
                if k == "times":
                    times = -1 if v in ("*", "inf") else int(v)
                else:
                    quals[k.strip()] = v.strip()
        specs.setdefault(point, []).append(FaultSpec(point, quals, times))
    return specs


def arm(spec_str: Optional[str] = None) -> None:
    """(Re)arm the registry: from ``spec_str``, or from the
    environment when None.  ``arm("")`` disarms.  Drills and tests use
    this to inject in-process; production arming is the env var at
    process start (module import calls ``arm(None)``)."""
    global _specs
    if spec_str is None:
        spec_str = os.environ.get(ENV_VAR, "")
    _specs = parse(spec_str) if spec_str else {}
    if _specs:
        _log.warning("FAULT INJECTION ARMED: %s",
                     {p: [repr(s) for s in ss]
                      for p, ss in _specs.items()})


def active() -> bool:
    """True when any fault is armed (cheap pre-check for call sites
    that need to assemble expensive context)."""
    return bool(_specs)


def fire(point: str, **ctx: Any) -> Optional[Dict[str, str]]:
    """Should this call site inject?  Returns the matched entry's
    qualifier payload (always truthy: includes ``point``) and consumes
    one fire from its budget; None when disarmed or unmatched.

    Disarmed cost: one global load + one falsy check.
    """
    if not _specs:
        return None
    for spec in _specs.get(point, ()):
        if spec.matches(ctx):
            if spec.remaining > 0:
                spec.remaining -= 1
            _log.warning("FAULT INJECTED: %s ctx=%r", point, ctx)
            payload = {"point": point}
            payload.update(spec.quals)
            return payload
    return None


def seed() -> int:
    return int(os.environ.get(SEED_ENV_VAR, "0"))


def rng(point: str):
    """A numpy Generator seeded from (VELES_FAULTS_SEED, point) — the
    deterministic randomness source for injected garbage."""
    import numpy as np
    return np.random.default_rng(
        (seed() << 32) ^ zlib.crc32(point.encode()))


def garbage(n: int = 48, point: str = "garbage") -> bytes:
    """``n`` deterministic garbage bytes for ``point``."""
    return rng(point).integers(0, 256, size=n, dtype="uint8").tobytes()


def garbage_text(n: int = 48, point: str = "garbage") -> str:
    """A deterministic printable NON-JSON garbage line (protocol-tear
    simulation: never parses, never empty, no newline)."""
    import string
    alphabet = string.ascii_letters + string.digits + "#%&*<>|"
    idx = rng(point).integers(0, len(alphabet), size=n)
    return "\x15" + "".join(alphabet[i] for i in idx)


def hang(seconds: float = 3600.0) -> None:
    """The canonical injected hang: sleep in 1s slices (so an external
    kill lands promptly) for ``seconds``."""
    import time
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        time.sleep(min(1.0, max(0.0, deadline - time.monotonic())))


def maybe_inject_sigterm(**ctx: Any) -> None:
    """Faultline ``preempt.sigterm``: deliver a REAL SIGTERM to this
    process (after ``after`` seconds on a timer thread) so drills can
    rehearse a preemption notice end to end — the installed
    graceful-stop handler must snapshot and exit 14 within
    ``$VELES_PREEMPT_GRACE``.  Call sites pass ``attempt`` (the
    supervisor's ``$VELES_SUPERVISE_ATTEMPT``) so a resumed child is
    not re-preempted."""
    f = fire("preempt.sigterm", **ctx)
    if not f:
        return
    import signal
    import threading
    import time as _time
    delay = float(f.get("after", 0.0))

    def _term() -> None:
        if delay > 0:
            _time.sleep(delay)
        os.kill(os.getpid(), signal.SIGTERM)

    threading.Thread(target=_term, daemon=True,
                     name="fault-preempt-sigterm").start()


def maybe_inject_child_crash(**ctx: Any) -> None:
    """Faultline ``supervisor.child_crash``: hard-kill this process
    with SIGKILL — no handlers, no snapshot, no atexit; the supervisor
    must resume the run from the newest intact snapshot / GA
    checkpoint.  Call sites pass ``attempt``/``gen`` so the drill can
    target exactly one crash."""
    if fire("supervisor.child_crash", **ctx):
        import signal
        import sys as _sys
        try:
            # the flight recorder's whole reason to exist: SIGKILL
            # skips atexit and the telemetry flush, so the ring is
            # written NOW or never (lazy import — faults loads before
            # almost everything, and a failed dump must not soften
            # the crash being rehearsed)
            from veles_tpu import trace as _trace
            _trace.dump("sigkill")
        except Exception:  # noqa: BLE001
            pass
        _sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    """Torn-write simulation: keep only the leading fraction of
    ``path`` (at least 1 byte, strictly less than the whole)."""
    size = os.path.getsize(path)
    keep = max(1, min(size - 1, int(size * keep_fraction)))
    os.truncate(path, keep)


# arm from the environment at import: children of an armed process
# inherit the env var, so one export covers the whole process tree
arm(None)
