"""ctypes bridge to the native C++ inference runtime.

Builds ``native/libveles_tpu.so`` on demand (make + g++; no pybind11 in
this environment — the C API in native/include/veles_c.h is the ABI)
and wraps it in a numpy-friendly ``NativeModel``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libveles_tpu.so")
_lib: Optional[ctypes.CDLL] = None


def ensure_built(force: bool = False) -> str:
    """Build the shared library if missing or older than its sources."""
    src = os.path.join(_NATIVE_DIR, "src", "libveles.cc")
    hdr = os.path.join(_NATIVE_DIR, "include", "veles_c.h")
    if not force and os.path.exists(_LIB_PATH):
        newest_src = max(os.path.getmtime(src), os.path.getmtime(hdr))
        if os.path.getmtime(_LIB_PATH) >= newest_src:
            return _LIB_PATH
    proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}")
    return _LIB_PATH


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.veles_load.restype = ctypes.c_void_p
        lib.veles_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.veles_free.argtypes = [ctypes.c_void_p]
        lib.veles_input_rank.restype = ctypes.c_int
        lib.veles_input_rank.argtypes = [ctypes.c_void_p]
        lib.veles_input_dims.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        lib.veles_output_size.restype = ctypes.c_int64
        lib.veles_output_size.argtypes = [ctypes.c_void_p]
        lib.veles_num_ops.restype = ctypes.c_int
        lib.veles_num_ops.argtypes = [ctypes.c_void_p]
        lib.veles_run.restype = ctypes.c_int
        lib.veles_run.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
        _lib = lib
    return _lib


class NativeModel:
    """A VTPN model executed by the C++ runtime."""

    def __init__(self, path: str) -> None:
        lib = _load_lib()
        err = ctypes.create_string_buffer(256)
        self._handle = lib.veles_load(path.encode(), err, len(err))
        if not self._handle:
            raise ValueError(
                f"veles_load({path!r}): {err.value.decode() or 'failed'}")
        self._lib = lib
        rank = lib.veles_input_rank(self._handle)
        dims = (ctypes.c_int64 * rank)()
        lib.veles_input_dims(self._handle, dims)
        self.input_shape = tuple(int(d) for d in dims)
        self.output_size = int(lib.veles_output_size(self._handle))
        self.num_ops = int(lib.veles_num_ops(self._handle))

    def run(self, x: np.ndarray) -> np.ndarray:
        """Forward a batch: (B, *input_shape) float32 -> (B, out)."""
        x = np.ascontiguousarray(x, np.float32)
        if tuple(x.shape[1:]) != self.input_shape:
            raise ValueError(f"input sample shape {x.shape[1:]} != "
                             f"model's {self.input_shape}")
        batch = x.shape[0]
        out = np.empty((batch, self.output_size), np.float32)
        rc = self._lib.veles_run(
            self._handle,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), batch,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise RuntimeError(f"veles_run failed with code {rc}")
        return out

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.veles_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:  # interpreter teardown
            pass
