"""Flightline: fleet-wide causal tracing + the crash-proof flight
recorder.

Sightline (veles_tpu/telemetry.py) answers "how fast" per process;
this module answers "what happened to THIS request" after Swarm fans
it across replicas, Sentinel hedges it, the batcher coalesces it, and
Evergreen's tap feeds it into a replay buffer.  Two pieces:

- **TraceContext** — ``(trace_id, span_id, parent_id, sampled)``,
  minted ONCE at the Swarm router's admission edge (``mint()``,
  head-based sampling at ``$VELES_TRACE_SAMPLE`` via the tap.py
  error-diffusion idiom, so the sampled fraction is exact) and
  propagated on every wire hop through the ``trace``/``span``/
  ``parent``/``sampled`` JSONL fields (registered in
  serve/protocol.py; veleslint's trace-wire-key rule pins
  :data:`WIRE_FIELDS` to that registry).  Each hop derives its own
  span with :meth:`TraceContext.child`; ``use(ctx)`` parks the
  context thread-locally so every journaled telemetry event inside
  the block auto-carries ``trace``/``span`` (telemetry's trace
  provider seam) — cross-process assembly then merges the
  ``journal-*.jsonl`` files by trace_id (``veles_tpu/obs.py``).

- **The flight recorder** — a fixed-size in-memory ring of recent
  spans/events (``record()``, no I/O on the hot path, ALWAYS armed),
  dumped to ``flightrec-<pid>-<n>-<reason>.json`` in the metrics dir
  (``dump()``, tempfile + ``os.replace``) on SIGTERM drains, injected
  SIGKILL crashes (faults.py), sentinel ejections, and promotion-gate
  verdicts — so every ejection and rollback ships with the trace tail
  that explains it, even when the process never got to flush.

Tracing must never take down serving: an unsampled context costs one
attribute check per hop, ``record()`` is a deque append, and
``dump()`` swallows OSError.
"""

from __future__ import annotations

import binascii
import contextlib
import json
import os
import re
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from veles_tpu import events, knobs, telemetry
from veles_tpu.analysis import witness

#: wire-protocol field names carrying trace context.  Every member
#: MUST be registered in veles_tpu/serve/protocol.py — veleslint's
#: ``trace-wire-key`` rule statically cross-checks this tuple against
#: the wire-key registry (zero waivers).
K_TRACE = "trace"
K_SPAN = "span"
K_PARENT = "parent"
K_SAMPLED = "sampled"
WIRE_FIELDS = ("trace", "span", "parent", "sampled")


def _hex(nbytes: int) -> str:
    return binascii.hexlify(os.urandom(nbytes)).decode()


def new_span_id() -> str:
    """A fresh 8-hex span id for an event that LINKS several traces
    instead of belonging to one (the batcher's coalesced dispatch)."""
    return _hex(4)


class TraceContext:
    """One causal hop: the trace (whole request tree), this hop's
    span, the span that caused it, and the head-sampling bit."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id or _hex(4)
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def child(self) -> "TraceContext":
        """A new span under this one (same trace, same sampling)."""
        return TraceContext(self.trace_id, _hex(4), self.span_id,
                            self.sampled)

    def fields(self) -> Dict[str, Any]:
        """Journal-ready ``trace``/``span``/``parent`` fields."""
        d: Dict[str, Any] = {"trace": self.trace_id,
                             "span": self.span_id}
        if self.parent_id:
            d["parent"] = self.parent_id
        return d

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_id} sampled={self.sampled})")


# -- minting (the router's admission edge) -----------------------------

_mint_lock = witness.lock("trace.mint")
_acc = 0.0


def mint(environ: Optional[Dict[str, str]] = None) -> TraceContext:
    """Mint a ROOT context.  Head-based sampling by error diffusion
    (``acc += rate; sample when acc >= 1``, the tap.py idiom): the
    sampled fraction of any request stream is exactly
    ``$VELES_TRACE_SAMPLE``, not a coin flip — the overhead bench
    compares rate 0 vs 1 on identical traffic."""
    global _acc
    rate = max(0.0, min(1.0, float(knobs.get(knobs.TRACE_SAMPLE,
                                             environ))))
    with _mint_lock:
        _acc += rate
        sampled = _acc >= 1.0
        if sampled:
            _acc -= 1.0
    return TraceContext(_hex(8), _hex(4), None, sampled)


# -- the thread-local current context ----------------------------------

_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context parked on this thread (None outside ``use``)."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]):
    """Park ``ctx`` thread-locally for the block: journaled telemetry
    events inside auto-carry its trace/span (the provider seam), and
    ``record()`` stamps ring entries with it."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


# -- wire propagation --------------------------------------------------

def to_wire(msg: Dict[str, Any],
            ctx: Optional[TraceContext]) -> Dict[str, Any]:
    """Stamp ``ctx`` onto an outgoing wire message IN PLACE.  Only a
    SAMPLED context rides the wire — rate 0 adds zero bytes per hop
    (the overhead baseline)."""
    if ctx is not None and ctx.sampled:
        msg[K_TRACE] = ctx.trace_id
        msg[K_SPAN] = ctx.span_id
        if ctx.parent_id:
            msg[K_PARENT] = ctx.parent_id
        msg[K_SAMPLED] = 1
    return msg


def from_wire(job: Dict[str, Any]) -> Optional[TraceContext]:
    """The sender's context read off an incoming message (None when
    the hop carried none).  The receiver's own work should run under
    ``use(from_wire(job).child())`` so its spans parent correctly."""
    tid = job.get(K_TRACE)
    if not tid:
        return None
    return TraceContext(str(tid), str(job.get(K_SPAN) or _hex(4)),
                        job.get(K_PARENT),
                        bool(job.get(K_SAMPLED, 1)))


# -- the flight recorder -----------------------------------------------

_rec_lock = witness.lock("trace.flightrec")
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=max(16, int(knobs.get(knobs.FLIGHTREC_CAP))))
_dump_seq = 0


def record(name: str, ctx: Optional[TraceContext] = None,
           **fields: Any) -> None:
    """Append one entry to the always-armed ring.  No I/O — the hot
    path (per request leg, per batch dispatch) may call this freely;
    the entry only leaves memory on ``dump()``."""
    rec: Dict[str, Any] = {"ts": round(time.time(), 3),
                           "mono": round(time.monotonic(), 6),
                           "ev": name}
    c = ctx if ctx is not None else current()
    if c is not None:
        rec["trace"] = c.trace_id
        rec["span"] = c.span_id
    rec.update(fields)
    with _rec_lock:
        _ring.append(rec)


def ring_entries() -> Tuple[Dict[str, Any], ...]:
    """The ring's current entries, oldest first (tests/drills)."""
    with _rec_lock:
        return tuple(_ring)


def dump(reason: str,
         metrics_dir: Optional[str] = None) -> Optional[str]:
    """Write the ring + the telemetry journal ring tail to
    ``flightrec-<pid>-<n>-<reason>.json`` (tempfile + ``os.replace``
    — a reader never sees a torn dump).  Returns the path, or None
    when no metrics dir is configured or the write failed — a dump
    must never take the dying process down harder."""
    global _dump_seq
    d = metrics_dir or telemetry.metrics_dir()
    if not d:
        return None
    reason = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason)) or "dump"
    with _rec_lock:
        _dump_seq += 1
        seq = _dump_seq
        ring = list(_ring)
    payload = {"pid": os.getpid(), "reason": reason,
               "ts": round(time.time(), 3),
               "mono": round(time.monotonic(), 6),
               "ring": ring,
               "journal_tail": telemetry.recent_events()[-256:]}
    path = os.path.join(d, f"flightrec-{os.getpid()}-{seq}-"
                           f"{reason}.json")
    try:
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".",
            suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
    except (OSError, ValueError, TypeError):
        return None
    telemetry.event(events.EV_FLIGHTREC_DUMP, reason=reason,
                    entries=len(ring), path=os.path.basename(path))
    return path


# -- the telemetry provider seam ---------------------------------------

def _provider() -> Optional[Tuple[str, str]]:
    c = current()
    if c is None or not c.sampled:
        return None
    return c.trace_id, c.span_id


telemetry.set_trace_provider(_provider)
