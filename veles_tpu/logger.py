"""Logging mixin.

Reference parity: veles/logger.py — a ``Logger`` mixin every unit
inherits, giving per-instance named loggers with colored console output.
The reference's optional MongoDB event sink (a durable, queryable
record of run events) has no database in the TPU environment; its
equivalent here is ``add_jsonl_sink`` — every log record appended as
one JSON line to a file (grep/jq replace mongo queries) — built on the
generic ``event_hooks`` seam.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Callable, List

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"

#: Hooks called with every LogRecord (the reference's MongoDB sink seam).
event_hooks: List[Callable[[logging.LogRecord], None]] = []


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


class _HookHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        # copy: a failing hook may unregister itself mid-iteration
        for hook in list(event_hooks):
            hook(record)


def add_jsonl_sink(path: str) -> Callable[[], None]:
    """Append every ``veles.*`` log record to ``path`` as one JSON
    line (the reference's MongoDB event sink, file-shaped).  Returns a
    detach function that unregisters the hook and closes the file."""
    f = open(path, "a", buffering=1)

    def hook(record: logging.LogRecord) -> None:
        try:
            f.write(json.dumps({
                "ts": round(record.created or time.time(), 3),
                "level": record.levelname,
                "unit": record.name,
                "message": record.getMessage(),
            }) + "\n")
        except (ValueError, OSError):
            # closed file / full or vanished disk: the event record is
            # an observability aid — it must never take down the run.
            # Drop the sink and keep training on the console handler.
            if hook in event_hooks:
                event_hooks.remove(hook)

    event_hooks.append(hook)

    def detach() -> None:
        if hook in event_hooks:
            event_hooks.remove(hook)
        f.close()

    return detach


_configured = False


def setup_logging(level: int = logging.INFO) -> None:
    global _configured
    rootlog = logging.getLogger("veles")
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s",
                            datefmt="%H:%M:%S")
        )
        rootlog.addHandler(handler)
        rootlog.addHandler(_HookHandler())
        rootlog.propagate = False
        _configured = True
    rootlog.setLevel(level)


class Logger:
    """Mixin giving ``self.info/debug/warning/error`` with a per-class
    (or per-unit, once ``name`` exists) logger name."""

    @property
    def logger(self) -> logging.Logger:
        name = getattr(self, "name", None) or type(self).__name__
        return logging.getLogger(f"veles.{name}")

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)
