"""Logging mixin.

Reference parity: veles/logger.py — a ``Logger`` mixin every unit
inherits, giving per-instance named loggers with colored console output.
The optional MongoDB event sink of the reference is out of scope (no
database in the TPU environment); an in-process event hook list covers
the same observability need.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, List

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"

#: Hooks called with every LogRecord (the reference's MongoDB sink seam).
event_hooks: List[Callable[[logging.LogRecord], None]] = []


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


class _HookHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        for hook in event_hooks:
            hook(record)


_configured = False


def setup_logging(level: int = logging.INFO) -> None:
    global _configured
    rootlog = logging.getLogger("veles")
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s",
                            datefmt="%H:%M:%S")
        )
        rootlog.addHandler(handler)
        rootlog.addHandler(_HookHandler())
        rootlog.propagate = False
        _configured = True
    rootlog.setLevel(level)


class Logger:
    """Mixin giving ``self.info/debug/warning/error`` with a per-class
    (or per-unit, once ``name`` exists) logger name."""

    @property
    def logger(self) -> logging.Logger:
        name = getattr(self, "name", None) or type(self).__name__
        return logging.getLogger(f"veles.{name}")

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)
