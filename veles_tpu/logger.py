"""Logging mixin.

Reference parity: veles/logger.py — a ``Logger`` mixin every unit
inherits, giving per-instance named loggers with colored console output.
The reference's optional MongoDB event sink (a durable, queryable
record of run events) has no database in the TPU environment; its
equivalent here is ``add_jsonl_sink`` — every log record appended as
one JSON line to a file (grep/jq replace mongo queries) — built on the
generic ``event_hooks`` seam.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Callable, List

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"

#: Hooks called with every LogRecord (the reference's MongoDB sink seam).
event_hooks: List[Callable[[logging.LogRecord], None]] = []


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


class _HookHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        # copy: a failing hook may unregister itself mid-iteration
        for hook in list(event_hooks):
            hook(record)


def add_jsonl_sink(path: str) -> Callable[[], None]:
    """Append every ``veles.*`` log record to ``path`` as one JSON
    line (the reference's MongoDB event sink, file-shaped).  Returns a
    detach function that unregisters the hook and closes the file."""
    f = open(path, "a", buffering=1)

    def hook(record: logging.LogRecord) -> None:
        try:
            f.write(json.dumps({
                "ts": round(record.created or time.time(), 3),
                "level": record.levelname,
                "unit": record.name,
                "message": record.getMessage(),
            }) + "\n")
        except (ValueError, OSError):
            # closed file / full or vanished disk: the event record is
            # an observability aid — it must never take down the run.
            # Drop the sink and keep training on the console handler.
            if hook in event_hooks:
                event_hooks.remove(hook)

    event_hooks.append(hook)

    def detach() -> None:
        if hook in event_hooks:
            event_hooks.remove(hook)
        f.close()

    return detach


def _journal_hook(record: logging.LogRecord) -> None:
    """Route WARNING+ log records into the Sightline journal (and the
    Flightline ring), so the operator timeline interleaves log lines
    with telemetry events instead of living in a second file.  Lazy
    imports keep logger.py import-light; any failure drops the record
    from the journal only — the console handler already ran."""
    if record.levelno < logging.WARNING:
        return
    try:
        from veles_tpu import events as _ev
        from veles_tpu import telemetry as _tm
        from veles_tpu import trace as _tr
        _tm.event(_ev.EV_LOG_RECORD, level=record.levelname,
                  unit=record.name, message=record.getMessage())
        _tr.record("log." + record.levelname.lower(),
                   unit=record.name, message=record.getMessage())
    except Exception:  # noqa: BLE001 — observability must never
        pass           # take down the unit that logged


_configured = False


def setup_logging(level: int = logging.INFO) -> None:
    global _configured
    rootlog = logging.getLogger("veles")
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s",
                            datefmt="%H:%M:%S")
        )
        rootlog.addHandler(handler)
        rootlog.addHandler(_HookHandler())
        rootlog.propagate = False
        if _journal_hook not in event_hooks:
            event_hooks.append(_journal_hook)
        # the veles_tpu.* namespace (faults.py logs there) previously
        # reached stderr only via logging.lastResort; give it the same
        # hook seam PLUS an explicit WARNING stderr handler so adding
        # the journal route does not change what the console shows
        # (propagate stays True: pytest caplog and operator root
        # configs keep seeing these records)
        flog = logging.getLogger("veles_tpu")
        ferr = logging.StreamHandler(sys.stderr)
        ferr.setLevel(logging.WARNING)
        flog.addHandler(ferr)
        flog.addHandler(_HookHandler())
        _configured = True
    rootlog.setLevel(level)


class Logger:
    """Mixin giving ``self.info/debug/warning/error`` with a per-class
    (or per-unit, once ``name`` exists) logger name."""

    @property
    def logger(self) -> logging.Logger:
        name = getattr(self, "name", None) or type(self).__name__
        return logging.getLogger(f"veles.{name}")

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)
