"""Dataset acquisition: real files when present, deterministic synthetic
generators otherwise.

This environment has no network and ships no datasets (verified:
full-disk search found none), so the five BASELINE.json benchmark
configs run on procedurally generated stand-ins by default.  Each
generator is fully determined by (seed, sizes): per-class template
patterns plus per-sample jitter/noise, linearly separable enough that
the reference architectures reach their target accuracies, while
keeping realistic shapes (28x28x1, 32x32x3, 227x227x3).

If real data is placed under ``root.common.data_dir`` (default
``~/.veles_tpu/data``) — e.g. MNIST IDX files — the loaders pick it up
instead (reference behaviour: veles/loader downloads/caches datasets;
offline here, so files must be pre-placed).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

Split = Tuple[np.ndarray, np.ndarray]

#: (path-set description, error) per corrupt/foreign cache-file set
#: skipped this run — the bounded-degradation ledger: skipping into
#: the synthetic fallback is allowed, but never SILENTLY (Faultline)
_corrupt_cache_skips: list = []
_corrupt_cache_warned = False


def corrupt_cache_count() -> int:
    """Corrupt/foreign pre-placed dataset file sets skipped (and
    warned about) so far this run."""
    return len(_corrupt_cache_skips)


def _note_corrupt_cache(what: str, exc: Exception) -> None:
    global _corrupt_cache_warned
    _corrupt_cache_skips.append((what, f"{type(exc).__name__}: {exc}"))
    if not _corrupt_cache_warned:
        _corrupt_cache_warned = True
        import logging
        logging.getLogger("veles_tpu.datasets").warning(
            "corrupt/foreign pre-placed dataset files skipped — the "
            "run continues on the SYNTHETIC fallback, which is almost "
            "never what you want with real data present: %s (%s). "
            "Further corrupt sets are counted silently "
            "(datasets.corrupt_cache_count()).", what, exc)


def data_dir() -> str:
    from veles_tpu.config import root
    d = root.common.get("data_dir") if "common" in root else None
    return os.path.expanduser(d or "~/.veles_tpu/data")


# -- real MNIST (IDX format), if files are pre-placed ------------------

_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def write_idx(path: str, array: np.ndarray) -> None:
    """Write an array in IDX format (the MNIST container: big-endian
    magic = dtype 0x08 (ubyte) + ndim, then dims, then raw bytes)."""
    from veles_tpu.snapshotter import atomic_write
    arr = np.ascontiguousarray(array, np.uint8)
    with atomic_write(path, "wb") as f:
        f.write(struct.pack(">I", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def generate_mnist_idx(target_dir: Optional[str] = None,
                       n_train: int = 60000, n_test: int = 10000,
                       seed: int = 28281) -> str:
    """Materialize the synthetic MNIST stand-in AS REAL IDX FILES under
    ``<data_dir>/mnist`` so the real-file loading path is exercisable
    end-to-end offline (round-1 VERDICT missing #3).  If genuine MNIST
    IDX files are ever pre-placed there, they are left untouched."""
    base = target_dir or os.path.join(data_dir(), "mnist")
    os.makedirs(base, exist_ok=True)
    present = [f for f in _MNIST_FILES.values()
               if os.path.exists(os.path.join(base, f))
               or os.path.exists(os.path.join(base, f + ".gz"))]
    if len(present) == len(_MNIST_FILES):
        return base
    if present:
        # NEVER overwrite a partial genuine set with synthetic data —
        # the user must complete or remove it
        missing = sorted(set(_MNIST_FILES.values()) - set(present))
        raise FileExistsError(
            f"{base} holds a partial MNIST IDX set ({present}); "
            f"refusing to overwrite with the synthetic stand-in. "
            f"Add the missing files {missing} or remove the partial "
            f"set.")
    (tx, ty), (vx, vy), _ = synthetic_classification(
        n_train, n_test, (28, 28, 1), n_classes=10, seed=seed)
    write_idx(os.path.join(base, _MNIST_FILES["train_images"]),
              np.round(tx[..., 0] * 255.0))
    write_idx(os.path.join(base, _MNIST_FILES["train_labels"]), ty)
    write_idx(os.path.join(base, _MNIST_FILES["test_images"]),
              np.round(vx[..., 0] * 255.0))
    write_idx(os.path.join(base, _MNIST_FILES["test_labels"]), vy)
    return base


def try_load_real_mnist() -> Optional[Tuple[Split, Split]]:
    base = os.path.join(data_dir(), "mnist")
    paths = {}
    for key, fname in _MNIST_FILES.items():
        for cand in (os.path.join(base, fname),
                     os.path.join(base, fname + ".gz")):
            if os.path.exists(cand):
                paths[key] = cand
                break
        else:
            return None
    tx = _read_idx(paths["train_images"]).astype(np.float32) / 255.0
    ty = _read_idx(paths["train_labels"]).astype(np.int32)
    vx = _read_idx(paths["test_images"]).astype(np.float32) / 255.0
    vy = _read_idx(paths["test_labels"]).astype(np.int32)
    return (tx[..., None], ty), (vx[..., None], vy)


# -- real CIFAR-10 (binary / python-pickle batches), if pre-placed -----

_CIFAR10_TRAIN_BATCHES = [f"data_batch_{i}" for i in range(1, 6)]
_CIFAR10_TEST_BATCH = "test_batch"


def _cifar10_dirs() -> list:
    """Candidate roots for the batch files, in priority order: the
    upstream archive unpacks into cifar-10-batches-{bin,py}; files
    dropped directly under <data_dir>/cifar10 work too."""
    base = os.path.join(data_dir(), "cifar10")
    return [os.path.join(base, "cifar-10-batches-bin"),
            os.path.join(base, "cifar-10-batches-py"),
            base]


def _read_cifar10_bin(path: str) -> Split:
    """One binary-format batch: records of 1 label byte + 3072 image
    bytes (R, G, B planes, 32x32 row-major each)."""
    raw = np.fromfile(path, np.uint8)
    if raw.size == 0 or raw.size % 3073:
        raise ValueError(f"{path}: not a CIFAR-10 binary batch "
                         f"({raw.size} bytes)")
    rec = raw.reshape(-1, 3073)
    y = rec[:, 0].astype(np.int32)
    if y.max(initial=0) > 9:
        # a right-sized garbage/foreign file must trigger the synthetic
        # fallback, not feed labels up to 255 into a 10-class workflow
        raise ValueError(f"{path}: labels outside 0..9 — not CIFAR-10")
    x = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x, np.float32) / np.float32(255.0), y


def _read_cifar10_py(path: str) -> Split:
    """One python-pickle-format batch: dict with b'data' (N, 3072)
    uint8 and b'labels' (the upstream pickles are py2-era, so
    encoding='bytes')."""
    import pickle
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = np.asarray(d[b"data"] if b"data" in d else d["data"],
                      np.uint8)
    labels = d.get(b"labels", d.get("labels")) if hasattr(d, "get") \
        else None
    if data.ndim != 2 or data.shape[1] != 3072 or labels is None:
        raise ValueError(f"{path}: not a CIFAR-10 pickle batch")
    x = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y = np.asarray(labels, np.int32)
    if y.size and (y.min() < 0 or y.max() > 9):
        raise ValueError(f"{path}: labels outside 0..9 — not CIFAR-10")
    return np.ascontiguousarray(x, np.float32) / np.float32(255.0), y


def try_load_real_cifar10() -> Optional[Tuple[Split, Split]]:
    """((train_x, train_y), (test_x, test_y)) from pre-placed real
    CIFAR-10 batch files — binary (.bin) or python-pickle layout —
    under ``<data_dir>/cifar10``; None when absent.  Pixels float32 in
    [0, 1], HWC."""
    for root in _cifar10_dirs():
        if not os.path.isdir(root):
            continue
        for suffix, reader in ((".bin", _read_cifar10_bin),
                               ("", _read_cifar10_py)):
            names = [b + suffix for b in _CIFAR10_TRAIN_BATCHES] \
                + [_CIFAR10_TEST_BATCH + suffix]
            paths = [os.path.join(root, n) for n in names]
            if not all(os.path.isfile(p) for p in paths):
                continue
            import pickle
            try:
                splits = [reader(p) for p in paths]
            except (ValueError, KeyError, EOFError, TypeError,
                    OSError, pickle.UnpicklingError) as e:
                # corrupt/foreign files -> synthetic fallback, but
                # COUNTED and warned once per run, never silent
                _note_corrupt_cache(f"cifar10 batch set under {root}",
                                    e)
                continue
            tx = np.concatenate([s[0] for s in splits[:-1]])
            ty = np.concatenate([s[1] for s in splits[:-1]])
            return (tx, ty), splits[-1]
    return None


def generate_cifar10_batches(target_dir: Optional[str] = None,
                             n_train: int = 50000,
                             n_test: int = 10000,
                             seed: int = 32323) -> str:
    """Materialize the synthetic CIFAR-10 stand-in AS REAL
    BINARY-FORMAT BATCH FILES (data_batch_1..5.bin + test_batch.bin)
    under ``<data_dir>/cifar10/cifar-10-batches-bin`` so the real-file
    loading path is exercisable end-to-end offline — the CIFAR
    analogue of generate_mnist_idx.  Idempotent; a complete genuine
    set is left untouched and a PARTIAL set is never overwritten."""
    base = target_dir or _cifar10_dirs()[0]
    os.makedirs(base, exist_ok=True)
    names = [b + ".bin" for b in _CIFAR10_TRAIN_BATCHES] \
        + [_CIFAR10_TEST_BATCH + ".bin"]
    present = [n for n in names
               if os.path.exists(os.path.join(base, n))]
    if len(present) == len(names):
        return base
    if present:
        missing = sorted(set(names) - set(present))
        raise FileExistsError(
            f"{base} holds a partial CIFAR-10 batch set ({present}); "
            f"refusing to overwrite with the synthetic stand-in. "
            f"Add the missing files {missing} or remove the partial "
            f"set.")
    (tx, ty), (vx, vy), _ = synthetic_classification(
        n_train, n_test, (32, 32, 3), n_classes=10, noise=0.5,
        seed=seed)

    def write_bin(path: str, x: np.ndarray, y: np.ndarray) -> None:
        planes = np.round(x * 255.0).astype(np.uint8) \
            .transpose(0, 3, 1, 2).reshape(len(x), 3072)
        rec = np.empty((len(x), 3073), np.uint8)
        rec[:, 0] = y
        rec[:, 1:] = planes
        rec.tofile(path)

    per = -(-n_train // len(_CIFAR10_TRAIN_BATCHES))
    for i, name in enumerate(_CIFAR10_TRAIN_BATCHES):
        sl = slice(i * per, (i + 1) * per)
        write_bin(os.path.join(base, name + ".bin"), tx[sl], ty[sl])
    write_bin(os.path.join(base, _CIFAR10_TEST_BATCH + ".bin"),
              vx, vy)
    return base


# -- ImageNet offline preparation --------------------------------------

def prepare_imagenet(source: str, out_dir: str,
                     image_size: int = 227, valid_frac: float = 0.1,
                     quality: int = 92,
                     progress_every: int = 5000) -> dict:
    """Offline ImageNet preparation (reference parity: the AlexNet
    sample's preparation scripts — resizing, label json, mean image;
    SURVEY.md §3.2 samples row).

    ``source`` is an archive (.tar/.tar.gz/.tgz/.zip) or a directory,
    holding either ``<split>/<class>/img`` (splits preserved) or flat
    ``<class>/img`` (split deterministically by ``valid_frac``).  Each
    image is decoded, bilinear-resized to ``image_size`` square RGB and
    re-encoded as JPEG under ``out_dir/<split>/<class>/`` — so training
    -time decode work is minimal and every row is already the static
    shape XLA needs.  Also writes:

    - ``labels.json``: sorted class name -> integer id;
    - ``mean_image.npy``: float32 (size, size, 3) mean over the TRAIN
      split in [0, 1] (the reference subtracts the mean image);
    - ``manifest.json``: per-split counts + parameters.

    Returns the manifest dict.  The output tree is exactly what
    ``ImageDirectoryLoader(data_dir=out_dir)`` expects, and
    ``models/alexnet.py`` accepts it via ``loader.data_dir``.
    """
    import json as _json
    import shutil
    import tarfile
    import zipfile

    from PIL import Image

    src = os.path.expanduser(source)
    out = os.path.expanduser(out_dir)
    extracted = None
    if os.path.isfile(src):
        extracted = os.path.join(out, "_extracted")
        os.makedirs(extracted, exist_ok=True)
        if src.endswith(".zip"):
            with zipfile.ZipFile(src) as z:
                z.extractall(extracted)
        else:
            with tarfile.open(src) as t:
                try:
                    t.extractall(extracted, filter="data")
                except TypeError:  # pre-3.10.12/3.11.4: no filter=
                    t.extractall(extracted)
        src = extracted
    if not os.path.isdir(src):
        raise ValueError(f"prepare_imagenet: {source!r} is neither a "
                         f"directory nor a readable archive")

    img_ext = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm",
               ".tif", ".tiff", ".webp")

    def is_img(fn: str) -> bool:
        return fn.lower().endswith(img_ext)

    def classes_of(d: str):
        return sorted(e for e in os.listdir(d)
                      if os.path.isdir(os.path.join(d, e)))

    # descend through pure wrapper directories (`tar czf x.tgz ILSVRC/`
    # puts everything under one top-level dir that is neither a split
    # nor a class) — a wrapper has exactly one subdir and no images
    while True:
        entries = os.listdir(src)
        subdirs = [e for e in entries
                   if os.path.isdir(os.path.join(src, e))]
        if len(subdirs) == 1 and not any(is_img(e) for e in entries) \
                and not any(is_img(f) for f in
                            os.listdir(os.path.join(src, subdirs[0]))):
            nxt = os.path.join(src, subdirs[0])
            # don't descend past a split layout ("train" at this level)
            if subdirs[0].lower() in ("train", "validation", "valid",
                                      "test"):
                break
            src = nxt
        else:
            break

    # detect layout: split dirs present, or flat class dirs
    split_names = {"train": "train", "validation": "validation",
                   "valid": "validation", "test": "test"}
    splits: dict = {}
    present = [e for e in os.listdir(src) if e.lower() in split_names
               and os.path.isdir(os.path.join(src, e))]
    if present:
        for e in present:
            splits[split_names[e.lower()]] = os.path.join(src, e)
        class_names = sorted(set().union(
            *(classes_of(d) for d in splits.values())))
    else:
        splits["__flat__"] = src
        class_names = classes_of(src)
    if not class_names:
        raise ValueError(f"prepare_imagenet: no class directories "
                         f"found under {src!r}")
    label_of = {n: i for i, n in enumerate(class_names)}

    os.makedirs(out, exist_ok=True)
    mean_acc = np.zeros((image_size, image_size, 3), np.float64)
    counts = {"train": 0, "validation": 0, "test": 0}

    # plan all (src_path, split, dst_path) first: collision-safe names
    # (img001.png + img001.jpeg must not overwrite each other) and a
    # deterministic validation split for flat layouts
    jobs = []
    taken: set = set()
    for split_key, sdir in splits.items():
        for cls in classes_of(sdir):
            cdir = os.path.join(sdir, cls)
            files = sorted(f for f in os.listdir(cdir) if is_img(f))
            for j, fn in enumerate(files):
                if split_key == "__flat__":
                    # every round(1/frac)-th file goes to validation
                    period = max(2, int(round(1.0 / valid_frac))) \
                        if valid_frac > 0 else 0
                    split = "validation" if period and \
                        j % period == period - 1 else "train"
                else:
                    split = split_key
                dst_dir = os.path.join(out, split, cls)
                base = os.path.splitext(fn)[0]
                dst = os.path.join(dst_dir, base + ".jpg")
                k = 1
                while dst in taken:
                    k += 1
                    dst = os.path.join(dst_dir, f"{base}.{k}.jpg")
                taken.add(dst)
                os.makedirs(dst_dir, exist_ok=True)
                jobs.append((os.path.join(cdir, fn), split, dst))

    def convert(job):
        path, split, dst = job
        with Image.open(path) as im:
            im = im.convert("RGB")
            if im.size != (image_size, image_size):
                im = im.resize((image_size, image_size), Image.BILINEAR)
            im.save(dst, "JPEG", quality=quality)
            # mean contribution returned, accumulated serially (the
            # pool must not race on mean_acc)
            arr = np.asarray(im, np.float64) / 255.0 \
                if split == "train" else None
        return split, arr

    from concurrent.futures import ThreadPoolExecutor
    workers = min(os.cpu_count() or 4, 16)
    with ThreadPoolExecutor(workers) as pool:
        for done, (split, arr) in enumerate(pool.map(convert, jobs), 1):
            counts[split] += 1
            if arr is not None:
                mean_acc += arr
            if progress_every and done % progress_every == 0:
                print(f"prepare-imagenet: {done}/{len(jobs)} images "
                      f"converted")

    if not any(counts.values()):
        raise ValueError(
            f"prepare_imagenet: found class directories "
            f"{class_names[:5]}... under {src!r} but zero images — "
            f"wrong layout? expected <split>/<class>/img or "
            f"<class>/img")
    if counts["train"]:
        mean = (mean_acc / counts["train"]).astype(np.float32)
        np.save(os.path.join(out, "mean_image.npy"), mean)
    from veles_tpu.snapshotter import atomic_write
    with atomic_write(os.path.join(out, "labels.json"), "w") as f:
        _json.dump(label_of, f, indent=1, sort_keys=True)
    manifest = {"image_size": image_size, "n_classes": len(class_names),
                "counts": counts, "source": source,
                "mean_image": bool(counts["train"])}
    with atomic_write(os.path.join(out, "manifest.json"),
                      "w") as f:
        _json.dump(manifest, f, indent=1)
    if extracted is not None:
        shutil.rmtree(extracted, ignore_errors=True)
    return manifest


# -- synthetic generators ----------------------------------------------

def _class_templates(rng: np.random.Generator, n_classes: int,
                     shape: Tuple[int, ...]) -> np.ndarray:
    """Smooth per-class patterns: low-frequency random fields, so
    convnets with pooling can exploit spatial structure."""
    h, w = shape[0], shape[1]
    c = shape[2] if len(shape) > 2 else 1
    coarse = rng.standard_normal((n_classes, max(2, h // 4),
                                  max(2, w // 4), c)).astype(np.float32)
    # separable bilinear upsample, float32 throughout: rows first
    # (n, h, cw, c), then columns.  The old one-shot 4-corner form built
    # four (n, h, w, c) float64 intermediates — gigabytes of allocation
    # at ImageNet scale (1000 x 227 x 227 x 3) and the dominant cost of
    # building the benchmark dataset.
    ys = np.linspace(0, coarse.shape[1] - 1, h)
    xs = np.linspace(0, coarse.shape[2] - 1, w)
    y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, coarse.shape[1] - 1)
    x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, coarse.shape[2] - 1)
    wy = (ys - y0).astype(np.float32)[None, :, None, None]
    wx = (xs - x0).astype(np.float32)[None, None, :, None]
    rows = coarse[:, y0] * (1 - wy) + coarse[:, y1] * wy
    return rows[:, :, x0] * (1 - wx) + rows[:, :, x1] * wx


#: OPT-IN one-entry cache of the last LARGE generated dataset
#: (``VELES_TPU_SYNTH_CACHE=1``, set by bench.py): the benchmark builds
#: the identical ImageNet-scale set twice (resident + streaming
#: workflows) and regeneration is minutes of single-core work.  Opt-in
#: because the cache retains a duplicate multi-GB copy for the process
#: lifetime — ordinary training runs must not pay that.  Callers must
#: treat the returned arrays as read-only — every in-tree consumer
#: copies (loaders ``np.concatenate`` the splits).  Small (test-sized)
#: sets are never cached.
_synth_cache: dict = {}
_SYNTH_CACHE_MIN_BYTES = 256 * 2 ** 20


def _synth_cache_enabled() -> bool:
    return bool(os.environ.get("VELES_TPU_SYNTH_CACHE"))


def synthetic_classification(
        n_train: int, n_valid: int, shape: Tuple[int, ...],
        n_classes: int = 10, noise: float = 0.4, max_shift: int = 2,
        seed: int = 20260729, n_test: int = 0,
) -> Tuple[Split, Split, Optional[Split]]:
    """Deterministic image-classification task.

    sample = circular-shifted class template + gaussian noise, values
    squashed to [0, 1].  Returns (train, valid, test-or-None).
    """
    key = (n_train, n_valid, tuple(shape), n_classes, noise,
           max_shift, seed, n_test)
    hit = _synth_cache.get(key) if _synth_cache_enabled() else None
    if hit is not None:
        return hit
    rng = np.random.default_rng(seed)
    templates = _class_templates(rng, n_classes, shape)

    def make(n: int) -> Split:
        y = rng.integers(0, n_classes, n).astype(np.int32)
        x = templates[y]  # fancy indexing: a fresh array, safe in-place
        if max_shift > 0:
            sh, sw = (rng.integers(-max_shift, max_shift + 1, (2, n)))
            # per-sample circular shift, grouped by shift value: there
            # are only 2*max_shift+1 distinct shifts per axis, so each
            # group rolls as one contiguous block op (identical values
            # to per-sample np.roll, without n python iterations or an
            # elementwise 3-index gather — both measured far slower at
            # ImageNet scale)
            for axis, shifts in ((1, sh), (2, sw)):
                for s in np.unique(shifts):
                    if s:
                        idx = np.nonzero(shifts == s)[0]
                        x[idx] = np.roll(x[idx], s, axis=axis)
        g = rng.standard_normal(x.shape, dtype=np.float32)
        np.multiply(g, np.float32(noise), out=g)
        x += g
        del g
        # squash into (0,1) like pixel data: sigmoid, in place
        np.negative(x, out=x)
        np.exp(x, out=x)
        x += 1.0
        np.reciprocal(x, out=x)
        if len(shape) == 2:
            x = x[..., 0] if x.shape[-1] == 1 else x
        return np.ascontiguousarray(x, np.float32), y

    train = make(n_train)
    valid = make(n_valid)
    test = make(n_test) if n_test else None
    result = (train, valid, test)
    nbytes = sum(s[0].nbytes for s in result if s is not None)
    if _synth_cache_enabled() and nbytes >= _SYNTH_CACHE_MIN_BYTES:
        _synth_cache.clear()  # hold at most one giant set
        _synth_cache[key] = result
    return result


def synthetic_classification_device(n: int, shape: Tuple[int, ...],
                                    n_classes: int = 10,
                                    noise: float = 0.4,
                                    max_shift: int = 2,
                                    seed: int = 20260729,
                                    jax_device=None,
                                    sharding=None):
    """The synthetic classification task born ON the accelerator: same
    family as ``synthetic_classification`` (low-frequency class
    templates -> per-sample circular shift -> gaussian noise ->
    sigmoid squash) implemented in jax, so an HBM-resident benchmark
    set never exists on the host and never crosses the interconnect.
    This matters because the host here can be a single slow core behind
    a thin tunnel: generating ImageNet-scale pixels in numpy and
    uploading them costs minutes, on-device generation costs
    milliseconds.  Values differ from the numpy generator (different
    PRNG/interp), but the task structure and difficulty are the same.

    Returns ``(data, labels)`` jax arrays: float32 (n, *shape) in
    (0, 1) and int32 (n,).
    """
    import jax
    import jax.numpy as jnp

    h, w = shape[0], shape[1]
    c = shape[2] if len(shape) > 2 else 1

    def gen(key):
        kt, ky, ks, kn = jax.random.split(key, 4)
        coarse = jax.random.normal(
            kt, (n_classes, max(2, h // 4), max(2, w // 4), c),
            jnp.float32)
        templates = jax.image.resize(coarse, (n_classes, h, w, c),
                                     "bilinear")
        y = jax.random.randint(ky, (n,), 0, n_classes, jnp.int32)
        x = templates[y]
        if max_shift > 0:
            sh = jax.random.randint(ks, (2, n), -max_shift,
                                    max_shift + 1)
            x = jax.vmap(
                lambda img, s0, s1: jnp.roll(img, (s0, s1),
                                             axis=(0, 1)))(
                x, sh[0], sh[1])
        g = jax.random.normal(kn, x.shape, jnp.float32)
        x = jax.nn.sigmoid(x + jnp.float32(noise) * g)
        if len(shape) == 2:
            x = x[..., 0]
        return x, y

    if sharding is not None:
        # mesh case: generate straight into the requested layout
        # (replicated for the resident-dataset step) — every device
        # runs the same cheap gen computation, nothing crosses the
        # host or the interconnect
        data, labels = jax.jit(
            gen, out_shardings=(sharding, sharding))(
            jax.random.PRNGKey(seed))
        data.block_until_ready()
        return data, labels
    import contextlib
    ctx = jax.default_device(jax_device) if jax_device is not None \
        else contextlib.nullcontext()
    with ctx:
        data, labels = jax.jit(gen)(jax.random.PRNGKey(seed))
        data.block_until_ready()
    return data, labels


def _main(argv=None) -> int:
    """``python -m veles_tpu.datasets make-mnist-idx [DIR]`` — offline
    dataset materialization (IDX files for the real-file path)."""
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.datasets")
    sub = p.add_subparsers(dest="cmd", required=True)
    mk = sub.add_parser("make-mnist-idx",
                        help="write MNIST-format IDX files (synthetic "
                             "stand-in) under DIR or the data dir")
    mk.add_argument("dir", nargs="?", default=None)
    mk.add_argument("--n-train", type=int, default=60000)
    mk.add_argument("--n-test", type=int, default=10000)
    mkc = sub.add_parser(
        "make-cifar10-batches",
        help="write CIFAR-10 binary-format batch files (synthetic "
             "stand-in) under DIR or the data dir")
    mkc.add_argument("dir", nargs="?", default=None)
    mkc.add_argument("--n-train", type=int, default=50000)
    mkc.add_argument("--n-test", type=int, default=10000)
    prep = sub.add_parser(
        "prepare-imagenet",
        help="resize + re-encode an image archive/tree into the "
             "<out>/<split>/<class>/img layout with labels.json and "
             "the train-split mean image")
    prep.add_argument("source", help="archive (.tar[.gz]/.zip) or "
                                     "directory of class subdirs")
    prep.add_argument("--out", required=True)
    prep.add_argument("--image-size", type=int, default=227)
    prep.add_argument("--valid-frac", type=float, default=0.1)
    prep.add_argument("--quality", type=int, default=92)
    args = p.parse_args(argv)
    if args.cmd == "prepare-imagenet":
        manifest = prepare_imagenet(
            args.source, args.out, image_size=args.image_size,
            valid_frac=args.valid_frac, quality=args.quality)
        print(manifest)
        return 0
    if args.cmd == "make-cifar10-batches":
        print(generate_cifar10_batches(args.dir, args.n_train,
                                       args.n_test))
        return 0
    base = generate_mnist_idx(args.dir, args.n_train, args.n_test)
    print(base)
    return 0


def cap_real(real, n_train: int, n_valid: int):
    """Requested sizes act as caps on real files too: a 100-sample
    smoke run must not silently get the full 50k/10k set just because
    files exist.  THE single policy point — both the module-level
    dataset functions and loader._RealFileMixin go through here."""
    (tx, ty), (vx, vy) = real
    return (tx[:n_train], ty[:n_train]), (vx[:n_valid], vy[:n_valid]), \
        None


def mnist(n_train: int = 60000, n_valid: int = 10000,
          force_synthetic: bool = False):
    """MNIST: real IDX files if present, else synthetic 28x28x1."""
    if not force_synthetic:
        real = try_load_real_mnist()
        if real is not None:
            return cap_real(real, n_train, n_valid)
    return synthetic_classification(
        n_train, n_valid, (28, 28, 1), n_classes=10, seed=28281)


def cifar10(n_train: int = 50000, n_valid: int = 10000,
            force_synthetic: bool = False):
    """CIFAR-10: real batch files if present, else synthetic 32x32x3."""
    if not force_synthetic:
        real = try_load_real_cifar10()
        if real is not None:
            return cap_real(real, n_train, n_valid)
    return synthetic_classification(
        n_train, n_valid, (32, 32, 3), n_classes=10, noise=0.5, seed=32323)


def imagenet(n_train: int = 8192, n_valid: int = 1024,
             image_size: int = 227, n_classes: int = 1000):
    """Synthetic ImageNet stand-in at AlexNet's input resolution.  Sizes
    default small — the benchmark measures images/sec, not accuracy."""
    return synthetic_classification(
        n_train, n_valid, (image_size, image_size, 3),
        n_classes=n_classes, noise=0.5, max_shift=8, seed=227227)

if __name__ == "__main__":
    import sys
    sys.exit(_main())
