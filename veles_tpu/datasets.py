"""Dataset acquisition: real files when present, deterministic synthetic
generators otherwise.

This environment has no network and ships no datasets (verified:
full-disk search found none), so the five BASELINE.json benchmark
configs run on procedurally generated stand-ins by default.  Each
generator is fully determined by (seed, sizes): per-class template
patterns plus per-sample jitter/noise, linearly separable enough that
the reference architectures reach their target accuracies, while
keeping realistic shapes (28x28x1, 32x32x3, 227x227x3).

If real data is placed under ``root.common.data_dir`` (default
``~/.veles_tpu/data``) — e.g. MNIST IDX files — the loaders pick it up
instead (reference behaviour: veles/loader downloads/caches datasets;
offline here, so files must be pre-placed).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

Split = Tuple[np.ndarray, np.ndarray]


def data_dir() -> str:
    from veles_tpu.config import root
    d = root.common.get("data_dir") if "common" in root else None
    return os.path.expanduser(d or "~/.veles_tpu/data")


# -- real MNIST (IDX format), if files are pre-placed ------------------

_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def write_idx(path: str, array: np.ndarray) -> None:
    """Write an array in IDX format (the MNIST container: big-endian
    magic = dtype 0x08 (ubyte) + ndim, then dims, then raw bytes)."""
    arr = np.ascontiguousarray(array, np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def generate_mnist_idx(target_dir: Optional[str] = None,
                       n_train: int = 60000, n_test: int = 10000,
                       seed: int = 28281) -> str:
    """Materialize the synthetic MNIST stand-in AS REAL IDX FILES under
    ``<data_dir>/mnist`` so the real-file loading path is exercisable
    end-to-end offline (round-1 VERDICT missing #3).  If genuine MNIST
    IDX files are ever pre-placed there, they are left untouched."""
    base = target_dir or os.path.join(data_dir(), "mnist")
    os.makedirs(base, exist_ok=True)
    if all(os.path.exists(os.path.join(base, f))
           for f in _MNIST_FILES.values()):
        return base
    (tx, ty), (vx, vy), _ = synthetic_classification(
        n_train, n_test, (28, 28, 1), n_classes=10, seed=seed)
    write_idx(os.path.join(base, _MNIST_FILES["train_images"]),
              np.round(tx[..., 0] * 255.0))
    write_idx(os.path.join(base, _MNIST_FILES["train_labels"]), ty)
    write_idx(os.path.join(base, _MNIST_FILES["test_images"]),
              np.round(vx[..., 0] * 255.0))
    write_idx(os.path.join(base, _MNIST_FILES["test_labels"]), vy)
    return base


def try_load_real_mnist() -> Optional[Tuple[Split, Split]]:
    base = os.path.join(data_dir(), "mnist")
    paths = {}
    for key, fname in _MNIST_FILES.items():
        for cand in (os.path.join(base, fname),
                     os.path.join(base, fname + ".gz")):
            if os.path.exists(cand):
                paths[key] = cand
                break
        else:
            return None
    tx = _read_idx(paths["train_images"]).astype(np.float32) / 255.0
    ty = _read_idx(paths["train_labels"]).astype(np.int32)
    vx = _read_idx(paths["test_images"]).astype(np.float32) / 255.0
    vy = _read_idx(paths["test_labels"]).astype(np.int32)
    return (tx[..., None], ty), (vx[..., None], vy)


# -- synthetic generators ----------------------------------------------

def _class_templates(rng: np.random.Generator, n_classes: int,
                     shape: Tuple[int, ...]) -> np.ndarray:
    """Smooth per-class patterns: low-frequency random fields, so
    convnets with pooling can exploit spatial structure."""
    h, w = shape[0], shape[1]
    c = shape[2] if len(shape) > 2 else 1
    coarse = rng.standard_normal((n_classes, max(2, h // 4),
                                  max(2, w // 4), c)).astype(np.float32)
    # bilinear upsample to full resolution
    out = np.empty((n_classes, h, w, c), np.float32)
    ys = np.linspace(0, coarse.shape[1] - 1, h)
    xs = np.linspace(0, coarse.shape[2] - 1, w)
    y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, coarse.shape[1] - 1)
    x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, coarse.shape[2] - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    out = (coarse[:, y0][:, :, x0] * (1 - wy) * (1 - wx)
           + coarse[:, y1][:, :, x0] * wy * (1 - wx)
           + coarse[:, y0][:, :, x1] * (1 - wy) * wx
           + coarse[:, y1][:, :, x1] * wy * wx)
    return out.astype(np.float32)


def synthetic_classification(
        n_train: int, n_valid: int, shape: Tuple[int, ...],
        n_classes: int = 10, noise: float = 0.4, max_shift: int = 2,
        seed: int = 20260729, n_test: int = 0,
) -> Tuple[Split, Split, Optional[Split]]:
    """Deterministic image-classification task.

    sample = circular-shifted class template + gaussian noise, values
    squashed to [0, 1].  Returns (train, valid, test-or-None).
    """
    rng = np.random.default_rng(seed)
    templates = _class_templates(rng, n_classes, shape)

    def make(n: int) -> Split:
        y = rng.integers(0, n_classes, n).astype(np.int32)
        x = templates[y]
        if max_shift > 0:
            sh, sw = (rng.integers(-max_shift, max_shift + 1, (2, n)))
            for i in range(n):  # per-sample circular shift
                x[i] = np.roll(x[i], (sh[i], sw[i]), axis=(0, 1))
        x = x + noise * rng.standard_normal(x.shape).astype(np.float32)
        x = 1.0 / (1.0 + np.exp(-x))  # squash into (0,1) like pixel data
        if len(shape) == 2:
            x = x[..., 0] if x.shape[-1] == 1 else x
        return x.astype(np.float32), y

    train = make(n_train)
    valid = make(n_valid)
    test = make(n_test) if n_test else None
    return train, valid, test


def _main(argv=None) -> int:
    """``python -m veles_tpu.datasets make-mnist-idx [DIR]`` — offline
    dataset materialization (IDX files for the real-file path)."""
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.datasets")
    sub = p.add_subparsers(dest="cmd", required=True)
    mk = sub.add_parser("make-mnist-idx",
                        help="write MNIST-format IDX files (synthetic "
                             "stand-in) under DIR or the data dir")
    mk.add_argument("dir", nargs="?", default=None)
    mk.add_argument("--n-train", type=int, default=60000)
    mk.add_argument("--n-test", type=int, default=10000)
    args = p.parse_args(argv)
    base = generate_mnist_idx(args.dir, args.n_train, args.n_test)
    print(base)
    return 0


def mnist(n_train: int = 60000, n_valid: int = 10000,
          force_synthetic: bool = False):
    """MNIST: real IDX files if present, else synthetic 28x28x1."""
    if not force_synthetic:
        real = try_load_real_mnist()
        if real is not None:
            return real[0], real[1], None
    return synthetic_classification(
        n_train, n_valid, (28, 28, 1), n_classes=10, seed=28281)


def cifar10(n_train: int = 50000, n_valid: int = 10000):
    return synthetic_classification(
        n_train, n_valid, (32, 32, 3), n_classes=10, noise=0.5, seed=32323)


def imagenet(n_train: int = 8192, n_valid: int = 1024,
             image_size: int = 227, n_classes: int = 1000):
    """Synthetic ImageNet stand-in at AlexNet's input resolution.  Sizes
    default small — the benchmark measures images/sec, not accuracy."""
    return synthetic_classification(
        n_train, n_valid, (image_size, image_size, 3),
        n_classes=n_classes, noise=0.5, max_shift=8, seed=227227)

if __name__ == "__main__":
    import sys
    sys.exit(_main())
