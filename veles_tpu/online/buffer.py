"""The replay buffer: bounded, reservoir-sampled, uint8 where possible.

Tapped live traffic is unbounded; host RAM (and the residency budget
the buffer is charged against) is not.  The buffer keeps at most
``capacity`` labeled training rows with classic reservoir sampling —
once full, the j-th arriving row replaces a uniformly drawn slot with
probability capacity/j — so the retained set is an unbiased sample of
everything tapped, under a SEEDED generator: the same tap order always
yields the same buffer (the determinism the online-vs-offline oracle
test rests on).

Storage reuses the PR 2 quantized-ingest codec (loader/quantize.py):
when the model's :class:`AffineDequant` round-trips the tapped float
rows exactly (they were dequantized from uint8 on some client's disk —
the common image case), rows store as uint8 at 1 byte/value, a 4x cut
against the residency charge, and batch assembly dequantizes with the
SAME affine the traced serving/training prologue uses.  Rows the codec
cannot represent exactly stay float32 — correctness is never traded
for the discount.

The held-out slice: every ``holdout_every``-th labeled tap lands in a
separate, never-trained-on partition that the promotion gate scores
shadow vs incumbent on.  Holdout slots cap at ``capacity // 4`` with
FIFO replacement (the gate wants the freshest view of the traffic
distribution, not a reservoir over all history).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from veles_tpu.analysis import witness


class ReplayBuffer:
    """Labeled-row store for one learning model.  Thread-safe: the
    hive main loop adds, the scavenger thread samples."""

    def __init__(self, capacity: int, seed: int = 0,
                 holdout_every: int = 8,
                 dequant: Optional[Any] = None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.holdout_every = max(0, int(holdout_every))
        self.holdout_cap = max(1, self.capacity // 4)
        #: the model's AffineDequant (None = float-only storage)
        self.dequant = dequant
        self._lock = witness.lock("online.buffer")
        self._rng = np.random.default_rng(seed)
        self._rows: List[np.ndarray] = []
        self._labels: List[int] = []
        self._holdout_rows: List[np.ndarray] = []
        self._holdout_labels: List[int] = []
        #: labeled ADD batches ever offered (holdout routing)
        self._seen = 0
        #: rows ever offered to the TRAIN partition (the reservoir j)
        self._train_seen = 0
        #: labeled ADD batches accepted — the version the trainer logs
        #: per step so an offline replay reconstructs the exact buffer
        #: state each step sampled from
        self.version = 0
        #: quantization verdict: None until the first add decides,
        #: then True (uint8 storage) or False (float32)
        self._quantized: Optional[bool] = None

    # -- codec ---------------------------------------------------------

    def _encode(self, rows: np.ndarray) -> np.ndarray:
        """f32 rows -> storage dtype.  The first add pins the codec:
        uint8 iff the inverse affine round-trips these rows exactly
        (checked against the forward dequant, not a tolerance)."""
        if self._quantized is None:
            self._quantized = False
            if self.dequant is not None:
                q = self._inverse(rows)
                if q is not None:
                    self._quantized = True
                    return q
            return np.asarray(rows, np.float32)
        if self._quantized:
            q = self._inverse(rows)
            if q is not None:
                return q
            # a later request breaks the byte range: store the exact
            # f32 row; _decode handles mixed dtypes per row
            return np.asarray(rows, np.float32)
        return np.asarray(rows, np.float32)

    def _inverse(self, rows: np.ndarray) -> Optional[np.ndarray]:
        dq = self.dequant
        scale = np.where(dq.scale == 0.0, 1.0,
                         dq.scale).astype(np.float64)
        q = np.round((rows.astype(np.float64) - dq.bias) / scale)
        if q.min() < 0 or q.max() > 255:
            return None
        q8 = q.astype(np.uint8)
        if not np.array_equal(dq.apply_host(q8),
                              np.asarray(rows, np.float32)):
            return None
        return q8

    def _decode(self, row: np.ndarray) -> np.ndarray:
        if row.dtype == np.uint8:
            return self.dequant.apply_host(row)
        return row

    # -- intake --------------------------------------------------------

    def add(self, rows: np.ndarray, labels: np.ndarray) -> str:
        """One labeled tapped request: route to train (reservoir) or
        holdout (every Nth, FIFO).  Returns the slot ("train" /
        "holdout") so the tap can qualify fault injection."""
        rows = np.asarray(rows, np.float32)
        labels = np.asarray(labels, np.int32).reshape(-1)
        if len(labels) == 1 and len(rows) > 1:
            labels = np.repeat(labels, len(rows))
        if len(labels) != len(rows):
            raise ValueError(
                f"{len(rows)} rows with {len(labels)} labels")
        enc = self._encode(rows)
        with self._lock:
            self._seen += 1
            holdout = self.holdout_every > 0 and \
                self._seen % self.holdout_every == 0
            if holdout:
                for r, lb in zip(enc, labels):
                    if len(self._holdout_rows) >= self.holdout_cap:
                        self._holdout_rows.pop(0)
                        self._holdout_labels.pop(0)
                    self._holdout_rows.append(r)
                    self._holdout_labels.append(int(lb))
            else:
                for r, lb in zip(enc, labels):
                    self._train_seen += 1
                    if len(self._rows) < self.capacity:
                        self._rows.append(r)
                        self._labels.append(int(lb))
                    else:
                        # reservoir: row t survives with probability
                        # capacity/t, under the seeded stream
                        j = int(self._rng.integers(
                            0, self._train_seen))
                        if j < self.capacity:
                            self._rows[j] = r
                            self._labels[j] = int(lb)
                self.version += 1
        return "holdout" if holdout else "train"

    # -- views ---------------------------------------------------------

    @property
    def train_rows(self) -> int:
        with self._lock:
            return len(self._rows)

    @property
    def holdout_rows(self) -> int:
        with self._lock:
            return len(self._holdout_rows)

    @property
    def nbytes(self) -> int:
        """Host bytes the stored rows occupy — the residency charge."""
        with self._lock:
            return int(sum(r.nbytes for r in self._rows)
                       + sum(r.nbytes for r in self._holdout_rows))

    @property
    def quantized(self) -> bool:
        return bool(self._quantized)

    def sample(self, batch: int,
               rng: np.random.Generator) -> Tuple[np.ndarray,
                                                  np.ndarray]:
        """``batch`` decoded f32 training rows drawn with replacement
        under ``rng`` — the caller seeds it from (model seed, step),
        so a step is a pure function of (buffer state, step index)."""
        with self._lock:
            n = len(self._rows)
            if n == 0:
                raise ValueError("empty replay buffer")
            idx = rng.integers(0, n, size=batch)
            rows = [self._rows[i] for i in idx]
            labels = np.asarray([self._labels[i] for i in idx],
                                np.int32)
        x = np.stack([self._decode(r) for r in rows]).astype(
            np.float32)
        return x, labels

    def holdout(self, limit: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
        """The decoded held-out slice (copy).  ``limit`` keeps only
        the NEWEST rows — the slicing happens BEFORE decode, so a
        bounded gate round never pays host decode for rows it will
        not score (the decode loop burns the GIL the serving threads
        share)."""
        with self._lock:
            rows = list(self._holdout_rows)
            labels = np.asarray(self._holdout_labels, np.int32)
        if limit is not None and len(rows) > limit:
            rows = rows[-limit:]
            labels = labels[-limit:]
        if not rows:
            return (np.zeros((0,), np.float32),
                    np.zeros((0,), np.int32))
        x = np.stack([self._decode(r) for r in rows]).astype(
            np.float32)
        return x, labels
