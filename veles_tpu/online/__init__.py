"""Evergreen: the online-learning tier of the hive.

A served model is frozen at package time; Evergreen un-freezes it
without ever leaving the serving process (or the chip):

- :mod:`veles_tpu.online.tap` — the traffic tap: a deterministic
  sampled fraction of admitted request rows (``$VELES_ONLINE_TAP_FRAC``)
  plus their ground-truth labels (the ``label`` wire field, or a late
  ``label_of`` join by wire id) mirror into
- :mod:`veles_tpu.online.buffer` — a bounded reservoir-sampled replay
  buffer, uint8-quantized through the PR 2 ingest codec when the
  model's dequant round-trips, its host bytes charged against the
  serving residency budget;
- :mod:`veles_tpu.online.trainer` — the scavenger: a fused fine-tune
  micro-step (the FusedStepRunner train body, vmapped over the
  ensemble's member axis) fires ONLY when every serving batcher is
  idle and the SLO headroom check passes, so serving latency owns the
  chip and learning eats the gaps; every step kind compiles once;
- :mod:`veles_tpu.online.promote` — the gate: fine-tuned params live
  as a shadow registration in the ResidencyManager, the tap's
  held-out slice scores shadow vs incumbent, and a win past
  ``$VELES_ONLINE_PROMOTE_MARGIN`` hot-swaps the params HBM-to-HBM
  (atomic pointer swap under the residency lock — no in-flight
  request ever sees torn params); a loss rolls the shadow back and
  journals.
"""

from veles_tpu.online.buffer import ReplayBuffer  # noqa: F401
from veles_tpu.online.promote import GATE_STATES  # noqa: F401
from veles_tpu.online.tap import TrafficTap  # noqa: F401
from veles_tpu.online.trainer import OnlineLearner  # noqa: F401
