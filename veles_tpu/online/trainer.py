"""The scavenger trainer: fine-tune in the serving idle gaps.

One thread inside the hive process (which owns the chip — there is
nobody else to give the idle cycles to) drives, per learning model, a
fused fine-tune micro-step: the FusedStepRunner train body — forward
with residuals, evaluator gradient, the per-unit backward/SGD chain —
``jax.vmap``-ed over the ensemble's stacked MEMBER axis and jitted
with donated params/opt, so one dispatch advances every member on the
same replay micro-batch and the step compiles exactly ONCE (fixed
``$VELES_ONLINE_MICRO_BATCH`` shape; the PR 7 compile-split gauge pins
zero post-warmup recompiles).

Scheduling is strictly parasitic: a step fires only when EVERY serving
batcher has been idle (empty queue, nothing in flight) for
``$VELES_ONLINE_IDLE_MS``, and — the PR 11 admission-estimator move
turned inward — only when the EMA step cost fits under
``$VELES_ONLINE_SLO_P99_MS`` (a scavenged step longer than the SLO
would become the p99 of whatever request arrives beneath it).  Skipped
opportunities count ``online.steps_skipped_busy``.

Determinism: step k of a model samples its micro-batch with a
generator seeded from ``(model seed, k)`` against the buffer state at
a recorded ``buffer.version`` — the (step, version) history makes an
offline replay of the same tapped rows reproduce the online param
trajectory f32-exactly (pinned by tests/test_online.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, knobs, telemetry
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger
from veles_tpu.online.buffer import ReplayBuffer
from veles_tpu.online.promote import PromotionGate
from veles_tpu.online.tap import TrafficTap
from veles_tpu.ops import batching


class ShadowTrainer:
    """One model's fine-tune state: the jitted vmapped step/score and
    the device-resident shadow params it advances.

    Deliberately free of hive plumbing so a test (or an offline
    oracle replay) can drive it directly: construct from the hosted
    workflow's unit chain, call :meth:`step` with explicit batches."""

    def __init__(self, forwards: List[Any], gds: List[Any],
                 evaluator: Any, device: Any, stacked_params: Any,
                 seed: int, lr_scale: float,
                 micro_batch: int) -> None:
        import jax
        import jax.numpy as jnp
        self.forwards = list(forwards)
        self.gds = list(gds)
        self.evaluator = evaluator
        self.device = device
        self.seed = int(seed)
        self.micro_batch = int(micro_batch)
        self.n_members = int(next(iter(next(iter(
            stacked_params.values())).values())).shape[0])
        #: the shadow's working params: a device-to-device COPY of the
        #: incumbent's stacked tree (the incumbent keeps serving its
        #: own; donation below only ever consumes the shadow's)
        self._params = jax.tree_util.tree_map(jnp.copy, stacked_params)
        self._opt = self._fresh_opt()
        #: absolute (n_gd, 2) fine-tune rates: the packaged units'
        #: training rates scaled down for the nudge regime
        self._lr = np.asarray(
            [[gd.learning_rate * lr_scale,
              gd.learning_rate_bias * lr_scale]
             if gd is not None else [0.0, 0.0] for gd in self.gds],
            np.float32)
        self.steps = 0
        #: (step index, buffer version) per step — the oracle log
        self.history: List[Tuple[int, int]] = []
        self._build()

    def _fresh_opt(self) -> Dict[str, Dict[str, Any]]:
        opt: Dict[str, Dict[str, Any]] = {}
        for gd in self.gds:
            if gd is None or not gd.accumulated_grads:
                continue
            opt[gd.name] = {
                k: self.device.zeros(
                    (self.n_members,) + tuple(v.shape), np.float32)
                for k, v in gd.accumulated_grads.items()}
        return opt

    def _build(self) -> None:
        import jax
        import jax.numpy as jnp

        from veles_tpu.engine import core as engine_core

        evaluator = self.evaluator
        cd = batching.resolve_compute_dtype(None, self.device)
        cast = batching.make_caster(cd)
        core = self._core = engine_core.ExecutionCore(
            self.device, None, pool="train", name="online-shadow")
        # the same shared Keel bodies the offline loops compose — the
        # rng key chain hashes (model seed, step) identically to the
        # oracle replay's, and the backward walk is the rates-only
        # (wd=None) spelling the single-model loops use
        forward_pass = engine_core.build_forward(self.forwards,
                                                 self.seed, cd)
        backward_update = engine_core.build_backward(self.forwards,
                                                     self.gds, cd)
        member_fwd = engine_core.build_member_forward(self.forwards,
                                                      cd)

        def member_step(params, opt, lr, x, labels, mask, rc):
            # the fused train body, one micro-batch per dispatch —
            # vmap lifts it over the leading member axis of params/opt
            cparams = cast(params)
            out, residuals = forward_pass(cparams, x, rc, True)
            m = evaluator.metrics_fn(out.astype(jnp.float32), labels,
                                     mask)
            err = m["err_output"]
            new_params, new_opt = backward_update(
                cparams, params, opt, residuals, err, lr)
            metrics = jnp.stack([m["n_err"], m["loss_sum"],
                                 m["count"]])
            return new_params, new_opt, metrics

        self._step = core.jit(
            core.vmap_members(member_step,
                              in_axes=(0, 0, None, None, None, None,
                                       None)),
            donate=(0, 1))

        def score(params, acc, x, labels, mask):
            probs = jax.vmap(member_fwd, in_axes=(0, None))(
                cast(params), x)
            # jnp.mean here is the shadow gate's own pinned oracle
            # contract (tests/test_online.py) — NOT the serving
            # dispatcher's fixed add chain; do not unify them
            pred = jnp.argmax(jnp.mean(probs, axis=0), axis=-1)
            wrong = jnp.sum((pred != labels).astype(jnp.float32)
                            * mask)
            return acc + jnp.stack([wrong, jnp.sum(mask)])

        self._score = core.jit(score, donate=(1,))

    # -- the two dispatch kinds ---------------------------------------

    def step(self, x: np.ndarray, labels: np.ndarray,
             version: int) -> np.ndarray:
        """One fine-tune micro-step over an exact micro_batch-shaped
        batch; returns the fetched (P, 3) [n_err, loss_sum, count]
        metrics (the fetch is the honest step barrier)."""
        if len(x) != self.micro_batch:
            raise ValueError(
                f"step batch is {len(x)} rows, the fixed shape is "
                f"{self.micro_batch}")
        mask = np.ones(self.micro_batch, np.float32)
        self.history.append((self.steps, int(version)))
        self._params, self._opt, met = self._step(
            self._params, self._opt, self._lr, x,
            np.asarray(labels, np.int32), mask, self.steps)
        self.steps += 1
        return np.asarray(met)

    def sample_rng(self, step: Optional[int] = None):
        """The seeded per-step generator the buffer draw uses — a pure
        function of (model seed, step index)."""
        k = self.steps if step is None else int(step)
        return np.random.default_rng([self.seed, k])

    def error_pct(self, params: Any, x: np.ndarray,
                  labels: np.ndarray,
                  abort=None) -> Optional[float]:
        """Held-out error % of any same-structure stacked params
        (shadow or incumbent) — fixed micro_batch-shaped chunks, one
        compile, donated [wrong, count] carry.  ``abort`` (checked
        between chunks) lets the scavenger bail out the moment live
        traffic arrives: returns None and the gate round defers —
        a multi-chunk sweep must never sit under a request."""
        chunk = self.micro_batch
        acc = self.device.zeros(2, np.float32)
        for i in range(0, len(x), chunk):
            if abort is not None and abort():
                return None
            xb, lb, mask = batching.pad_chunk(
                np.asarray(x[i:i + chunk], np.float32),
                np.asarray(labels[i:i + chunk], np.int32), chunk)
            acc = self._score(params, acc, xb, lb, mask)
            if abort is not None:
                # sync EVERY chunk: async dispatches otherwise pile
                # onto the device queue, and a serving dispatch
                # submitted behind the pile waits for all of it — the
                # abort check is only as fine-grained as the longest
                # un-synced chain (measured: the whole 1.2x-bar gap)
                acc.block_until_ready()
        acc = np.asarray(acc)
        return 100.0 * float(acc[0]) / max(float(acc[1]), 1.0)

    def reset_from(self, stacked_params: Any) -> None:
        """Rollback: the shadow restarts from a device copy of the
        given (incumbent) tree, momentum cleared."""
        import jax
        import jax.numpy as jnp
        self._params = jax.tree_util.tree_map(jnp.copy,
                                              stacked_params)
        self._opt = self._fresh_opt()

    def take_params(self) -> Any:
        """The promotion handoff: returns the current shadow tree and
        re-copies it as the new working tree, so the engine owns one
        pytree and later donated steps can never invalidate it."""
        import jax
        import jax.numpy as jnp
        promoted = self._params
        self._params = jax.tree_util.tree_map(jnp.copy, promoted)
        return promoted

    def host_members(self) -> List[Dict[str, Dict[str, np.ndarray]]]:
        """The current shadow params as N host member pytrees (the
        spill/restore copies the residency manager keeps) — one
        device fetch per leaf, called OFF the promotion critical
        path."""
        out: List[Dict[str, Dict[str, np.ndarray]]] = []
        for i in range(self.n_members):
            out.append({
                fn: {pn: np.asarray(arr[i])
                     for pn, arr in d.items()}
                for fn, d in self._params.items()})
        return out


class OnlineLearner(Logger):
    """The hive's learning tier: tap + buffers + scavenger thread +
    per-model promotion gates."""

    def __init__(self, residency: Any,
                 environ: Optional[Dict[str, str]] = None) -> None:
        env = environ
        self.residency = residency
        self.tap = TrafficTap(knobs.get(knobs.ONLINE_TAP_FRAC, env))
        self.buffer_rows = int(knobs.get(knobs.ONLINE_BUFFER_ROWS,
                                         env))
        self.holdout_every = int(knobs.get(knobs.ONLINE_HOLDOUT_EVERY,
                                           env))
        self.micro_batch = int(knobs.get(knobs.ONLINE_MICRO_BATCH,
                                         env))
        self.min_steps = int(knobs.get(knobs.ONLINE_MIN_STEPS, env))
        self.margin = float(knobs.get(knobs.ONLINE_PROMOTE_MARGIN,
                                      env))
        self.idle_s = max(0.0, float(knobs.get(knobs.ONLINE_IDLE_MS,
                                               env))) / 1000.0
        self.slo_p99_ms = float(knobs.get(knobs.ONLINE_SLO_P99_MS,
                                          env))
        self.lr_scale = float(knobs.get(knobs.ONLINE_LR_SCALE, env))
        self.duty = min(1.0, max(0.01, float(
            knobs.get(knobs.ONLINE_DUTY, env))))
        #: monotonic ts before which the duty throttle vetoes the
        #: next step (rest = cost * (1-duty)/duty after each one)
        self._rest_until = 0.0
        self._lock = witness.lock("online.learner")
        self._trainers: Dict[str, ShadowTrainer] = {}
        self._gates: Dict[str, PromotionGate] = {}
        #: EMA of the fetched step wall (ms) — the SLO headroom input
        self._step_ema_ms: Optional[float] = None
        self._stop = threading.Event()
        #: the elastic fleet's first degradation rung: a suspended
        #: learner stays armed (taps keep filling the buffers) but
        #: takes no steps — under sustained load there are no idle
        #: gaps to scavenge, and the ladder wants the capacity back
        self._suspended = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- arming --------------------------------------------------------

    def arm_model(self, name: str) -> bool:
        """Arm learning for one hosted model.  Needs the packaged
        workflow's gradient chain (meta["workflow"]) and a resident
        engine; returns False (and stays silent on the serving path)
        otherwise."""
        m = self.residency.models[name]
        w = m.meta.get("workflow")
        gds = list(getattr(w, "gds", []) or [])
        evaluator = getattr(w, "evaluator", None)
        if w is None or evaluator is None or \
                not any(gd is not None for gd in gds):
            self.warning("online: model %r has no trainable chain; "
                         "not armed", name)
            return False
        engine = self.residency.ensure(name)
        # the package seed when the hive recorded one, else a stable
        # name digest — NEVER hash() (salted per process: it would
        # break the offline oracle's replay of the sample stream)
        import zlib
        seed = int(m.meta.get("seed")
                   or (zlib.crc32(name.encode()) & 0x7FFFFFFF))
        buf = ReplayBuffer(self.buffer_rows, seed=seed,
                           holdout_every=self.holdout_every,
                           dequant=getattr(w.loader, "dequant", None))
        trainer = ShadowTrainer(
            m.forwards, gds, evaluator, self.residency.device,
            engine.stacked_params, seed=seed,
            lr_scale=self.lr_scale, micro_batch=self.micro_batch)
        gate = PromotionGate(name, self.residency, self.margin,
                             self.min_steps)
        with self._lock:
            self._trainers[name] = trainer
            self._gates[name] = gate
        self.tap.arm(name, buf)
        # the shadow's stacked params + the buffer's host bytes are
        # real residency cost: charge them so the LRU budget sees them
        self.residency.reserve(f"{name}@shadow", m.param_bytes,
                               pool="train")
        telemetry.event(events.EV_ONLINE_ARMED, model=name,
                        members=trainer.n_members,
                        micro_batch=self.micro_batch,
                        buffer_rows=self.buffer_rows)
        self.info("online: armed %r (%d members, micro_batch=%d, "
                  "buffer=%d rows)", name, trainer.n_members,
                  self.micro_batch, self.buffer_rows)
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="online-scavenger")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def suspend(self) -> None:
        """Park the scavenger (idempotent): tapping continues, steps
        and gate rounds stop until :meth:`resume`."""
        if not self._suspended.is_set():
            self._suspended.set()
            self.info("online: learner SUSPENDED (degradation rung)")

    def resume(self) -> None:
        if self._suspended.is_set():
            self._suspended.clear()
            self.info("online: learner resumed")

    @property
    def suspended(self) -> bool:
        return self._suspended.is_set()

    # -- the scavenger loop -------------------------------------------

    def _serving_idle(self) -> bool:
        """True when every resident engine's batcher is empty, quiet
        for idle_s, and nothing is in flight (plain int reads — no
        lock edge into the batcher from the scavenger)."""
        now = time.monotonic()
        for m in self.residency.models.values():
            b = getattr(m.engine, "_batcher", None) \
                if m.engine is not None else None
            if b is None:
                continue
            if b.pending_rows > 0 or \
                    now - b.last_activity < self.idle_s:
                return False
        return True

    def _rest_for(self, cost_s: float) -> float:
        """Duty-cycle rest after ``cost_s`` of scavenged work, capped
        at 1s (the compile firing must not park the learner)."""
        return min(1.0, cost_s * (1.0 - self.duty) / self.duty)

    def _headroom(self) -> bool:
        """The SLO check: a step whose EMA cost exceeds the target
        p99 would BECOME the p99 of a request landing under it."""
        if self.slo_p99_ms <= 0 or self._step_ema_ms is None:
            return True
        return self._step_ema_ms <= self.slo_p99_ms

    def _loop(self) -> None:
        poll = max(0.001, self.idle_s / 2.0 if self.idle_s else 0.001)
        while not self._stop.wait(poll):
            if self._suspended.is_set():
                continue
            with self._lock:
                items = list(self._trainers.items())
            for name, trainer in items:
                if self._stop.is_set():
                    return
                buf = self.tap.buffers.get(name)
                gate = self._gates.get(name)
                if buf is None or gate is None:
                    continue
                if buf.train_rows < self.micro_batch:
                    gate.state = "filling"
                    continue
                if time.monotonic() < self._rest_until:
                    continue   # duty throttle: resting, not busy
                if not self._serving_idle() or not self._headroom():
                    telemetry.counter(
                        events.CTR_ONLINE_STEPS_SKIPPED_BUSY).inc()
                    continue
                try:
                    self._step_one(name, trainer, buf, gate)
                except Exception as e:  # noqa: BLE001 — the learner
                    # must never take down the process it scavenges
                    self.error("online: step failed for %r "
                               "(disarming it): %s: %s", name,
                               type(e).__name__, e)
                    with self._lock:
                        self._trainers.pop(name, None)

    def _step_one(self, name: str, trainer: ShadowTrainer,
                  buf: ReplayBuffer, gate: PromotionGate) -> None:
        if gate.state == "filling":
            gate.state = "training"
        rng = trainer.sample_rng()
        t0 = time.perf_counter()
        x, labels = buf.sample(trainer.micro_batch, rng)
        trainer.step(x, labels, buf.version)
        dt = time.perf_counter() - t0
        gate.last_step_ts = time.monotonic()
        # the duty throttle rests the WHOLE step cost (host sample +
        # decode + dispatch): on a shared-core box the GIL the decode
        # loop holds is serving capacity too.  Rest is capped at 1s
        # so the one-time compile firing cannot park the learner.
        self._rest_until = gate.last_step_ts + self._rest_for(dt)
        ms = 1000.0 * dt
        self._step_ema_ms = ms if self._step_ema_ms is None else \
            0.8 * self._step_ema_ms + 0.2 * ms
        telemetry.counter(events.CTR_ONLINE_STEPS).inc()
        telemetry.counter(events.CTR_ONLINE_STEP_ROWS).inc(
            trainer.micro_batch)
        telemetry.counter(events.CTR_ONLINE_STEP_SECONDS).inc(dt)
        telemetry.histogram(
            events.HIST_ONLINE_STEP_DISPATCH_SECONDS).record(dt)
        self._publish_gauges(name, trainer, buf, gate)
        if gate.due(trainer.steps):
            self._gate_round(name, trainer, buf, gate)

    def _gate_round(self, name: str, trainer: ShadowTrainer,
                    buf: ReplayBuffer, gate: PromotionGate) -> None:
        # a gate round is several step-lengths of chip + host time —
        # the worst thing the scavenger can put under a request.  If
        # traffic arrived during the step that made this round due,
        # defer it (last_gate_step stays put, so it re-arms after the
        # very next idle step).
        if not self._serving_idle():
            telemetry.counter(
                events.CTR_ONLINE_STEPS_SKIPPED_BUSY).inc()
            return
        # ...and bound the scored slice to the NEWEST rows: the cap
        # keeps the round's cost fixed no matter how full the holdout
        # partition is (the 1.2x p99 acceptance bar)
        hx, hl = buf.holdout(limit=8 * self.micro_batch)
        if len(hx) < max(4, self.micro_batch // 8):
            gate.last_gate_step = trainer.steps   # wait a full round
            return
        t0 = time.perf_counter()
        m = self.residency.models[name]
        engine = m.engine
        if engine is None or not m.resident:
            # spilled under LRU pressure: nothing to score against
            # (or swap into) — the round re-arms after the restore
            return

        def _busy() -> bool:
            return not self._serving_idle()

        shadow_err = trainer.error_pct(trainer._params, hx, hl,
                                       abort=_busy)
        incumbent_err = None if shadow_err is None else \
            trainer.error_pct(engine.stacked_params, hx, hl,
                              abort=_busy)
        if shadow_err is None or incumbent_err is None:
            # traffic arrived mid-sweep: the round defers (and
            # re-arms after the very next idle step)
            telemetry.counter(
                events.CTR_ONLINE_STEPS_SKIPPED_BUSY).inc()
            return
        gate_dt = time.perf_counter() - t0
        telemetry.histogram(events.HIST_ONLINE_GATE_SECONDS).record(
            gate_dt)
        # the round's cost counts against the duty budget too
        verdict = gate.decide(trainer.steps, shadow_err,
                              incumbent_err)
        # the tap's recent trace-id tail: the verdict journals WITH
        # the lineage of the traffic that produced these params
        lineage = self.tap.lineage_sample(name)
        if verdict == "promote":
            gate.promote(trainer.take_params(), trainer.steps,
                         lineage=lineage)
            # host spill/restore copies refresh OFF the swap path
            self.residency.refresh_host_params(
                name, trainer.host_members())
        elif verdict == "rollback":
            trainer.reset_from(engine.stacked_params)
            gate.rollback(trainer.steps, lineage=lineage)
        # the whole round — scoring, tree copies, the host param
        # refresh — is scavenged work; it all pays duty rest
        self._rest_until = time.monotonic() + \
            self._rest_for(time.perf_counter() - t0)
        self._publish_gauges(name, trainer, buf, gate)

    def _publish_gauges(self, name: str, trainer: ShadowTrainer,
                        buf: ReplayBuffer,
                        gate: PromotionGate) -> None:
        nbytes = buf.nbytes
        self.residency.reserve(f"{name}@buffer", nbytes,
                               pool="scratch")
        telemetry.gauge(events.GAUGE_ONLINE_BUFFER_ROWS).set(
            buf.train_rows + buf.holdout_rows)
        telemetry.gauge(events.GAUGE_ONLINE_BUFFER_BYTES).set(nbytes)
        telemetry.gauge(
            f"online.model.{name}.buffer_rows").set(buf.train_rows)
        telemetry.gauge(
            f"online.model.{name}.steps").set(trainer.steps)
        telemetry.gauge(
            f"online.model.{name}.gate_state").set(gate.state_code())

    # -- introspection (op=learn) --------------------------------------

    def status(self) -> Dict[str, Dict[str, Any]]:
        """{model: learner row} with protocol-declared keys — the
        hive's op=learn payload and the obs/web panel feed."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            items = list(self._trainers.items())
            gates = dict(self._gates)
        for name, trainer in items:
            buf = self.tap.buffers.get(name)
            gate = gates.get(name)
            if buf is None or gate is None:
                continue
            row = {
                "state": gate.state,
                "steps": trainer.steps,
                "buffer_rows": buf.train_rows,
                "holdout_rows": buf.holdout_rows,
                "buffer_bytes": buf.nbytes,
                "promotions": gate.promotions,
                "rollbacks": gate.rollbacks,
                "shadow_error_pct": gate.shadow_error_pct,
                "incumbent_error_pct": gate.incumbent_error_pct,
                "margin": gate.margin,
                "time_to_serve_ms": gate.time_to_serve_ms,
            }
            out[name] = row
        return out
