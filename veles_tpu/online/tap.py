"""The traffic tap: live request rows -> the replay buffer.

Sits on the hive's admission path (after the engine accepted the
request, so rejected/misshapen rows never pollute the buffer) and
mirrors a DETERMINISTIC fraction of requests: an error-diffusion
accumulator (``acc += frac; tap when acc >= 1``) rather than a coin
flip, so the tapped subsequence of any request stream is exactly
reproducible — the property the online-vs-offline training oracle and
the poison drill both pin.

Labels: a request may carry its ground truth inline (the ``label``
wire field — one int per row, or one scalar for the whole request) or
deliver it LATE as a ``{"label_of": <wire id>, "label": [...]}`` line
once the truth is known (the click/outcome case).  Tapped-but-
unlabeled rows wait in a bounded FIFO pending map keyed by wire id;
labels for ids that were never tapped (or already evicted) count
``online.label_orphans`` and drop — an orphan label is operator
telemetry, never an error.

Fault point ``online.poison_batch`` (chaos drill): scrambles the
labels of a tapped batch deterministically before they enter the
buffer; qualified by ``model`` and ``slot`` (train/holdout) so the
drill can poison exactly the training stream and prove the gate's
held-out slice refuses to promote garbage.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, faults, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.online.buffer import ReplayBuffer

#: tapped-but-unlabeled requests a model may hold before the oldest
#: pending row is evicted (late labels for it then count as orphans)
PENDING_CAP = 256

#: contributing trace ids retained per model — the promotion gate's
#: lineage sample (a bounded tail, not the full training history)
LINEAGE_CAP = 32


class TrafficTap:
    """Per-hive tap over every learning model's admitted traffic."""

    def __init__(self, frac: float) -> None:
        self.frac = max(0.0, min(1.0, float(frac)))
        self._lock = witness.lock("online.tap")
        #: per-model error-diffusion accumulator
        self._acc: Dict[str, float] = {}
        #: wire id -> (model, rows) awaiting a late label
        self._pending: "Dict[Any, Tuple[str, np.ndarray]]" = {}
        #: model name -> ReplayBuffer (armed by the learner)
        self.buffers: Dict[str, ReplayBuffer] = {}
        #: model name -> recent trace ids of tapped traffic — the
        #: Flightline lineage a promotion carries back to the live
        #: requests that trained it
        self._lineage: Dict[str, "deque[str]"] = {}

    def arm(self, model: str, buffer: ReplayBuffer) -> None:
        with self._lock:
            self.buffers[model] = buffer
            self._acc.setdefault(model, 0.0)

    # -- the admission-path hook ---------------------------------------

    def tap(self, model: str, jid: Any, rows: np.ndarray,
            label: Optional[Any] = None,
            ctx: Optional[trace.TraceContext] = None) -> None:
        """One admitted request.  Samples deterministically; labeled
        rows go straight to the buffer, unlabeled ones park for a
        ``label_of`` join.  ``ctx`` (the request's Flightline span)
        enters the model's bounded lineage tail so the NEXT promotion
        can name the traffic that trained it."""
        with self._lock:
            buf = self.buffers.get(model)
            if buf is None or self.frac <= 0.0:
                return
            acc = self._acc.get(model, 0.0) + self.frac
            take = acc >= 1.0
            if take:
                acc -= 1.0
            self._acc[model] = acc
            if take and ctx is not None and ctx.sampled:
                self._lineage.setdefault(
                    model, deque(maxlen=LINEAGE_CAP)).append(
                    ctx.trace_id)
        if not take:
            return
        rows = np.asarray(rows, np.float32)
        telemetry.counter(events.CTR_ONLINE_TAPPED_ROWS).inc(len(rows))
        if label is not None:
            self._admit(model, buf, rows, label)
            return
        with self._lock:
            while len(self._pending) >= PENDING_CAP:
                # FIFO eviction: dicts preserve insertion order
                self._pending.pop(next(iter(self._pending)))
            self._pending[jid] = (model, rows)

    def label_for(self, jid: Any, label: Any) -> bool:
        """A late ``label_of`` line: join by wire id.  False (and an
        orphan count) when the id was never tapped or already
        evicted."""
        with self._lock:
            hit = self._pending.pop(jid, None)
        if hit is None:
            telemetry.counter(events.CTR_ONLINE_LABEL_ORPHANS).inc()
            return False
        model, rows = hit
        with self._lock:
            buf = self.buffers.get(model)
        if buf is None:
            return False
        self._admit(model, buf, rows, label)
        return True

    def _admit(self, model: str, buf: ReplayBuffer,
               rows: np.ndarray, label: Any) -> None:
        labels = np.asarray(label, np.int32).reshape(-1)
        # the slot decision must come BEFORE poison so the fault can
        # qualify on it; peek via the buffer's routing counter
        slot = "holdout" if buf.holdout_every > 0 and \
            (buf._seen + 1) % buf.holdout_every == 0 else "train"
        if faults.fire("online.poison_batch", model=model, slot=slot):
            # deterministic garbage labels (the drill's poisoned
            # stream): same seed -> same corruption, run to run
            labels = faults.rng("online.poison_batch").integers(
                0, max(2, int(labels.max()) + 1),
                size=len(labels) if len(labels) > 1 else len(rows),
                dtype=np.int32)
        got = buf.add(rows, labels)
        assert got == slot, (got, slot)
        telemetry.counter(events.CTR_ONLINE_LABELED_ROWS).inc(
            len(rows))

    # -- introspection -------------------------------------------------

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def lineage_sample(self, model: str) -> List[str]:
        """Recent trace ids of ``model``'s tapped traffic, oldest
        first (empty when tracing is off) — stamped onto promotion /
        rollback journal entries."""
        with self._lock:
            return list(self._lineage.get(model, ()))
