"""The promotion gate: shadow params -> the serving dispatcher,
HBM-to-HBM.

Fine-tuned params are only worth serving if they are measurably
better, and the proof must come from data the trainer never touched:
the tap's held-out slice.  Each gate round scores shadow and incumbent
on that slice through the SAME jitted forward chain and applies a
symmetric hysteresis margin (``$VELES_ONLINE_PROMOTE_MARGIN``, in
error-pct points):

- shadow better by >= margin  -> **promote**: the already-device-
  resident stacked param pytree is handed to the serving engine in one
  atomic pointer swap under the residency lock — params never visit
  the host, no recompile happens (the engine's dispatch jit is already
  warm at the serving shape), and an in-flight dispatch keeps the tree
  it read while later ones read the new one (never torn — the
  ``online.swap_mid_request`` chaos drill races dispatches against the
  swap and asserts oracle-clean answers);
- shadow worse by >= margin   -> **rollback**: the shadow resets to a
  device copy of the incumbent's params (momentum cleared) and the
  regression journals — this is what catches an
  ``online.poison_batch`` label-poisoned training stream;
- within the margin            -> keep training.

``online.time_to_serve`` — the ROADMAP item-4 handoff metric, last
fine-tune step to first request served on the new params — is armed at
swap time through the engine's next-dispatch hook.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from veles_tpu import events, faults, telemetry, trace

#: gate lifecycle states (numeric code = list index, for the
#: ``online.model.<name>.gate_state`` gauge family)
GATE_STATES = ("filling", "training", "promoted", "rolled_back")


class PromotionGate:
    """Per-model gate bookkeeping + the swap itself."""

    def __init__(self, model: str, residency: Any, margin: float,
                 min_steps: int) -> None:
        self.model = model
        self.residency = residency
        self.margin = float(margin)
        self.min_steps = max(1, int(min_steps))
        self.state = "filling"
        self.last_gate_step = 0
        #: promote/rollback hysteresis: after a verdict that moved
        #: params, the gate rests 4 full rounds — back-to-back
        #: promote/rollback churn on a noisy held-out slice is pure
        #: param thrash (and its tree copies are serving-latency tax)
        self.cooldown_until_step = 0
        self.promotions = 0
        self.rollbacks = 0
        self.shadow_error_pct = None
        self.incumbent_error_pct = None
        #: monotonic ts of the shadow's last fine-tune step — the
        #: time_to_serve clock starts here
        self.last_step_ts = None
        self.time_to_serve_ms = None

    def due(self, steps: int) -> bool:
        """Is a gate evaluation due after ``steps`` total steps?"""
        return steps - self.last_gate_step >= self.min_steps \
            and steps >= self.cooldown_until_step

    def decide(self, steps: int, shadow_err: float,
               incumbent_err: float) -> str:
        """Record one gate round; returns "promote" / "rollback" /
        "continue"."""
        self.last_gate_step = steps
        self.shadow_error_pct = shadow_err
        self.incumbent_error_pct = incumbent_err
        if incumbent_err - shadow_err >= self.margin:
            verdict = "promote"
        elif shadow_err - incumbent_err >= self.margin:
            verdict = "rollback"
        else:
            verdict = "continue"
        telemetry.event(events.EV_ONLINE_GATE, model=self.model,
                        steps=steps,
                        shadow_error_pct=round(shadow_err, 4),
                        incumbent_error_pct=round(incumbent_err, 4),
                        margin=self.margin, verdict=verdict)
        return verdict

    def promote(self, stacked_params: Any, steps: int,
                lineage: Optional[List[str]] = None) -> None:
        """Hand the shadow's device-resident params to the serving
        engine.  The ``online.swap_mid_request`` stall fires BEFORE
        the residency lock (a drill must widen the race window, not
        create a blocking-under-lock hazard).  ``lineage`` (the tap's
        recent trace-id tail) journals WITH the promotion — the
        Evergreen chain stays causally traceable from a served
        promotion back to the live traffic that trained it."""
        f = faults.fire("online.swap_mid_request", model=self.model)
        if f:
            time.sleep(float(f.get("seconds", 0.25)))
        t0 = time.perf_counter()
        engine = self.residency.swap_params(self.model, stacked_params)
        swap_ms = 1000.0 * (time.perf_counter() - t0)
        last_step = self.last_step_ts

        def _first_served() -> None:
            if last_step is None:
                return
            dt = time.monotonic() - last_step
            self.time_to_serve_ms = round(1000.0 * dt, 3)
            telemetry.gauge(events.GAUGE_ONLINE_TIME_TO_SERVE).set(
                round(dt, 6))

        engine.notify_next_dispatch(_first_served)
        self.promotions += 1
        self.state = "promoted"
        self.cooldown_until_step = steps + 4 * self.min_steps
        telemetry.counter(events.CTR_ONLINE_PROMOTIONS).inc()
        telemetry.event(
            events.EV_ONLINE_PROMOTED, model=self.model, steps=steps,
            shadow_error_pct=self.shadow_error_pct,
            incumbent_error_pct=self.incumbent_error_pct,
            swap_ms=round(swap_ms, 3),
            lineage=list(lineage) if lineage else None)
        trace.record("gate.promote", model=self.model, steps=steps,
                     lineage_n=len(lineage) if lineage else 0)
        trace.dump("promote")

    def rollback(self, steps: int,
                 lineage: Optional[List[str]] = None) -> None:
        self.rollbacks += 1
        self.state = "rolled_back"
        self.cooldown_until_step = steps + 4 * self.min_steps
        telemetry.counter(events.CTR_ONLINE_ROLLBACKS).inc()
        telemetry.event(
            events.EV_ONLINE_ROLLBACK, model=self.model, steps=steps,
            shadow_error_pct=self.shadow_error_pct,
            incumbent_error_pct=self.incumbent_error_pct,
            lineage=list(lineage) if lineage else None)
        trace.record("gate.rollback", model=self.model, steps=steps,
                     lineage_n=len(lineage) if lineage else 0)
        trace.dump("rollback")

    def state_code(self) -> int:
        try:
            return GATE_STATES.index(self.state)
        except ValueError:
            return -1
