"""Device backends.

Reference parity: veles/backends.py — a ``Device`` base with OpenCL /
CUDA / numpy engines, context management, and a per-device capability
database for autotuned kernel block sizes.

TPU-first design: two engines survive — ``NumpyDevice`` (the pure-host
golden path, reference's "numpy backend") and ``JaxDevice`` (TPU, or
XLA:CPU for tests).  There is no block-size autotuning database: tiling
onto the MXU is XLA's job.  ``JaxDevice`` owns the jit cache and the
compute dtype policy (bfloat16 matmuls by default on TPU — the MXU's
native format — with float32 params).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import numpy as np

from veles_tpu.logger import Logger


class Device(Logger):
    """Base device."""

    is_jax = False
    backend_name = "base"

    def __init__(self) -> None:
        self.compute_dtype = np.float32
        #: cumulative host->device bytes shipped through ``put`` —
        #: the transfer-accounting hook the quantized-ingest tests and
        #: bench read.  ``put`` is dtype-preserving by contract: a
        #: uint8 upload must stay 1 byte/element in HBM (the 4x
        #: residency win), never silently widen to the compute dtype.
        self.h2d_bytes = 0

    def put(self, array: np.ndarray) -> Any:
        return array

    def get(self, buf: Any) -> np.ndarray:
        return np.asarray(buf)

    def zeros(self, shape, dtype=np.float32) -> Any:
        """A zero buffer in this device's memory (host numpy here; jax
        devices generate it on-device — no host array, no transfer)."""
        return np.zeros(shape, dtype)

    def compile(self, fn: Callable, **jit_kwargs: Any) -> Callable:
        return fn

    def synchronize(self) -> None:
        pass

    def __repr__(self) -> str:
        return f"<{type(self).__name__}>"


class NumpyDevice(Device):
    """Pure-host execution; the bit-reproducible golden backend
    (reference: veles/backends.py NumpyDevice)."""

    backend_name = "numpy"


def _harden_compile_cache_writes() -> None:
    """Make the on-disk XLA cache's entry writes ATOMIC (idempotent).

    jax's ``LRUCache.put`` with eviction disabled (the default,
    ``jax_compilation_cache_max_size=-1``) takes no lock and writes
    the entry with a direct ``write_bytes`` — NOT temp-file+rename.
    Two processes compiling the same program concurrently (parallel GA
    workers, a bench phase next to a test run) interleave their writes
    and leave a torn executable on disk; every later process that gets
    a cache hit on that key then ABORTS inside xla_extension while
    deserializing it (observed on this box as deterministic
    ``Fatal Python error: Aborted`` at the same test, session after
    session, until the directory was wiped — the same failure family
    as the round-5 foreign-version GPFs).  The patch routes the write
    through a pid-suffixed temp file + ``os.replace`` in the same
    directory, so a reader sees either no entry or a complete one,
    and a writer killed mid-write leaves only a dead ``.tmp`` that is
    never served.  The eviction-enabled path already serializes both
    sides under a file lock and is left alone.
    """
    try:
        from jax._src import lru_cache as lc
    except Exception:  # noqa: BLE001 — internal layout may move
        return
    orig_put = lc.LRUCache.put
    if getattr(orig_put, "_veles_atomic", False):
        return
    cache_suffix = getattr(lc, "_CACHE_SUFFIX", None)
    atime_suffix = getattr(lc, "_ATIME_SUFFIX", None)
    if cache_suffix is None:
        return  # unknown internals: leave jax's behaviour untouched

    import os
    import time

    @functools.wraps(orig_put)
    def atomic_put(self, key, val):
        if getattr(self, "eviction_enabled", True) or not key:
            return orig_put(self, key, val)  # lock-serialized already
        final = self.path / f"{key}{cache_suffix}"
        if final.exists():
            return
        tmp = self.path / f"{key}{cache_suffix}.tmp{os.getpid()}"
        try:
            tmp.write_bytes(val)
            os.replace(tmp, final)
            if atime_suffix is not None:
                (self.path / f"{key}{atime_suffix}").write_bytes(
                    time.time_ns().to_bytes(8, "little"))
        except OSError:
            # cache is an optimization: a failed write (disk full,
            # perms) must not fail the compile that produced ``val``
            try:
                tmp.unlink()
            except OSError:
                pass

    atomic_put._veles_atomic = True
    lc.LRUCache.put = atomic_put


def _enable_persistent_compile_cache() -> None:
    """Point XLA at an on-disk executable cache (idempotent).

    The big fused train-step programs take minutes to compile through
    the tunneled TPU platform; the persistent cache makes every later
    process (reruns of bench.py, GA workers, the driver) load them in
    milliseconds.  Opt out with VELES_TPU_NO_COMPILE_CACHE=1; relocate
    with VELES_TPU_COMPILE_CACHE_DIR.

    The default directory is namespaced by the jaxlib version PLUS an
    ``aw`` (atomic-writes) era tag: deserializing an executable
    written by a different build — or a torn entry written before
    ``_harden_compile_cache_writes`` existed — crashes inside
    xla_extension, so the namespace retires every directory the old
    non-atomic writers could have corrupted, exactly like the round-5
    version-keying retired the flat dir.

    CPU-only processes NEVER enable the cache (Faultline root cause):
    on this jaxlib, XLA:CPU executables round-tripped through the
    persistent cache deserialize to numerically WRONG programs —
    reproduced as nondeterministic NaN trainings (~50% of identical
    runs once entries were warm; bit-deterministic healthy with the
    cache cold or off) plus the GPF/SIGABRT family, striking randomly
    because the trace fingerprint also varies run to run.  CPU
    compiles here are sub-second, so the cache bought nothing but the
    corruption; the tunneled TPU's minutes-long compiles keep it.
    """
    import os
    if os.environ.get("VELES_TPU_NO_COMPILE_CACHE"):
        return
    path = os.environ.get("VELES_TPU_COMPILE_CACHE_DIR")
    try:
        import jax
        if jax.default_backend() == "cpu":
            return
        _harden_compile_cache_writes()
        if path is None:
            path = _compile_cache_default_dir()
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


def _compile_cache_default_dir() -> str:
    """The era-namespaced default cache dir (split out so tests can
    assert the retirement naming without activating the cache)."""
    import os

    import jax
    ver = getattr(jax, "__version__", "unknown")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "veles_tpu", f"xla_cache-{ver}-aw")


class JaxDevice(Device):
    """An XLA device (TPU in production; CPU for tests/simulation).

    ``compile`` wraps ``jax.jit`` with buffer donation support — donated
    inputs are the rebind targets that give Vectors in-place update
    semantics in HBM.
    """

    is_jax = True
    backend_name = "jax"

    def __init__(self, platform: Optional[str] = None,
                 ordinal: int = 0, compute_dtype: Any = None) -> None:
        super().__init__()
        import jax
        self._jax = jax
        # LOCAL devices: in a multihost run jax.devices() enumerates
        # every process's chips and ordinal 0 would be process 0's —
        # non-addressable from its peers, so their first upload died
        # with "Cannot copy array to non-addressable device".  A
        # single-device engine must own one of ITS OWN chips; global
        # enumeration belongs to mesh construction (parallel/mesh.py).
        devices = jax.local_devices(backend=platform) if platform \
            else jax.local_devices()
        self.jax_device = devices[ordinal]
        self.platform = self.jax_device.platform
        # no-op for CPU-only processes — see the function's docstring
        # (XLA:CPU executables do not survive the cache round-trip)
        _enable_persistent_compile_cache()
        if compute_dtype is None:
            import jax.numpy as jnp
            compute_dtype = jnp.bfloat16 if self.platform == "tpu" \
                else jnp.float32
        self.compute_dtype = compute_dtype
        self._jit_cache: Dict[Any, Callable] = {}

    def put(self, array: np.ndarray) -> Any:
        # Copy before upload: device_put may alias host memory (XLA:CPU
        # is zero-copy) or defer the H2D transfer, and the map/unmap
        # protocol lets callers mutate the host buffer right after
        # unmap() while async-dispatched steps still read it.  The copy
        # makes uploads value-snapshots, restoring the reference's
        # enqueue-time semantics.  dtype-preserving: uint8 stays uint8
        # in HBM (quantized ingest's 4x residency cut depends on it).
        arr = np.array(array, copy=True)
        self.h2d_bytes += arr.nbytes
        from veles_tpu.engine import core as engine_core
        return engine_core.put(arr, self.jax_device)

    def get(self, buf: Any) -> np.ndarray:
        return np.asarray(buf)

    def zeros(self, shape, dtype=np.float32) -> Any:
        import jax.numpy as jnp
        with self._jax.default_device(self.jax_device):
            return jnp.zeros(shape, dtype)

    def compile(self, fn: Callable, **jit_kwargs: Any) -> Callable:
        return self._jax.jit(fn, **jit_kwargs)

    def cached_compile(self, key: Any, make_fn: Callable[[], Callable],
                       **jit_kwargs: Any) -> Callable:
        """Memoized jit: units ask for their step function by key so
        re-initialization reuses the compiled executable."""
        if key not in self._jit_cache:
            self._jit_cache[key] = self.compile(make_fn(), **jit_kwargs)
        return self._jit_cache[key]

    def synchronize(self) -> None:
        from veles_tpu.engine import core as engine_core
        (engine_core.put(0.0, self.jax_device) + 0).block_until_ready()

    def __repr__(self) -> str:
        return f"<JaxDevice {self.jax_device}>"


class TPUDevice(JaxDevice):
    backend_name = "tpu"

    def __init__(self, ordinal: int = 0, compute_dtype: Any = None) -> None:
        super().__init__(platform=None, ordinal=ordinal,
                         compute_dtype=compute_dtype)


@functools.lru_cache(maxsize=None)
def make_device(backend: str = "auto") -> Device:
    """Factory: 'numpy', 'tpu'/'jax', 'cpu' (XLA:CPU), or 'auto'
    (TPU if visible, else XLA:CPU, else numpy)."""
    if backend == "numpy":
        return NumpyDevice()
    if backend in ("tpu", "jax", "auto"):
        try:
            import jax
            jax.devices()
            return JaxDevice()
        except Exception:
            if backend == "auto":
                return NumpyDevice()
            raise
    if backend == "cpu":
        return JaxDevice(platform="cpu")
    raise ValueError(f"unknown backend {backend!r}")
