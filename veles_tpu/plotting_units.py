"""Plotting units: error curves, confusion matrix, weight images.

Reference parity: veles/plotting_units.py + veles/plotter.py —
``AccumulatingPlotter`` (error/loss curves over epochs),
``MatrixPlotter`` (confusion matrix), ``Weights2D`` (first-layer filter
images).  Units sit after Decision in the control graph and fire once
per train-epoch end; each emits a plot event onto the graphics bus
(veles_tpu/graphics_server.py) which renders to files and/or publishes
to live zmq subscribers.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from veles_tpu.graphics_server import get_server
from veles_tpu.loader.base import CLASS_NAMES
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit


class Plotter(Unit):
    """Base plotting unit: skipped unless the epoch just ended."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.decision = None

    def link_decision(self, decision) -> None:
        self.decision = decision
        self.gate_skip = Bool.from_expr(
            lambda d=decision: not bool(d.epoch_ended_flag))

    def make_event(self) -> Optional[dict]:
        raise NotImplementedError

    def run(self) -> None:
        event = self.make_event()
        if event is not None:
            event.setdefault("plotter", self.name)
            get_server().enqueue(event)


class AccumulatingPlotter(Plotter):
    """Error% (or loss) curves per class over epochs, from Decision's
    history rows."""

    def __init__(self, workflow=None, field: str = "error_pct",
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.field = field

    def make_event(self) -> Optional[dict]:
        series = {}
        for klass_name in CLASS_NAMES:
            rows = [h for h in self.decision.history
                    if h["class"] == klass_name]
            if rows:
                series[klass_name] = ([h["epoch"] for h in rows],
                                      [h[self.field] for h in rows])
        if not series:
            return None
        return {"kind": "curves", "series": series,
                "ylabel": self.field,
                "title": f"{self.workflow.name}: {self.field}"}


class MatrixPlotter(Plotter):
    """Confusion matrix heat map for the last completed validation (or
    train) epoch; Decision stashes per-class snapshots so the plot is
    per-epoch, not run-cumulative."""

    def __init__(self, workflow=None, evaluator=None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.evaluator = evaluator

    def make_event(self) -> Optional[dict]:
        from veles_tpu.loader.base import TRAIN, VALID
        per = getattr(self.decision, "confusion_per_class", None)
        conf = None
        klass = None
        if per is not None:
            for klass in (VALID, TRAIN):
                if per[klass] is not None:
                    conf = per[klass]
                    break
        if conf is None:
            return None
        return {"kind": "matrix", "matrix": np.asarray(conf),
                "title": f"{self.workflow.name}: confusion "
                         f"({CLASS_NAMES[klass]})"}


class Weights2D(Plotter):
    """First-layer weights rendered as image tiles (the reference's
    filter-visualization plotter)."""

    def __init__(self, workflow=None, unit=None, limit: int = 25,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.unit = unit
        self.limit = limit

    def make_event(self) -> Optional[dict]:
        f = self.unit
        if f is None or not f.weights:
            return None
        w = np.asarray(f.weights.map_read())
        tiles = self._to_tiles(w)
        if tiles is None:
            return None
        return {"kind": "image_grid", "tiles": tiles[:self.limit],
                "title": f"{self.workflow.name}: {f.name} weights"}

    @staticmethod
    def _to_tiles(w: np.ndarray) -> Optional[List[np.ndarray]]:
        if w.ndim == 4:            # conv HWIO -> one tile per out-chan
            h, kw, cin, cout = w.shape
            t = np.transpose(w, (3, 0, 1, 2))
            if cin == 3:
                lo, hi = t.min(), t.max()
                return list((t - lo) / max(hi - lo, 1e-12))
            return list(t.mean(-1))
        if w.ndim == 2:            # FC: rows reshaped if square-able
            n = w.shape[0]
            side = int(np.sqrt(n))
            if side * side == n:
                return list(np.transpose(
                    w.reshape(side, side, -1), (2, 0, 1)))
            side3 = int(np.sqrt(n // 3)) if n % 3 == 0 else 0
            if side3 and side3 * side3 * 3 == n:
                t = np.transpose(w.reshape(side3, side3, 3, -1),
                                 (3, 0, 1, 2))
                lo, hi = t.min(), t.max()
                return list((t - lo) / max(hi - lo, 1e-12))
            return None
        return None
