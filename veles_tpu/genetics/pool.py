"""Chip-owning GA evaluation pool — the ``tpu-evaluator`` execution
mode (round-4/5 VERDICT: the GA idled the chip by default because
``auto`` + parallel workers had to fall back to CPU to avoid a device
race).

Topology: exactly ONE evaluator subprocess (genetics/worker.py
``--serve``) acquires the accelerator at startup and evaluates every
genome of the run on it, consuming jobs from a queue; the parent's N
"workers" become host-side PREP threads that assemble job payloads
(genome -> per-job seed -> wire JSON, plus any caller-supplied staging
hook, e.g. materializing a dataset the genome's config points at).
Prep threads never construct a device, so N > 1 workers can no longer
race to initialize an exclusive TPU — the race is gone by
construction, not by policy fallback.

Failure contract (same as the subprocess-per-genome mode): a genome
that crashes the evaluator or exceeds the per-genome timeout scores
``inf``; the pool restarts the evaluator and the remaining genomes of
the generation continue.  The GA run never dies to one bad gene.
"""

from __future__ import annotations

import json
import queue
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from veles_tpu.logger import Logger


class ChipEvaluatorPool(Logger):
    """One serve-mode evaluator owns the device; N prep threads feed
    its queue.

    ``worker_cmd`` is the full evaluator argv (``... worker --serve
    workflow.py [config.py ...] -b BACKEND -s SEED``).  ``prep`` is an
    optional host-side staging hook run by the prep threads on each
    genome's values dict before submission (config/data preparation —
    the CPU-parallel share of an evaluation).
    """

    def __init__(self, worker_cmd: List[str], workers: int = 2,
                 timeout: float = 3600.0, seed: int = 1234,
                 prep: Optional[Callable[[Dict[str, Any]],
                                         Dict[str, Any]]] = None
                 ) -> None:
        self.worker_cmd = list(worker_cmd)
        self.workers = max(1, workers)
        self.timeout = timeout
        self.seed = seed
        self.prep = prep
        self.hello: Optional[Dict[str, Any]] = None
        self._proc: Optional[subprocess.Popen] = None
        self._lines: "queue.Queue[Optional[str]]" = queue.Queue()
        self._reader: Optional[threading.Thread] = None
        self._next_id = 0

    # -- evaluator lifecycle ------------------------------------------

    def start(self) -> Dict[str, Any]:
        """Spawn the evaluator and block on its hello line — the ONLY
        device probe of the whole GA run, and it happens in the child.
        Returns the hello dict ({"platform", "is_accelerator", ...})."""
        self._proc = subprocess.Popen(
            self.worker_cmd, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, text=True, bufsize=1)
        self._lines = queue.Queue()
        self._reader = threading.Thread(target=self._read_stdout,
                                        args=(self._proc,), daemon=True)
        self._reader.start()
        hello = self._next_json(self.timeout)
        if not hello or not hello.get("ready"):
            self._kill()
            raise RuntimeError(
                f"evaluator did not come up: {hello!r}")
        self.hello = hello
        self.info("chip evaluator up: pid %s on %s (%s)",
                  hello["pid"], hello["platform"], hello["backend"])
        return hello

    @property
    def platform(self) -> str:
        return (self.hello or {}).get("platform", "unknown")

    @property
    def is_accelerator(self) -> bool:
        return bool((self.hello or {}).get("is_accelerator"))

    def close(self) -> None:
        if self._proc is None:
            return
        try:
            if self._proc.poll() is None and self._proc.stdin:
                self._proc.stdin.write(
                    json.dumps({"op": "shutdown"}) + "\n")
                self._proc.stdin.flush()
                self._proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — cleanup must not raise
            pass
        self._kill()

    def __enter__(self) -> "ChipEvaluatorPool":
        if self.hello is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _kill(self) -> None:
        # self.hello is kept: callers may still read the platform of
        # the evaluator that just died
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=10)
        self._proc = None

    def _read_stdout(self, proc) -> None:
        for line in proc.stdout:
            self._lines.put(line)
        self._lines.put(None)  # EOF marker

    def _next_json(self, timeout: float) -> Optional[Dict[str, Any]]:
        """Next parseable JSON line from the evaluator (its training
        runs may also log non-JSON to stdout-adjacent streams; stdout
        itself carries only our protocol, but stay tolerant)."""
        import time
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                line = self._lines.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            if line is None:
                return None  # evaluator died
            line = line.strip()
            if not line:
                continue
            try:
                return json.loads(line)
            except ValueError:
                continue

    # -- evaluation ----------------------------------------------------

    def _prep_jobs(self, values_list: List[Dict[str, Any]]) \
            -> List[Dict[str, Any]]:
        """Fan the host-side staging hook out over the prep threads and
        draw wire ids — the CPU-parallel share of a generation."""
        lock = threading.Lock()

        def prep_one(values):
            if self.prep is not None:
                values = self.prep(dict(values))
            with lock:   # id draw is the only shared state
                self._next_id += 1
                jid = self._next_id
            return {"id": jid, "values": values, "seed": self.seed}

        with ThreadPoolExecutor(self.workers) as pool:
            return list(pool.map(prep_one, values_list))

    def evaluate_many(self, values_list: List[Dict[str, Any]]) \
            -> List[float]:
        """One generation: prep fans out over the thread workers, the
        evaluator consumes the queue in submission order.

        Failure contract: when the evaluator dies or hangs, the job at
        the head of the unresolved queue was in flight — but an
        evaluator-side death (OOM from a previous genome, a crashed
        chip runtime) is not proof of a bad gene, so the in-flight
        genome is RETRIED ONCE on the fresh evaluator before being
        scored inf.  Three consecutive restarts that resolve nothing
        mean the evaluator itself is broken: the remainder scores inf
        rather than restart-looping forever."""
        if self._proc is None or self._proc.poll() is not None:
            self.start()
        jobs = self._prep_jobs(values_list)
        order = [j["id"] for j in jobs]
        fits: Dict[int, float] = {}
        pending = list(jobs)
        retried: set = set()
        barren_restarts = 0
        while pending:
            done = self._run_jobs(pending, fits)
            pending = [j for j in pending if j["id"] not in done]
            if not pending:
                break
            barren_restarts = 0 if done else barren_restarts + 1
            if barren_restarts >= 3:
                self.warning(
                    "evaluator resolved nothing across %d consecutive "
                    "restarts; scoring the remaining %d genomes inf",
                    barren_restarts, len(pending))
                break
            head = pending[0]
            if head["id"] in retried:
                # the same genome killed a fresh evaluator twice —
                # now the gene is the prime suspect: score it inf
                pending.pop(0)
                fits[head["id"]] = float("inf")
                self.warning(
                    "evaluator lost genome %s twice (%s); scoring inf,"
                    " restarting for %d remaining", head["id"],
                    head["values"], len(pending))
            else:
                # first loss: the evaluator may have died of its own
                # accord — give the innocent-until-proven genome one
                # retry on the fresh evaluator
                retried.add(head["id"])
                self.warning(
                    "evaluator died with genome %s in flight; "
                    "retrying it once on a fresh evaluator",
                    head["id"])
            self._kill()
            if pending:
                self.start()
        for j in pending:   # broken-evaluator bailout: score inf
            fits[j["id"]] = float("inf")
        return [fits[i] for i in order]

    def evaluate_one(self, values: Dict[str, Any]) -> float:
        return self.evaluate_many([values])[0]

    def evaluate_cohort(self, values_list: List[Dict[str, Any]]) \
            -> List[float]:
        """One same-shape-signature cohort as ONE evaluator job: the
        serve process trains all members through the population-batched
        vmapped engine (one compile per signature per run) and answers
        with the per-member fitness list.  Prep still fans out over the
        thread workers.  A dead evaluator gets one restart+retry of
        the whole cohort; an evaluator-side error raises so the
        GeneticOptimizer falls back to the per-genome oracle."""
        if self._proc is None or self._proc.poll() is not None:
            self.start()
        jobs = self._prep_jobs(values_list)
        job = {"id": jobs[0]["id"],
               "members": [j["values"] for j in jobs],
               "seed": self.seed}
        timeout = self.timeout * max(1, len(values_list))
        for attempt in (1, 2):
            try:
                self._proc.stdin.write(json.dumps(job) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError):
                msg = None
            else:
                msg = self._next_json(timeout)
                while msg is not None and msg.get("id") != job["id"]:
                    msg = self._next_json(timeout)
            if msg is not None and "fitnesses" in msg:
                fits = msg["fitnesses"]
                if len(fits) != len(values_list):
                    raise RuntimeError(
                        f"evaluator returned {len(fits)} fitnesses "
                        f"for a {len(values_list)}-member cohort")
                return [float("inf") if f is None else float(f)
                        for f in fits]
            if msg is not None:   # evaluator-side error: not a death
                raise RuntimeError(
                    f"cohort failed in evaluator: {msg.get('error')}")
            self.warning("evaluator died on a %d-member cohort "
                         "(attempt %d); restarting",
                         len(values_list), attempt)
            self._kill()
            self.start()
        raise RuntimeError(
            f"evaluator died twice on a {len(values_list)}-member "
            f"cohort")

    def _run_jobs(self, jobs, fits: Dict[int, float]) -> set:
        """Stream ``jobs`` to the evaluator, collect results by id.
        Returns the set of ids that resolved; stops early when the
        evaluator dies or a per-genome timeout expires."""
        done: set = set()
        try:
            for j in jobs:
                self._proc.stdin.write(json.dumps(j) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return done
        want = {j["id"] for j in jobs}
        while done != want:
            msg = self._next_json(self.timeout)
            if msg is None:
                return done  # death or per-genome timeout
            jid = msg.get("id")
            if jid not in want:
                continue
            if "fitness" in msg:
                fits[jid] = float(msg["fitness"])
            else:
                self.warning("genome %s failed in evaluator: %s",
                             jid, msg.get("error"))
                fits[jid] = float("inf")
            done.add(jid)
        return done
