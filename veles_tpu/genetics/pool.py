"""Chip-owning GA evaluation pool — the ``tpu-evaluator`` execution
mode (round-4/5 VERDICT: the GA idled the chip by default because
``auto`` + parallel workers had to fall back to CPU to avoid a device
race).

Topology: exactly ONE evaluator subprocess (genetics/worker.py
``--serve``) acquires the accelerator at startup and evaluates every
genome of the run on it, consuming jobs from a queue; the parent's N
"workers" become host-side PREP threads that assemble job payloads
(genome -> per-job seed -> wire JSON, plus any caller-supplied staging
hook, e.g. materializing a dataset the genome's config points at).
Prep threads never construct a device, so N > 1 workers can no longer
race to initialize an exclusive TPU — the race is gone by
construction, not by policy fallback.

Failure contract (same as the subprocess-per-genome mode): a genome
that crashes the evaluator or exceeds the per-genome timeout scores
``inf``; the pool restarts the evaluator and the remaining genomes of
the generation continue.  The GA run never dies to one bad gene.

Supervision (Faultline): the serve-mode evaluator emits periodic
heartbeat lines, and the pool enforces TWO deadlines much tighter
than the ``timeout`` whole-genome cap:

- **heartbeat deadline** — no line of any kind (heartbeat, result,
  even garbage) for ``heartbeat_deadline`` seconds means the process
  is wedged or its pipe is dead: kill + restart, in seconds instead
  of the 3600 s cap;
- **adaptive per-genome deadline** — an EMA of measured genome
  durations; the in-flight genome exceeding
  ``max(min_genome_deadline, ema * genome_deadline_factor)`` (capped
  at ``timeout``) means the evaluator is alive but stuck (heartbeats
  still flowing) — same replacement path.

Either detection routes into the existing death contract: the
in-flight genome is retried once on a fresh evaluator, then scored
``inf``.  Restarts back off exponentially with jitter
(``restart_backoff`` .. ``restart_backoff_cap``) so a crash-looping
evaluator cannot storm the host, and ``max_barren_restarts``
consecutive restarts that resolve nothing bail out the generation.
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger


class ChipEvaluatorPool(Logger):
    """One serve-mode evaluator owns the device; N prep threads feed
    its queue.

    ``worker_cmd`` is the full evaluator argv (``... worker --serve
    workflow.py [config.py ...] -b BACKEND -s SEED``).  ``prep`` is an
    optional host-side staging hook run by the prep threads on each
    genome's values dict before submission (config/data preparation —
    the CPU-parallel share of an evaluation).

    Supervision knobs (all per-instance; the CLI surfaces
    ``--ga-eval-timeout``/``--eval-timeout`` and
    ``--heartbeat-deadline``):

    - ``timeout``: hard per-genome cap, seconds (default 3600) — the
      last-resort deadline when no duration EMA exists yet;
    - ``heartbeat_deadline``: max silence (no stdout line at all)
      before the evaluator is declared hung (default 60; 0 disables);
    - ``genome_deadline_factor`` x the duration EMA = the adaptive
      per-genome deadline (default 4.0), floored at
      ``min_genome_deadline`` (default 60 s — a genome whose shape
      signature forces a fresh XLA compile must not read as a hang),
      capped at ``timeout``;
    - ``restart_backoff``/``restart_backoff_cap``: exponential
      restart delay with +-25% deterministic jitter (defaults 0.5 s /
      30 s; the first restart is immediate);
    - ``max_barren_restarts``: consecutive no-progress restarts
      before the remainder of the generation scores inf (default 3).
    """

    def __init__(self, worker_cmd: List[str], workers: int = 2,
                 timeout: float = 3600.0, seed: int = 1234,
                 prep: Optional[Callable[[Dict[str, Any]],
                                         Dict[str, Any]]] = None,
                 heartbeat_deadline: float = 60.0,
                 genome_deadline_factor: float = 4.0,
                 min_genome_deadline: float = 60.0,
                 restart_backoff: float = 0.5,
                 restart_backoff_cap: float = 30.0,
                 max_barren_restarts: int = 3) -> None:
        self.worker_cmd = list(worker_cmd)
        self.workers = max(1, workers)
        self.timeout = timeout
        self.seed = seed
        self.prep = prep
        self.heartbeat_deadline = max(0.0, heartbeat_deadline)
        self.genome_deadline_factor = genome_deadline_factor
        self.min_genome_deadline = min_genome_deadline
        self.restart_backoff = restart_backoff
        self.restart_backoff_cap = restart_backoff_cap
        self.max_barren_restarts = max(1, max_barren_restarts)
        self.hello: Optional[Dict[str, Any]] = None
        self._proc: Optional[subprocess.Popen] = None
        self._lines: "queue.Queue[Optional[str]]" = queue.Queue()
        self._reader: Optional[threading.Thread] = None
        self._next_id = 0
        #: EMA of measured per-genome durations (seconds) — feeds the
        #: adaptive deadline; survives evaluator restarts
        self.genome_duration_ema: Optional[float] = None
        #: supervision telemetry: counts live in the process-wide
        #: registry (``ga.hangs_detected``/``ga.evaluator_restarts``)
        #: — the ``hangs_detected``/``restarts`` properties report this
        #: pool's share via construction-time baselines; the last-hang
        #: fields describe the CURRENT generation only (reset by
        #: ``_begin_generation``)
        self._hangs_base = telemetry.counter(
            events.CTR_GA_HANGS_DETECTED).value
        self._restarts_base = telemetry.counter(
            events.CTR_GA_EVALUATOR_RESTARTS).value
        self.last_hang_wait: Optional[float] = None
        self.last_hang_kind: Optional[str] = None
        self._consecutive_restarts = 0
        #: child pids whose final metrics snapshot was already merged
        self._adopted_pids: set = set()
        self._backoff_rng = np.random.default_rng(seed ^ 0x5EED)

    @property
    def hangs_detected(self) -> int:
        return max(0, telemetry.counter(
            events.CTR_GA_HANGS_DETECTED).value - self._hangs_base)

    @property
    def restarts(self) -> int:
        return max(0, telemetry.counter(
            events.CTR_GA_EVALUATOR_RESTARTS).value
                   - self._restarts_base)

    def _note_hang(self, kind: str, wait: float) -> None:
        """One detected hang: instance last-hang fields, registry
        counter/gauges, and a journal event — the drill-facing record
        that a hung evaluator was caught, how, and how fast."""
        self.last_hang_kind = kind
        self.last_hang_wait = wait
        telemetry.counter(events.CTR_GA_HANGS_DETECTED).inc()
        telemetry.gauge(events.GAUGE_GA_LAST_HANG_WAIT).set(
            round(wait, 3))
        telemetry.event(events.EV_GA_HANG_DETECTED, kind=kind,
                        wait=round(wait, 3))

    def _begin_generation(self) -> None:
        """Reset the per-generation hang descriptors.  Without this,
        ``last_hang_kind``/``last_hang_wait`` kept describing a hang
        from generations ago and drill telemetry attributed it to the
        current one (cumulative counts live in the registry and are
        untouched)."""
        self.last_hang_kind = None
        self.last_hang_wait = None

    # -- evaluator lifecycle ------------------------------------------

    def start(self) -> Dict[str, Any]:
        """Spawn the evaluator and block on its hello line — the ONLY
        device probe of the whole GA run, and it happens in the child.
        Returns the hello dict ({"platform", "is_accelerator", ...})."""
        self._proc = subprocess.Popen(
            self.worker_cmd, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, text=True, bufsize=1)
        self._lines = queue.Queue()
        # the queue is BOUND to this reader at spawn: a dead
        # evaluator's reader must deliver its EOF marker to its OWN
        # queue, never into the replacement's (the EOF would read as
        # the fresh evaluator dying before hello)
        self._reader = threading.Thread(
            target=self._read_stdout, args=(self._proc, self._lines),
            daemon=True)
        self._reader.start()
        hello = self._next_json(self.timeout)
        if not hello or not hello.get("ready"):
            self._kill()
            raise RuntimeError(
                f"evaluator did not come up: {hello!r}")
        self.hello = hello
        self.info("chip evaluator up: pid %s on %s (%s)",
                  hello["pid"], hello["platform"], hello["backend"])
        return hello

    def _restart_with_backoff(self) -> None:
        """Restart after a death/hang, with exponential backoff +
        deterministic jitter once restarts come consecutively (a
        crash-looping evaluator must not storm the host)."""
        telemetry.counter(events.CTR_GA_EVALUATOR_RESTARTS).inc()
        self._consecutive_restarts += 1
        n = self._consecutive_restarts
        telemetry.event(events.EV_GA_EVALUATOR_RESTART, consecutive=n)
        if n > 1:
            delay = min(self.restart_backoff_cap,
                        self.restart_backoff * (2.0 ** (n - 2)))
            delay *= 0.75 + 0.5 * float(self._backoff_rng.random())
            self.warning("restart storm (%d consecutive): backing off "
                         "%.2fs before respawn", n, delay)
            time.sleep(delay)
        self.start()

    @property
    def platform(self) -> str:
        return (self.hello or {}).get("platform", "unknown")

    @property
    def is_accelerator(self) -> bool:
        return bool((self.hello or {}).get("is_accelerator"))

    def close(self) -> None:
        if self._proc is None:
            return
        try:
            if self._proc.poll() is None and self._proc.stdin:
                self._proc.stdin.write(
                    json.dumps({"op": "shutdown"}) + "\n")
                self._proc.stdin.flush()
                self._proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — cleanup must not raise
            pass
        self._kill()

    def __enter__(self) -> "ChipEvaluatorPool":
        if self.hello is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _kill(self) -> None:
        # self.hello is kept: callers may still read the platform of
        # the evaluator that just died
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=10)
        self._proc = None
        self._adopt_child_metrics()

    def _adopt_child_metrics(self) -> None:
        """Fold a dead/closed evaluator's metrics snapshot into this
        process's registry (once per child pid), so the GA run reports
        ONE aggregate view: the child's fused-step and evaluator-side
        numbers land next to the pool's own supervision counters.  The
        serve loop flushes after every job, so even a kill -9'd child
        leaves a snapshot at most one genome stale."""
        pid = (self.hello or {}).get("pid")
        if not pid or pid in self._adopted_pids:
            return
        if telemetry.adopt_child_snapshot(pid):
            self._adopted_pids.add(pid)
            self.debug("merged evaluator pid %s telemetry snapshot",
                       pid)

    def _read_stdout(self, proc, lines) -> None:
        for line in proc.stdout:
            lines.put(line)
        lines.put(None)  # EOF marker

    def _next_event(self, timeout: float) -> Tuple[str, Any]:
        """Next stdout event within ``timeout``:
        ``("json", obj)`` — a protocol line (result or heartbeat);
        ``("line", raw)`` — a non-empty non-JSON line (still proof of
        life — e.g. an injected garbage line);
        ``("eof", None)`` — the evaluator died;
        ``("timeout", None)`` — nothing arrived in time."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return "timeout", None
            try:
                line = self._lines.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            if line is None:
                return "eof", None
            line = line.strip()
            if not line:
                continue
            try:
                return "json", json.loads(line)
            except ValueError:
                return "line", line

    def _next_json(self, timeout: float) -> Optional[Dict[str, Any]]:
        """Next parseable JSON line from the evaluator within
        ``timeout``; None on timeout or death.  (Training runs may
        also log non-JSON to stdout-adjacent streams; stdout itself
        carries only our protocol, but stay tolerant.)"""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            kind, payload = self._next_event(remaining)
            if kind == "json":
                return payload
            if kind in ("eof", "timeout"):
                return None
            # "line": garbage — keep draining

    # -- evaluation ----------------------------------------------------

    def _prep_jobs(self, values_list: List[Dict[str, Any]]) \
            -> List[Dict[str, Any]]:
        """Fan the host-side staging hook out over the prep threads and
        draw wire ids — the CPU-parallel share of a generation."""
        lock = witness.lock("pool.prep")
        # generation tag for the wire (GeneticOptimizer exports it per
        # evaluation round): lets VELES_FAULTS qualifiers and evaluator
        # logs target a specific generation
        gen = os.environ.get("VELES_GA_GENERATION")

        def prep_one(values):
            if self.prep is not None:
                values = self.prep(dict(values))
            with lock:   # id draw is the only shared state
                self._next_id += 1
                jid = self._next_id
            job = {"id": jid, "values": values, "seed": self.seed}
            if gen is not None:
                job["gen"] = int(gen)
            # each genome job is a trace root: the evaluator's spans
            # (jit, score, device put) journal under it, so a slow
            # generation decomposes per genome across the process gap
            trace.to_wire(job, trace.mint())
            return job

        with ThreadPoolExecutor(self.workers) as pool:
            return list(pool.map(prep_one, values_list))

    def _genome_deadline(self) -> float:
        """Seconds the in-flight genome may run before it is declared
        hung: the duration-EMA-scaled adaptive deadline once any
        genome has completed, else the hard ``timeout`` cap."""
        if self.genome_duration_ema is None:
            return self.timeout
        return min(self.timeout,
                   max(self.min_genome_deadline,
                       self.genome_duration_ema
                       * self.genome_deadline_factor))

    def _observe_genome_duration(self, dt: float) -> None:
        ema = self.genome_duration_ema
        self.genome_duration_ema = dt if ema is None \
            else 0.7 * ema + 0.3 * dt
        telemetry.histogram(events.HIST_GA_GENOME_SECONDS).record(dt)

    def evaluate_many(self, values_list: List[Dict[str, Any]]) \
            -> List[float]:
        """One generation: prep fans out over the thread workers, the
        evaluator consumes the queue in submission order.

        Failure contract: when the evaluator dies or hangs (heartbeat
        silence, or the adaptive per-genome deadline), the job at the
        head of the unresolved queue was in flight — but an
        evaluator-side death (OOM from a previous genome, a crashed
        chip runtime) is not proof of a bad gene, so the in-flight
        genome is RETRIED ONCE on the fresh evaluator before being
        scored inf.  ``max_barren_restarts`` consecutive restarts that
        resolve nothing mean the evaluator itself is broken: the
        remainder scores inf rather than restart-looping forever."""
        self._begin_generation()
        if self._proc is None or self._proc.poll() is not None:
            self.start()
        jobs = self._prep_jobs(values_list)
        order = [j["id"] for j in jobs]
        fits: Dict[int, float] = {}
        pending = list(jobs)
        retried: set = set()
        barren_restarts = 0
        while pending:
            done = self._run_jobs(pending, fits)
            pending = [j for j in pending if j["id"] not in done]
            if done:
                self._consecutive_restarts = 0
            if not pending:
                break
            barren_restarts = 0 if done else barren_restarts + 1
            if barren_restarts >= self.max_barren_restarts:
                self.warning(
                    "evaluator resolved nothing across %d consecutive "
                    "restarts; scoring the remaining %d genomes inf",
                    barren_restarts, len(pending))
                break
            head = pending[0]
            if head["id"] in retried:
                # the same genome killed a fresh evaluator twice —
                # now the gene is the prime suspect: score it inf
                pending.pop(0)
                fits[head["id"]] = float("inf")
                telemetry.counter(events.CTR_GA_GENOMES_LOST).inc()
                telemetry.event(events.EV_GA_GENOME_LOST,
                                job=head["id"])
                self.warning(
                    "evaluator lost genome %s twice (%s); scoring inf,"
                    " restarting for %d remaining", head["id"],
                    head["values"], len(pending))
            else:
                # first loss: the evaluator may have died or hung of
                # its own accord — give the innocent-until-proven
                # genome one retry on the fresh evaluator
                retried.add(head["id"])
                telemetry.counter(events.CTR_GA_GENOME_RETRIES).inc()
                telemetry.event(events.EV_GA_GENOME_RETRY,
                                job=head["id"])
                self.warning(
                    "evaluator lost genome %s in flight; "
                    "retrying it once on a fresh evaluator",
                    head["id"])
            self._kill()
            if pending:
                self._restart_with_backoff()
        for j in pending:   # broken-evaluator bailout: score inf
            fits[j["id"]] = float("inf")
        return [fits[i] for i in order]

    def evaluate_one(self, values: Dict[str, Any]) -> float:
        return self.evaluate_many([values])[0]

    def evaluate_cohort(self, values_list: List[Dict[str, Any]]) \
            -> List[float]:
        """One same-shape-signature cohort as ONE evaluator job: the
        serve process trains all members through the population-batched
        vmapped engine (one compile per signature per run) and answers
        with the per-member fitness list.  Prep still fans out over the
        thread workers.  A dead OR hung evaluator (heartbeat silence)
        gets one restart+retry of the whole cohort; an evaluator-side
        error raises so the GeneticOptimizer falls back to the
        per-genome oracle."""
        self._begin_generation()
        if self._proc is None or self._proc.poll() is not None:
            self.start()
        jobs = self._prep_jobs(values_list)
        job = {"id": jobs[0]["id"],
               "members": [j["values"] for j in jobs],
               "seed": self.seed}
        if "gen" in jobs[0]:
            job["gen"] = jobs[0]["gen"]
        # the cohort rides under the FIRST member's trace root (one
        # dispatch, one trace) — the per-member contexts minted at
        # prep are otherwise dropped with the per-genome jobs
        trace.to_wire(job, trace.from_wire(jobs[0]))
        timeout = self.timeout * max(1, len(values_list))
        for attempt in (1, 2):
            try:
                self._proc.stdin.write(json.dumps(job) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError):
                msg = None
            else:
                msg = self._await_cohort_result(job["id"], timeout)
            if msg is not None and "fitnesses" in msg:
                self._consecutive_restarts = 0
                fits = msg["fitnesses"]
                if len(fits) != len(values_list):
                    raise RuntimeError(
                        f"evaluator returned {len(fits)} fitnesses "
                        f"for a {len(values_list)}-member cohort")
                return [float("inf") if f is None else float(f)
                        for f in fits]
            if msg is not None:   # evaluator-side error: not a death
                raise RuntimeError(
                    f"cohort failed in evaluator: {msg.get('error')}")
            self.warning("evaluator lost on a %d-member cohort "
                         "(attempt %d); restarting",
                         len(values_list), attempt)
            self._kill()
            self._restart_with_backoff()
        raise RuntimeError(
            f"evaluator died twice on a {len(values_list)}-member "
            f"cohort")

    def _await_cohort_result(self, want_id: int, timeout: float) \
            -> Optional[Dict[str, Any]]:
        """Wait for the cohort result while enforcing the heartbeat
        deadline (cohorts have no per-genome granularity, so the
        liveness signal IS the heartbeat stream)."""
        deadline = time.monotonic() + timeout
        last_activity = time.monotonic()
        while True:
            now = time.monotonic()
            remaining = deadline - now
            if remaining <= 0:
                return None
            if self.heartbeat_deadline:
                hb_left = last_activity + self.heartbeat_deadline - now
                if hb_left <= 0:
                    self._note_hang("heartbeat", now - last_activity)
                    self.warning(
                        "evaluator silent for %.1fs during a cohort "
                        "(heartbeat deadline %.1fs) — declaring hung",
                        now - last_activity, self.heartbeat_deadline)
                    return None
                remaining = min(remaining, hb_left)
            kind, payload = self._next_event(min(remaining, 1.0))
            if kind == "eof":
                return None
            if kind in ("json", "line"):
                last_activity = time.monotonic()
            if kind == "json" and payload.get("id") == want_id:
                return payload

    def _run_jobs(self, jobs, fits: Dict[int, float]) -> set:
        """Stream ``jobs`` to the evaluator, collect results by id.
        Returns the set of ids that resolved; stops early when the
        evaluator dies, falls silent past the heartbeat deadline, or
        the in-flight genome exceeds its (adaptive) deadline."""
        done: set = set()
        try:
            for j in jobs:
                self._proc.stdin.write(json.dumps(j) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return done
        want = {j["id"] for j in jobs}
        now = time.monotonic()
        last_activity = now
        genome_start = now
        while done != want:
            now = time.monotonic()
            waits = [genome_start + self._genome_deadline() - now]
            if self.heartbeat_deadline:
                waits.append(last_activity + self.heartbeat_deadline
                             - now)
            wait = min(waits)
            if wait > 0:
                kind, payload = self._next_event(min(wait, 1.0))
            else:
                kind, payload = "timeout", None
            now = time.monotonic()
            if kind == "eof":
                return done  # death: caller restarts/retries
            if kind in ("json", "line"):
                last_activity = now
            if kind == "json":
                jid = payload.get("id")
                if jid in want and jid not in done and (
                        "fitness" in payload or "error" in payload):
                    if "fitness" in payload:
                        fits[jid] = float(payload["fitness"])
                    else:
                        self.warning(
                            "genome %s failed in evaluator: %s",
                            jid, payload.get("error"))
                        fits[jid] = float("inf")
                    done.add(jid)
                    self._observe_genome_duration(now - genome_start)
                    genome_start = now
                continue
            if kind == "line":
                continue   # garbage is still proof of life
            # timeout slice expired: check the real deadlines
            if self.heartbeat_deadline and \
                    now - last_activity >= self.heartbeat_deadline:
                self._note_hang("heartbeat", now - last_activity)
                self.warning(
                    "evaluator silent for %.1fs (heartbeat deadline "
                    "%.1fs) — declaring hung, replacing",
                    now - last_activity, self.heartbeat_deadline)
                return done
            if now - genome_start >= self._genome_deadline():
                self._note_hang("genome_deadline", now - genome_start)
                self.warning(
                    "genome in flight for %.1fs, over its deadline "
                    "%.1fs (duration EMA %.1fs) — declaring the "
                    "evaluator hung, replacing",
                    now - genome_start, self._genome_deadline(),
                    self.genome_duration_ema or -1.0)
                return done
        return done
