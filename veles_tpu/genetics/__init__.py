"""Genetic-algorithm hyperparameter tuning.

Reference parity: veles/genetics/ — config values wrapped in
``Tune(...)`` become GA genes; the optimizer spawns many workflow runs
and selects by fitness (validation error) (SURVEY.md §3.1 Genetics).

TPU adaptation: the GA itself (tournament selection, blend crossover,
gaussian mutation, elitism) is deterministic through a named PRNG
stream; evaluation execution is pluggable — sequential in-process,
subprocess-per-genome fan-out (worker.py), or the chip-owning
``tpu-evaluator`` pool (pool.py: ONE persistent evaluator process owns
the accelerator, prep workers stay host-side, no device race).

Usage::

    root.mnist.layers[0]["<-"]["learning_rate"] = Tune(0.1, 0.01, 1.0)
    ...
    python -m veles_tpu --optimize 8:5 workflow.py config.py
"""

from veles_tpu.genetics.core import (GeneticOptimizer, Tune, find_tunes,
                                     liftable_tune, shape_signature,
                                     substitute_tunes)

__all__ = ["Tune", "GeneticOptimizer", "find_tunes",
           "substitute_tunes", "liftable_tune", "shape_signature",
           "ChipEvaluatorPool", "GAServingHandoff"]


def __getattr__(name):
    # pool.py / handoff.py pull in subprocess + serving machinery;
    # keep `import veles_tpu.genetics` light for the workers that
    # only need Tune
    if name == "ChipEvaluatorPool":
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        return ChipEvaluatorPool
    if name == "GAServingHandoff":
        from veles_tpu.genetics.handoff import GAServingHandoff
        return GAServingHandoff
    raise AttributeError(name)
