"""Genetic-algorithm hyperparameter tuning.

Reference parity: veles/genetics/ — config values wrapped in
``Tune(...)`` become GA genes; the optimizer spawns many workflow runs
and selects by fitness (validation error) (SURVEY.md §3.1 Genetics).

TPU adaptation: evaluations run in-process sequentially (one chip, jit
caches warm between runs) instead of forked worker processes; the GA
itself (tournament selection, blend crossover, gaussian mutation,
elitism) is deterministic through a named PRNG stream.

Usage::

    root.mnist.layers[0]["<-"]["learning_rate"] = Tune(0.1, 0.01, 1.0)
    ...
    python -m veles_tpu --optimize 8:5 workflow.py config.py
"""

from veles_tpu.genetics.core import (GeneticOptimizer, Tune, find_tunes,
                                     substitute_tunes)

__all__ = ["Tune", "GeneticOptimizer", "find_tunes", "substitute_tunes"]
