"""GA core: Tune markers, tree walking, the optimizer loop."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, prng, telemetry
from veles_tpu.config import Config
from veles_tpu.logger import Logger


class Tune:
    """Marks a config value as a GA gene: ``Tune(default, min, max)``.
    Integer defaults breed integers (e.g. layer widths), float defaults
    breed floats (e.g. learning rates, log-uniform when min > 0)."""

    def __init__(self, value: Any, minv: float, maxv: float) -> None:
        if minv > maxv:
            raise ValueError(f"Tune: min {minv} > max {maxv}")
        self.value = value
        self.minv = minv
        self.maxv = maxv
        self.is_int = isinstance(value, (int, np.integer)) and \
            not isinstance(value, bool)
        #: log-scale breeding for positive float ranges spanning >=10x
        self.log_scale = (not self.is_int and minv > 0
                          and maxv / minv >= 10.0)

    def clip(self, x: float) -> Any:
        x = float(np.clip(x, self.minv, self.maxv))
        return int(round(x)) if self.is_int else x

    def __repr__(self) -> str:
        return f"Tune({self.value}, {self.minv}, {self.maxv})"


def _walk(obj: Any, path: str, out: Dict[str, Tune]) -> None:
    if isinstance(obj, Tune):
        out[path] = obj
    elif isinstance(obj, Config):
        for k, v in obj.__dict__.items():
            _walk(v, f"{path}.{k}" if path else k, out)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _walk(v, f"{path}[{k!r}]", out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _walk(v, f"{path}[{i}]", out)


def find_tunes(tree: Any) -> Dict[str, Tune]:
    """All Tune markers under a Config tree / dict / list, keyed by a
    path expression usable with substitute_tunes."""
    out: Dict[str, Tune] = {}
    _walk(tree, "", out)
    return out


def _set_path(tree: Any, path: str, value: Any) -> None:
    # path grammar produced by _walk: dotted attrs + [key] indexers
    import re

    tokens = re.findall(r"[A-Za-z_][A-Za-z_0-9]*|\[[^\]]+\]", path)
    cur = tree
    for i, tok in enumerate(tokens):
        last = i == len(tokens) - 1
        if tok.startswith("["):
            key = eval(tok[1:-1])  # noqa: S307 — our own repr'd keys
            if last:
                cur[key] = value
            else:
                cur = cur[key]
        else:
            if last:
                setattr(cur, tok, value)
            else:
                cur = getattr(cur, tok)


def substitute_tunes(tree: Any, values: Dict[str, Any]) -> None:
    """Replace each Tune marker with a concrete value, in place."""
    for path, v in values.items():
        _set_path(tree, path, v)


#: gradient-unit hyperparameters a float gene may set WITHOUT changing
#: any array shape — the population-batched trainer lifts these into
#: per-member vectors (``lr_rates`` / ``update_params(decays=...)``),
#: so genomes differing only here share one vmapped training dispatch
LIFTABLE_HYPERS = ("learning_rate", "learning_rate_bias",
                   "weight_decay", "weight_decay_bias")


def liftable_tune(path: str, tune: Tune) -> bool:
    """True when this gene only scales a per-member SGD hyperparameter
    (float learning rate / weight decay in a layer's ``"<-"`` dict).
    Integer genes (layer widths, kernel counts) always change the
    decoded model's SHAPE SIGNATURE and split cohorts instead."""
    if tune.is_int:
        return False
    return any(path.endswith(f"[{k!r}]") for k in LIFTABLE_HYPERS)


def shape_signature(values: Dict[str, Any],
                    tunes: Dict[str, Tune]) -> Tuple:
    """The cohort key of a decoded genome: every NON-liftable decoded
    value, in path order.  Two genomes with equal signatures decode to
    identical model structure/data/optimizer topology and may train as
    members of one vmapped cohort."""
    return tuple((p, values[p]) for p in sorted(tunes)
                 if not liftable_tune(p, tunes[p]))


class GeneticOptimizer(Logger):
    """Tournament-select / blend-crossover / gaussian-mutate GA.

    ``evaluate(values: {path: value}) -> fitness`` — LOWER is better
    (validation error).  Failed evaluations (exceptions) score inf and
    are selected against instead of aborting the run.
    """

    def __init__(self, evaluate: Callable[[Dict[str, Any]], float],
                 tunes: Dict[str, Tune],
                 population: int = 8,
                 generations: int = 5,
                 elite: int = 2,
                 mutation_rate: float = 0.25,
                 mutation_sigma: float = 0.15,
                 rng_stream: str = "genetics",
                 evaluate_many: Optional[Callable[
                     [List[Dict[str, Any]]], List[float]]] = None,
                 evaluate_cohort: Optional[Callable[
                     [List[Dict[str, Any]]], List[float]]] = None,
                 state_path: Optional[str] = None,
                 stop_check: Optional[Callable[[], bool]] = None) \
            -> None:
        if not tunes:
            raise ValueError("no Tune(...) markers found to optimize")
        self.evaluate = evaluate
        #: batch evaluator — N genomes at once (subprocess fan-out);
        #: None = sequential in-process map over ``evaluate``
        self._evaluate_many = evaluate_many
        #: cohort evaluator — N genomes OF ONE SHAPE SIGNATURE trained
        #: as a single population-batched dispatch (the tpu-evaluator
        #: pool's vmapped path).  When set, _fitness_many buckets each
        #: generation by shape_signature() and dispatches one cohort
        #: per bucket; a bucket whose cohort evaluation fails falls
        #: back to the per-genome path — the parity oracle.
        self._evaluate_cohort = evaluate_cohort
        #: cohort sizes of the most recent generation (telemetry)
        self.last_cohort_sizes: List[int] = []
        #: per-generation checkpoint file; run() resumes from it when
        #: it exists (reference parity: Genetics "spawns many workflow
        #: runs" and long GA runs must survive restarts)
        self.state_path = state_path
        #: Phoenix graceful stop: a callable polled at each generation
        #: boundary; True = stop breeding now and return the best so
        #: far (the checkpoint already on disk is the resume point)
        self._stop_check = stop_check
        self.tunes = tunes
        self.paths = sorted(tunes)
        self.population = max(population, 2 + elite)
        self.generations = generations
        self.elite = elite
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.rng = prng.get(rng_stream).numpy
        #: [(fitness, values)] per generation, best first
        self.history: List[List[Tuple[float, Dict[str, Any]]]] = []
        #: evaluation throughput accounting (see _fitness_many)
        self.eval_count = 0
        self.eval_seconds = 0.0

    # -- genome <-> values --------------------------------------------

    def _to_gene(self, t: Tune, x: float) -> float:
        return float(np.log(x)) if t.log_scale else float(x)

    def _from_gene(self, t: Tune, g: float) -> Any:
        return t.clip(np.exp(g) if t.log_scale else g)

    def _bounds(self, t: Tune) -> Tuple[float, float]:
        if t.log_scale:
            return float(np.log(t.minv)), float(np.log(t.maxv))
        return float(t.minv), float(t.maxv)

    def _decode(self, genome: np.ndarray) -> Dict[str, Any]:
        return {p: self._from_gene(self.tunes[p], g)
                for p, g in zip(self.paths, genome)}

    # -- GA operators --------------------------------------------------

    def _initial_population(self) -> np.ndarray:
        pop = []
        # individual 0 = the config's own defaults
        pop.append([self._to_gene(self.tunes[p], self.tunes[p].value)
                    for p in self.paths])
        for _ in range(self.population - 1):
            genome = []
            for p in self.paths:
                lo, hi = self._bounds(self.tunes[p])
                genome.append(self.rng.uniform(lo, hi))
            pop.append(genome)
        return np.asarray(pop, np.float64)

    def _tournament(self, fits: np.ndarray, k: int = 2) -> int:
        picks = self.rng.choice(len(fits), size=k, replace=False)
        return int(picks[np.argmin(fits[picks])])

    def _crossover(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        alpha = self.rng.uniform(-0.25, 1.25, size=a.shape)  # BLX-like
        return a + alpha * (b - a)

    def _mutate(self, g: np.ndarray) -> np.ndarray:
        g = g.copy()
        for i, p in enumerate(self.paths):
            if self.rng.random() < self.mutation_rate:
                lo, hi = self._bounds(self.tunes[p])
                g[i] += self.rng.normal(0.0, self.mutation_sigma) \
                    * (hi - lo)
            lo, hi = self._bounds(self.tunes[p])
            g[i] = np.clip(g[i], lo, hi)
        return g

    # -- the loop ------------------------------------------------------

    def _fitness(self, genome: np.ndarray) -> float:
        values = self._decode(genome)
        try:
            return float(self.evaluate(values))
        except Exception as e:  # noqa: BLE001 — GA must survive bad genes
            self.warning("evaluation failed for %s: %s", values, e)
            return float("inf")

    #: env var naming the generation being evaluated — read by worker
    #: processes (spawned per genome) and by the evaluator pool's job
    #: payloads, so ``VELES_FAULTS`` qualifiers like ``@gen=2`` and
    #: operator logs can target a specific generation
    GENERATION_ENV = "VELES_GA_GENERATION"

    def _fitness_many(self, genomes: np.ndarray,
                      gen: Optional[int] = None) -> np.ndarray:
        import os
        import time
        if gen is not None:
            os.environ[self.GENERATION_ENV] = str(gen)
        t0 = time.perf_counter()
        fits = self._fitness_many_inner(genomes)
        dt = time.perf_counter() - t0
        #: cumulative (evaluations, seconds) — the GA's own throughput
        #: record, so execution modes (cpu fan-out vs the chip-owning
        #: evaluator) are comparable on the same run log; the registry
        #: carries the same totals (``ga.evaluations``/
        #: ``ga.eval_seconds``) plus the per-round distribution
        self.eval_count += len(genomes)
        self.eval_seconds += dt
        telemetry.counter(events.CTR_GA_EVALUATIONS).inc(len(genomes))
        telemetry.counter(events.CTR_GA_EVAL_SECONDS).inc(dt)
        telemetry.histogram(events.HIST_GA_GENERATION_SECONDS).record(dt)
        telemetry.event(events.EV_GA_GENERATION_EVALUATED, gen=gen,
                        genomes=len(genomes), seconds=round(dt, 2))
        if dt > 0:
            self.info("evaluated %d genomes in %.1fs (%.2f genomes/s)",
                      len(genomes), dt, len(genomes) / dt)
        # Faultline supervisor.child_crash: hard-die HERE — after the
        # generation's real work, before its checkpoint lands — the
        # worst-case crash point the supervisor must resume from
        # (the re-evaluated generation is bit-identical: the restored
        # RNG replays the same breeding)
        from veles_tpu import faults
        faults.maybe_inject_child_crash(
            gen=gen, site="ga_generation",
            attempt=os.environ.get("VELES_SUPERVISE_ATTEMPT", "0"))
        return fits

    def _fitness_many_inner(self, genomes: np.ndarray) -> np.ndarray:
        if self._evaluate_cohort is not None:
            return self._fitness_cohorts(genomes)
        return self._fitness_serial(genomes)

    def _fitness_cohorts(self, genomes: np.ndarray) -> np.ndarray:
        """Bucket the generation by shape signature and train each
        bucket as ONE population-batched cohort.  A genome whose decode
        fails scores inf without poisoning its cohort; a bucket whose
        cohort dispatch fails falls back to the per-genome oracle."""
        fits = np.full(len(genomes), float("inf"), np.float64)
        decoded: List[Optional[Dict[str, Any]]] = []
        for g in genomes:
            try:
                decoded.append(self._decode(g))
            except Exception as e:  # noqa: BLE001 — bad genes score inf
                self.warning("genome decode failed (%s); scoring inf",
                             e)
                decoded.append(None)
        buckets: Dict[Tuple, List[int]] = {}
        for i, v in enumerate(decoded):
            if v is None:
                continue
            buckets.setdefault(shape_signature(v, self.tunes),
                               []).append(i)
        self.last_cohort_sizes = [len(ix) for ix in buckets.values()]
        if buckets:
            self.info("cohorts: %d signature bucket(s), sizes %s",
                      len(buckets), self.last_cohort_sizes)
        for idxs in buckets.values():
            try:
                bf = self._evaluate_cohort([decoded[i] for i in idxs])
                if len(bf) != len(idxs):
                    raise ValueError(
                        f"cohort evaluator returned {len(bf)} "
                        f"fitnesses for {len(idxs)} genomes")
                bf = [float("inf") if f is None else float(f)
                      for f in bf]
            except Exception as e:  # noqa: BLE001 — fall back, never
                # abort: the per-genome path is the parity oracle
                self.warning(
                    "cohort evaluation failed for a %d-genome bucket "
                    "(%s); falling back to per-genome evaluation",
                    len(idxs), e)
                bf = self._fitness_serial(genomes[idxs]).tolist()
            fits[idxs] = bf
        return fits

    def _fitness_serial(self, genomes: np.ndarray) -> np.ndarray:
        if self._evaluate_many is None:
            return np.array([self._fitness(g) for g in genomes],
                            np.float64)
        try:
            fits = self._evaluate_many(
                [self._decode(g) for g in genomes])
            # batch evaluators (e.g. the chip pool) may mark a lost
            # genome None — same contract as a failure: inf, selected
            # against
            fits = [float("inf") if f is None else float(f)
                    for f in fits]
            return np.asarray(fits, np.float64)
        except Exception as e:  # noqa: BLE001 — same contract as
            # _fitness: failures score inf, never abort the run
            self.warning("batch evaluation failed (%s); falling back "
                         "to per-genome evaluation", e)
            return np.array([self._fitness(g) for g in genomes],
                            np.float64)

    # -- checkpoint / resume -------------------------------------------

    def _save_state(self, gen: int, pop: np.ndarray,
                    fits: np.ndarray) -> None:
        """Atomic per-generation checkpoint: next generation to run,
        its (already evaluated) population, the full history, and the
        GA RNG state — a resumed run continues bit-identically.

        Integrity (Faultline): the state carries an embedded CRC32
        (over the canonical JSON of everything else, so the file stays
        ONE plain-json document), written through a pid-unique temp
        file; the previous checkpoint is rotated to
        ``<state_path>.prev`` so a write torn by a crash (or an
        injected ``checkpoint.corrupt``) always leaves an intact
        predecessor to resume from."""
        if not self.state_path:
            return
        import json
        import os
        import tempfile
        import zlib

        from veles_tpu import faults
        state = {
            "format": 2,
            "paths": self.paths,
            "generation": gen,
            "population": pop.tolist(),
            "fits": fits.tolist(),
            "history": [[(f, v) for f, v in g] for g in self.history],
            "rng_state": self.rng.bit_generator.state,
        }
        state["crc32"] = zlib.crc32(
            json.dumps(state, sort_keys=True).encode()) & 0xFFFFFFFF
        directory = os.path.dirname(os.path.abspath(self.state_path)) \
            or "."
        fd, tmp = tempfile.mkstemp(
            dir=directory, suffix=".tmp",
            prefix=os.path.basename(self.state_path) + ".")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(state, f)
            if faults.fire("checkpoint.corrupt", gen=gen):
                faults.truncate_file(tmp)
            if os.path.exists(self.state_path):
                os.replace(self.state_path, self.state_path + ".prev")
            os.replace(tmp, self.state_path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        # Phoenix: keep the supervisor's flag-less resume pointer
        # current (records the GA state path + metrics dir)
        from veles_tpu.snapshotter import write_resume_manifest
        write_resume_manifest(ga_state=self.state_path)

    @staticmethod
    def _read_state_file(path: str) -> dict:
        """Parse + verify one checkpoint file; raises
        SnapshotCorruptError on a torn/corrupt one."""
        import json
        import zlib

        from veles_tpu.snapshotter import SnapshotCorruptError
        try:
            with open(path) as f:
                state = json.load(f)
            if int(state.get("format", 1)) >= 2:
                want = state.pop("crc32", None)
                if want is None:
                    raise SnapshotCorruptError(
                        f"{path}: format-2 checkpoint without its "
                        f"embedded CRC (torn write)")
                got = zlib.crc32(json.dumps(
                    state, sort_keys=True).encode()) & 0xFFFFFFFF
                if got != int(want):
                    raise SnapshotCorruptError(f"{path}: CRC mismatch")
        except SnapshotCorruptError:
            raise
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise SnapshotCorruptError(
                f"{path}: {type(e).__name__}: {e}") from e
        return state

    def _load_state(self):
        """Resume data from the newest INTACT checkpoint: the state
        file itself, else its ``.prev`` predecessor (resume then
        re-runs one generation bit-identically).  Both corrupt raises
        — a torn checkpoint must never silently restart the run."""
        import os

        from veles_tpu.snapshotter import SnapshotCorruptError
        if not self.state_path or not (
                os.path.exists(self.state_path)
                or os.path.exists(self.state_path + ".prev")):
            return None
        state = None
        errors = []
        for path in (self.state_path, self.state_path + ".prev"):
            if not os.path.exists(path):
                continue
            try:
                state = self._read_state_file(path)
            except SnapshotCorruptError as e:
                errors.append(str(e))
                self.warning("GA checkpoint %s is corrupt (%s); "
                             "trying predecessor", path, e)
                continue
            if path != self.state_path:
                telemetry.counter(events.CTR_GA_CHECKPOINT_FALLBACKS).inc()
                telemetry.event(events.EV_GA_CHECKPOINT_FALLBACK,
                                corrupt=self.state_path, used=path)
                self.warning("resuming from intact predecessor %s",
                             path)
            break
        if state is None:
            telemetry.event(events.EV_GA_CHECKPOINT_UNRECOVERABLE,
                            path=self.state_path)
            raise SnapshotCorruptError(
                f"GA checkpoint {self.state_path} and its .prev "
                f"predecessor are both corrupt ({errors}); remove "
                f"them to start a fresh run")
        if state["paths"] != self.paths:
            raise ValueError(
                f"GA state file {self.state_path} was written for "
                f"genes {state['paths']}, current config has "
                f"{self.paths} — remove the stale state file")
        self.history = [[(float(f), v) for f, v in g]
                        for g in state["history"]]
        self.rng.bit_generator.state = state["rng_state"]
        return (int(state["generation"]),
                np.asarray(state["population"], np.float64),
                np.asarray(state["fits"], np.float64))

    # -- the loop ------------------------------------------------------

    def run(self) -> Tuple[Dict[str, Any], float]:
        """One full GA run.  ``history`` afterwards holds exactly
        ``generations + 1`` entries: the ranked population at the
        START of each of the ``generations`` breeding steps, plus the
        final bred-and-evaluated population appended after the loop.
        Safe to call twice on one optimizer: a fresh (non-resumed)
        run resets ``history`` first, and a resumed run restores it
        from the checkpoint — either way the final-generation entry is
        never duplicated."""
        resumed = self._load_state()
        if resumed is not None:
            start_gen, pop, fits = resumed
            telemetry.event(events.EV_GA_RESUMED, generation=start_gen,
                            state=self.state_path)
            self.info("resumed GA at generation %d from %s",
                      start_gen, self.state_path)
        else:
            start_gen = 0
            # a second run() on the same optimizer starts a FRESH run:
            # stale history would otherwise keep the previous run's
            # generations+1 entries and duplicate the final append
            self.history = []
            pop = self._initial_population()
            fits = self._fitness_many(pop, gen=0)
            self._save_state(0, pop, fits)
        for gen in range(start_gen, self.generations):
            if self._stop_check is not None and self._stop_check():
                # graceful stop at the generation boundary: the
                # checkpoint written after the previous generation is
                # the resume point; a resumed run continues the
                # remaining generations bit-identically
                telemetry.event(events.EV_PREEMPT_GA_STOP, generation=gen)
                self.warning(
                    "graceful stop: breeding halted before generation "
                    "%d; resume continues from the checkpoint", gen)
                break
            order = np.argsort(fits)
            pop, fits = pop[order], fits[order]
            self.history.append([(float(f), self._decode(g))
                                 for f, g in zip(fits, pop)])
            telemetry.event(events.EV_GA_GENERATION, gen=gen,
                            best=float(fits[0]))
            self.info("generation %d: best=%.4f %s", gen, fits[0],
                      self._decode(pop[0]))
            nxt = list(pop[:self.elite])
            while len(nxt) < self.population:
                a = pop[self._tournament(fits)]
                b = pop[self._tournament(fits)]
                child = self._mutate(self._crossover(a, b))
                nxt.append(child)
            new = np.asarray(nxt)
            new_fits = np.concatenate([
                fits[:self.elite],
                self._fitness_many(new[self.elite:], gen=gen + 1)])
            pop, fits = new, new_fits
            self._save_state(gen + 1, pop, fits)
        # the last bred population WAS evaluated — record it, or
        # history[-1] silently under-reports the final state (e.g.
        # EnsembleTrainer.from_ga would seed from the previous
        # generation's ranking even when final offspring beat it);
        # history length lands at generations + 1
        order = np.argsort(fits)
        pop, fits = pop[order], fits[order]
        self.history.append([(float(f), self._decode(g))
                             for f, g in zip(fits, pop)])
        best = self._decode(pop[0])
        self.info("GA done: best fitness %.4f with %s", fits[0], best)
        return best, float(fits[0])
