"""Genome evaluation workers (reference parity: veles/genetics/
spawns a process per workflow run — SURVEY.md §3.1 Genetics).

One-shot mode (the classic CPU fan-out unit):

``python -m veles_tpu.genetics.worker workflow.py [config.py ...]
--values '<json {path: value}>' [-b BACKEND] [-s SEED]``

Runs ONE full training with the Tune markers substituted and prints a
single JSON line ``{"fitness": <best validation error>}`` on stdout.
The process boundary is the isolation: the global ``root`` mutation,
jit caches, and any crash stay in this process — the GA parent only
sees the fitness (or a dead worker, scored inf).

Serve mode (the chip-owning evaluator of the ``tpu-evaluator`` GA
execution policy — see veles_tpu/genetics/pool.py):

``python -m veles_tpu.genetics.worker --serve workflow.py [...]``

ONE persistent process acquires the device at startup, announces it
with a hello line (``{"ready": true, "pid", "backend", "platform",
"is_accelerator"}``), then consumes genome jobs as JSON lines on stdin
(``{"id": n, "values": {...}, "seed": s}``) and answers each with
``{"id": n, "fitness": f, "pid": p}`` (or ``{"id", "error"}`` — bad
genes must never kill the evaluator).  Owning the device across
genomes is the point: an exclusive TPU admits exactly one client, so
this is the only process that ever touches it (parallel prep workers
stay host-side), and the jax client + persistent compile cache stay
warm between genomes instead of paying process startup + backend init
+ recompile per evaluation.  The per-process ``root`` isolation the
one-shot mode gets for free is reproduced by snapshotting the pristine
config tree once and rebuilding it (restore -> config files ->
overrides -> tunes) before every genome.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _split_files(files):
    overrides = [a for a in files if a.startswith("root.") and "=" in a]
    workflow_file, *config_files = [a for a in files
                                    if a not in overrides]
    return workflow_file, config_files, overrides


def _evaluate(workflow_file: str, backend: str, seed: int,
              verbose: bool) -> float:
    """One genome's training run -> fitness.  ``root`` must already
    hold the substituted config."""
    from veles_tpu.launcher import Launcher, drive_workflow, \
        workflow_fitness

    launcher = Launcher(backend=backend, seed=seed, verbose=verbose)
    try:
        drive_workflow(launcher, workflow_file)
        return workflow_fitness(launcher.workflow)
    finally:
        _release(launcher)


def _release(launcher) -> None:
    """Return the device buffers a finished genome run holds — HBM on
    an exclusive chip must not accumulate across the generations a
    serve-mode evaluator lives through (same hygiene as bench.py's
    phase transitions)."""
    import gc
    w = getattr(launcher, "workflow", None)
    if w is not None:
        fused = getattr(w, "fused", None)
        if fused is not None and hasattr(fused, "release_device_state"):
            fused.release_device_state()
        ld = getattr(w, "loader", None)
        for vec_name in ("original_data", "original_labels",
                         "original_targets"):
            vec = getattr(ld, vec_name, None)
            if vec is not None and hasattr(vec, "reset"):
                vec.reset()
        w.stop()
    launcher.workflow = None
    gc.collect()


def _rebuild_root(pristine, config_files, overrides, values) -> None:
    """Rebuild the global config tree for one genome: pristine state ->
    config files -> overrides -> tune substitution.  Reproduces the
    per-process ``root`` isolation the one-shot mode's process boundary
    provides, inside the persistent evaluator."""
    import copy

    from veles_tpu.config import parse_overrides, root
    from veles_tpu.genetics import substitute_tunes
    from veles_tpu.launcher import apply_config_file

    root.__dict__.clear()
    root.__dict__.update(copy.deepcopy(pristine))
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)
    substitute_tunes(root, values)


class _CohortTooBig(Exception):
    """Raised by the chunk trainer when the HBM accounting caps the
    cohort below the attempted size; carries the admissible cap."""

    def __init__(self, cap: int) -> None:
        super().__init__(f"cohort over HBM budget; cap {cap}")
        self.cap = max(1, cap)


def _hbm_cohort_cap(workflow, requested: int,
                    n_devices: int = 1) -> int:
    """Largest member count one vmapped cohort may stack, from the
    params x P accounting: per member the engine holds f32 params +
    f32 momentum + a compute-dtype cast + transient grads — ~4
    param-sized buffers.  The budget is the device's reported
    ``bytes_limit`` (TPU) or ``VELES_TPU_GA_HBM_BUDGET`` (default
    8 GiB where the backend reports none), with half held back for the
    resident dataset + the cohort's activations.

    ``n_devices`` > 1 is the member-sharded mesh (Lattice): each
    device stacks only P/N members, so the admissible cohort is N x
    one device's cap at the SAME per-device budget — unless
    $VELES_MESH_SHARD_MEMBERS says never."""
    import os

    import numpy as np

    param_bytes = 0
    for f in workflow.forwards:
        for v in f.param_vectors().values():
            if v:
                param_bytes += int(np.prod(v.shape)) * 4
    per_member = max(param_bytes * 4, 1)
    budget = None
    jdev = getattr(workflow.fused.device, "jax_device", None)
    if jdev is not None:
        try:
            budget = int((jdev.memory_stats() or {})
                         .get("bytes_limit", 0)) or None
        except Exception:  # noqa: BLE001 — CPU backends report none
            budget = None
    if budget is None:
        budget = int(os.environ.get("VELES_TPU_GA_HBM_BUDGET",
                                    8 << 30))
    if n_devices > 1:
        from veles_tpu import knobs
        from veles_tpu.parallel.mesh import shard_mode
        if shard_mode(knobs.get(knobs.MESH_SHARD_MEMBERS)) != "never":
            budget *= int(n_devices)
    cap = max(1, (budget // 2) // per_member)
    if requested:
        cap = min(cap, max(1, requested))
    return cap


def _structure_sig(workflow):
    """Cheap structural fingerprint of a built (un-initialized)
    workflow: the layer configs with the liftable per-member
    hyperparameters stripped.  Members of one cohort MUST agree on it
    — the vmapped engine trains every member at the representative's
    shapes, so a member that decoded to a different structure would
    otherwise silently train as somebody else's genome."""
    from veles_tpu.genetics.core import LIFTABLE_HYPERS
    sig = []
    for cfg in getattr(workflow, "layers_config", []):
        back = {k: v for k, v in dict(cfg.get("<-", {})).items()
                if k not in LIFTABLE_HYPERS}
        sig.append((cfg.get("type"),
                    repr(sorted(dict(cfg.get("->", {})).items())),
                    repr(sorted(back.items()))))
    return tuple(sig)


def _train_cohort_chunk(create, pristine, config_files, overrides,
                        args, members, hypers, idxs, seed):
    """Train ONE same-signature chunk via the population-batched
    engine; returns its fitness list in ``idxs`` order.  Raises
    _CohortTooBig when the HBM accounting says to split first."""
    import numpy as np

    from veles_tpu.launcher import Launcher
    from veles_tpu.ops.fused import PopulationTrainEngine

    _rebuild_root(pristine, config_files, overrides, members[idxs[0]])
    launcher = Launcher(backend=args.backend, seed=seed,
                        verbose=args.verbose)
    engine = None
    try:
        launcher.create_workflow(create)
        launcher.initialize()
        w = launcher.workflow
        dp = int(getattr(args, "dp", 0) or 0)
        cap = _hbm_cohort_cap(w, args.cohort, n_devices=dp or 1)
        if len(idxs) > cap:
            raise _CohortTooBig(cap)
        rates = np.stack([hypers[i][0] for i in idxs])
        decays = np.stack([hypers[i][1] for i in idxs])
        mesh = None
        if dp > 1:
            # member-sharded cohort (Lattice): the engine shards its
            # stacked member axis over an N-device mesh and keeps the
            # (small, GA-scale) dataset replicated on it
            from veles_tpu.parallel import make_mesh
            mesh = make_mesh(dp)
        engine = PopulationTrainEngine(w, rates, decays, mesh=mesh)
        return [float(f) for f in engine.run()]
    finally:
        if engine is not None:
            engine.release()
        _release(launcher)


def _evaluate_cohort(workflow_file, config_files, overrides, pristine,
                     args, members, seed):
    """One same-signature cohort -> per-member fitness list.

    Per-member harvest first (rebuild root, build the workflow host-
    side, read each genome's gd learning rates / weight decays): a
    member whose decode or build fails scores inf WITHOUT poisoning
    the cohort.  The valid members then train in population-batched
    chunks; a chunk that fails (OOM included) splits in half and
    retries — never crashes — and a failing singleton falls back to
    the per-genome oracle path."""
    import logging

    import numpy as np

    from veles_tpu import prng
    from veles_tpu.launcher import load_workflow_module

    log = logging.getLogger("veles_tpu.genetics.worker")
    mod = load_workflow_module(workflow_file)
    create = getattr(mod, "create_workflow", None)
    if create is None:
        raise RuntimeError(
            f"{workflow_file}: cohort evaluation needs "
            f"create_workflow(launcher)")

    class _FL:
        workflow = None

    n = len(members)
    fits = [float("inf")] * n
    hypers = [None] * n
    sig_ref = None
    valid = []
    for i, values in enumerate(members):
        try:
            _rebuild_root(pristine, config_files, overrides, values)
            prng.seed_all(seed)
            w = create(_FL())
            sig = (len(w.gds), _structure_sig(w))
            if sig_ref is None:
                sig_ref = sig
            elif sig != sig_ref:
                raise ValueError(
                    "model structure differs from the cohort "
                    "representative (shape-signature mismatch)")
            hypers[i] = (
                np.asarray([[gd.learning_rate, gd.learning_rate_bias]
                            if gd is not None else [0.0, 0.0]
                            for gd in w.gds], np.float32),
                np.asarray([[gd.weight_decay, gd.weight_decay_bias]
                            if gd is not None else [0.0, 0.0]
                            for gd in w.gds], np.float32))
            valid.append(i)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — bad gene: inf
            log.warning("cohort member %d invalid (%s: %s); scoring "
                        "inf", i, type(e).__name__, e)
    if not valid:
        return fits
    pending = [list(valid)]
    while pending:
        idxs = pending.pop(0)
        try:
            chunk_fits = _train_cohort_chunk(
                create, pristine, config_files, overrides, args,
                members, hypers, idxs, seed)
            for i, f in zip(idxs, chunk_fits):
                fits[i] = f
        except KeyboardInterrupt:
            raise
        except _CohortTooBig as e:
            log.info("cohort of %d over HBM budget; chunking at %d",
                     len(idxs), e.cap)
            pending = [idxs[j:j + e.cap]
                       for j in range(0, len(idxs), e.cap)] + pending
        except BaseException as e:  # noqa: BLE001 — split, never crash
            if len(idxs) == 1:
                log.warning("cohort singleton %d failed batched (%s: "
                            "%s); per-genome oracle fallback",
                            idxs[0], type(e).__name__, e)
                try:
                    _rebuild_root(pristine, config_files, overrides,
                                  members[idxs[0]])
                    fits[idxs[0]] = _evaluate(
                        workflow_file, args.backend, seed,
                        args.verbose)
                except KeyboardInterrupt:
                    raise
                except BaseException as e2:  # noqa: BLE001
                    log.warning("oracle fallback for member %d also "
                                "failed (%s); scoring inf", idxs[0],
                                e2)
            else:
                half = len(idxs) // 2
                log.warning("cohort chunk of %d failed (%s: %s); "
                            "splitting and retrying", len(idxs),
                            type(e).__name__, e)
                pending = [idxs[:half], idxs[half:]] + pending
    return fits


def serve(args) -> int:
    """The chip-owning evaluation loop (tpu-evaluator mode).

    Emits periodic heartbeat lines (``{"hb": n, "pid", "job"}``)
    from a daemon thread so the parent pool can tell a slow genome
    from a wedged process — see genetics/pool.py for the deadlines
    the heartbeats feed.  ``--heartbeat-every 0`` disables.
    """
    import copy
    import os
    import threading

    from veles_tpu import events, faults, telemetry, trace
    from veles_tpu.analysis import witness
    from veles_tpu.backends import make_device
    from veles_tpu.config import root
    from veles_tpu.logger import setup_logging

    setup_logging(10 if args.verbose else 20)
    workflow_file, config_files, overrides = _split_files(args.files)
    # the pristine config tree, BEFORE any config file ran: each genome
    # rebuilds root from here so substitutions can't leak across jobs
    # (the isolation the one-shot mode's process boundary provided)
    pristine = copy.deepcopy(dict(root.__dict__))

    # acquire the device ONCE — this process is the chip's only client
    # for the whole GA run (make_device is memoized, so every genome's
    # Launcher reuses this same handle)
    device = make_device(args.backend)
    platform = getattr(device, "platform", device.backend_name)
    hello = {"ready": True, "pid": os.getpid(),
             "backend": device.backend_name, "platform": platform,
             "is_accelerator": bool(device.is_jax
                                    and platform != "cpu")}

    # ALL protocol lines go through one lock so the heartbeat thread
    # can never interleave bytes into a result line
    emit_lock = witness.lock("worker.emit")

    def emit(obj) -> None:
        with emit_lock:
            print(json.dumps(obj), flush=True)

    emit(hello)
    telemetry.flush()   # even a job-less child leaves a snapshot

    hb_state = {"job": None, "silent": False}
    hb_stop = threading.Event()

    def _hb_loop() -> None:
        n = 0
        while not hb_stop.wait(args.heartbeat_every):
            if hb_state["silent"]:
                continue
            emit({"hb": n, "pid": os.getpid(),
                  "job": hb_state["job"]})
            n += 1

    if args.heartbeat_every > 0:
        threading.Thread(target=_hb_loop, daemon=True,
                         name="serve-heartbeat").start()

    seq = 0   # ordinal of the job within this evaluator's life
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        job = json.loads(line)
        if job.get("op") == "shutdown":
            break
        result = {"id": job["id"], "pid": os.getpid()}
        hb_state["job"] = job["id"]
        fault_ctx = {"job": job["id"], "seq": seq}
        if "gen" in job:
            fault_ctx["gen"] = job["gen"]
        seq += 1
        telemetry.counter(events.CTR_EVALUATOR_JOBS).inc()
        # the pool's per-job trace root off the wire: running the job
        # under our own child span makes every journaled event inside
        # (the job span below included) auto-carry trace/span, so the
        # parent-side merge decomposes a slow generation per genome
        wctx = trace.from_wire(job)
        try:
            # the span is the child-side per-job record: its histogram
            # (evaluator.job_seconds) and journal line ride the
            # snapshot the parent pool merges after this process dies
            with trace.use(wctx.child() if wctx is not None
                           else None), \
                 telemetry.span(events.SPAN_EVALUATOR_JOB_SECONDS,
                                journal=True,
                                job=job["id"],
                                cohort=len(job.get("members", []))
                                or None):
                hang = faults.fire("evaluator.hang", **fault_ctx)
                if hang:
                    # a stall mid-genome: heartbeats keep flowing
                    # unless the drill asked for a fully wedged
                    # process (silent)
                    hb_state["silent"] = bool(hang.get("silent"))
                    faults.hang(float(hang.get("seconds", 3600.0)))
                    hb_state["silent"] = False
                if "members" in job:
                    # cohort job: same-signature genomes trained as
                    # one population-batched dispatch chain (chunked
                    # to the HBM budget; bad members score inf
                    # individually)
                    result["fitnesses"] = _evaluate_cohort(
                        workflow_file, config_files, overrides,
                        pristine, args, job["members"],
                        int(job.get("seed", args.seed)))
                else:
                    _rebuild_root(pristine, config_files, overrides,
                                  job["values"])
                    result["fitness"] = _evaluate(
                        workflow_file, args.backend,
                        int(job.get("seed", args.seed)), args.verbose)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — bad genes score
            # inf at the parent; the evaluator must outlive them
            result["error"] = f"{type(e).__name__}: {e}"
            telemetry.counter(events.CTR_EVALUATOR_JOB_ERRORS).inc()
        hb_state["job"] = None
        # flush BEFORE the result line: once the parent sees the
        # result it may kill/merge at any time, and the snapshot must
        # already include this job
        telemetry.flush()
        if faults.fire("evaluator.garbage_line", **fault_ctx):
            # a torn protocol line (e.g. a crashing library printing
            # over stdout) — the pool must treat it as noise + proof
            # of life, never as a result
            with emit_lock:
                print(faults.garbage_text(point="evaluator"),
                      flush=True)
        emit(result)
    hb_stop.set()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veles_tpu.genetics.worker")
    p.add_argument("files", nargs="+")
    p.add_argument("--values", default=None,
                   help="JSON {tune_path: value} (one-shot mode)")
    p.add_argument("--serve", action="store_true",
                   help="persistent chip-owning evaluator: genome jobs "
                        "as JSON lines on stdin, results on stdout")
    p.add_argument("--cohort", type=int, default=0,
                   help="serve mode: cap on the member count of one "
                        "population-batched training dispatch "
                        "(0 = auto, bounded by the HBM budget only)")
    p.add_argument("--dp", type=int, default=0,
                   help="serve mode: member-shard cohort dispatches "
                        "over an N-device mesh (P/N members per "
                        "device; raises the HBM cohort cap by N — "
                        "simulate on CPU with XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N)")
    p.add_argument("--heartbeat-every", type=float,
                   default=float(os.environ.get(
                       "VELES_HEARTBEAT_EVERY", "5.0")),
                   help="serve mode: seconds between heartbeat lines "
                        "on stdout (default 5, or "
                        "$VELES_HEARTBEAT_EVERY; 0 disables)")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("-s", "--seed", type=int, default=1234)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    # genome workers (serve-mode evaluator AND one-shot subprocesses)
    # are GA children: preemption semantics belong to the parent, so
    # their Launchers must not install graceful-stop handlers — a
    # signaled worker dies plainly and the parent's retry/inf contract
    # handles it, exactly as before Phoenix
    os.environ["VELES_PREEMPT_DISABLE"] = "1"

    if args.serve:
        return serve(args)
    if args.values is None:
        p.error("--values is required without --serve")

    from veles_tpu.config import parse_overrides, root
    from veles_tpu.genetics import substitute_tunes

    workflow_file, config_files, overrides = _split_files(args.files)
    from veles_tpu.launcher import apply_config_file
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)
    substitute_tunes(root, json.loads(args.values))

    try:
        fitness = _evaluate(workflow_file, args.backend, args.seed,
                            args.verbose)
    except RuntimeError as e:
        if "defines neither" in str(e):
            print(str(e), file=sys.stderr)
            return 2
        raise
    print(json.dumps({"fitness": fitness}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
