"""Genome evaluation workers (reference parity: veles/genetics/
spawns a process per workflow run — SURVEY.md §3.1 Genetics).

One-shot mode (the classic CPU fan-out unit):

``python -m veles_tpu.genetics.worker workflow.py [config.py ...]
--values '<json {path: value}>' [-b BACKEND] [-s SEED]``

Runs ONE full training with the Tune markers substituted and prints a
single JSON line ``{"fitness": <best validation error>}`` on stdout.
The process boundary is the isolation: the global ``root`` mutation,
jit caches, and any crash stay in this process — the GA parent only
sees the fitness (or a dead worker, scored inf).

Serve mode (the chip-owning evaluator of the ``tpu-evaluator`` GA
execution policy — see veles_tpu/genetics/pool.py):

``python -m veles_tpu.genetics.worker --serve workflow.py [...]``

ONE persistent process acquires the device at startup, announces it
with a hello line (``{"ready": true, "pid", "backend", "platform",
"is_accelerator"}``), then consumes genome jobs as JSON lines on stdin
(``{"id": n, "values": {...}, "seed": s}``) and answers each with
``{"id": n, "fitness": f, "pid": p}`` (or ``{"id", "error"}`` — bad
genes must never kill the evaluator).  Owning the device across
genomes is the point: an exclusive TPU admits exactly one client, so
this is the only process that ever touches it (parallel prep workers
stay host-side), and the jax client + persistent compile cache stay
warm between genomes instead of paying process startup + backend init
+ recompile per evaluation.  The per-process ``root`` isolation the
one-shot mode gets for free is reproduced by snapshotting the pristine
config tree once and rebuilding it (restore -> config files ->
overrides -> tunes) before every genome.
"""

from __future__ import annotations

import argparse
import json
import sys


def _split_files(files):
    overrides = [a for a in files if a.startswith("root.") and "=" in a]
    workflow_file, *config_files = [a for a in files
                                    if a not in overrides]
    return workflow_file, config_files, overrides


def _evaluate(workflow_file: str, backend: str, seed: int,
              verbose: bool) -> float:
    """One genome's training run -> fitness.  ``root`` must already
    hold the substituted config."""
    from veles_tpu.launcher import Launcher, drive_workflow, \
        workflow_fitness

    launcher = Launcher(backend=backend, seed=seed, verbose=verbose)
    try:
        drive_workflow(launcher, workflow_file)
        return workflow_fitness(launcher.workflow)
    finally:
        _release(launcher)


def _release(launcher) -> None:
    """Return the device buffers a finished genome run holds — HBM on
    an exclusive chip must not accumulate across the generations a
    serve-mode evaluator lives through (same hygiene as bench.py's
    phase transitions)."""
    import gc
    w = getattr(launcher, "workflow", None)
    if w is not None:
        fused = getattr(w, "fused", None)
        if fused is not None and hasattr(fused, "release_device_state"):
            fused.release_device_state()
        ld = getattr(w, "loader", None)
        for vec_name in ("original_data", "original_labels",
                         "original_targets"):
            vec = getattr(ld, vec_name, None)
            if vec is not None and hasattr(vec, "reset"):
                vec.reset()
        w.stop()
    launcher.workflow = None
    gc.collect()


def serve(args) -> int:
    """The chip-owning evaluation loop (tpu-evaluator mode)."""
    import copy
    import os

    from veles_tpu.backends import make_device
    from veles_tpu.config import parse_overrides, root
    from veles_tpu.genetics import substitute_tunes
    from veles_tpu.launcher import apply_config_file
    from veles_tpu.logger import setup_logging

    setup_logging(10 if args.verbose else 20)
    workflow_file, config_files, overrides = _split_files(args.files)
    # the pristine config tree, BEFORE any config file ran: each genome
    # rebuilds root from here so substitutions can't leak across jobs
    # (the isolation the one-shot mode's process boundary provided)
    pristine = copy.deepcopy(dict(root.__dict__))

    # acquire the device ONCE — this process is the chip's only client
    # for the whole GA run (make_device is memoized, so every genome's
    # Launcher reuses this same handle)
    device = make_device(args.backend)
    platform = getattr(device, "platform", device.backend_name)
    hello = {"ready": True, "pid": os.getpid(),
             "backend": device.backend_name, "platform": platform,
             "is_accelerator": bool(device.is_jax
                                    and platform != "cpu")}
    print(json.dumps(hello), flush=True)

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        job = json.loads(line)
        if job.get("op") == "shutdown":
            break
        result = {"id": job["id"], "pid": os.getpid()}
        try:
            root.__dict__.clear()
            root.__dict__.update(copy.deepcopy(pristine))
            for cf in config_files:
                apply_config_file(cf)
            parse_overrides(overrides)
            substitute_tunes(root, job["values"])
            result["fitness"] = _evaluate(
                workflow_file, args.backend,
                int(job.get("seed", args.seed)), args.verbose)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — bad genes score
            # inf at the parent; the evaluator must outlive them
            result["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veles_tpu.genetics.worker")
    p.add_argument("files", nargs="+")
    p.add_argument("--values", default=None,
                   help="JSON {tune_path: value} (one-shot mode)")
    p.add_argument("--serve", action="store_true",
                   help="persistent chip-owning evaluator: genome jobs "
                        "as JSON lines on stdin, results on stdout")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("-s", "--seed", type=int, default=1234)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    if args.serve:
        return serve(args)
    if args.values is None:
        p.error("--values is required without --serve")

    from veles_tpu.config import parse_overrides, root
    from veles_tpu.genetics import substitute_tunes

    workflow_file, config_files, overrides = _split_files(args.files)
    from veles_tpu.launcher import apply_config_file
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)
    substitute_tunes(root, json.loads(args.values))

    try:
        fitness = _evaluate(workflow_file, args.backend, args.seed,
                            args.verbose)
    except RuntimeError as e:
        if "defines neither" in str(e):
            print(str(e), file=sys.stderr)
            return 2
        raise
    print(json.dumps({"fitness": fitness}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
