"""One-genome evaluation worker (reference parity: veles/genetics/
spawns a process per workflow run — SURVEY.md §3.1 Genetics).

``python -m veles_tpu.genetics.worker workflow.py [config.py ...]
--values '<json {path: value}>' [-b BACKEND] [-s SEED]``

Runs ONE full training with the Tune markers substituted and prints a
single JSON line ``{"fitness": <best validation error>}`` on stdout.
The process boundary is the isolation: the global ``root`` mutation,
jit caches, and any crash stay in this process — the GA parent only
sees the fitness (or a dead worker, scored inf).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veles_tpu.genetics.worker")
    p.add_argument("files", nargs="+")
    p.add_argument("--values", required=True,
                   help="JSON {tune_path: value}")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("-s", "--seed", type=int, default=1234)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    from veles_tpu.config import parse_overrides, root
    from veles_tpu.genetics import substitute_tunes
    from veles_tpu.launcher import (Launcher, apply_config_file,
                                    drive_workflow, workflow_fitness)

    overrides = [a for a in args.files
                 if a.startswith("root.") and "=" in a]
    workflow_file, *config_files = [a for a in args.files
                                    if a not in overrides]
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)
    substitute_tunes(root, json.loads(args.values))

    launcher = Launcher(backend=args.backend, seed=args.seed,
                        verbose=args.verbose)
    try:
        drive_workflow(launcher, workflow_file)
    except RuntimeError as e:
        if "defines neither" in str(e):
            print(str(e), file=sys.stderr)
            return 2
        raise
    print(json.dumps({"fitness": workflow_fitness(launcher.workflow)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
