"""The GA→serving handoff that never leaves HBM (the Keel payoff).

The classic path from "the GA finished" to "the winner serves" is a
full host round trip: snapshot the best genome, write an npz, package
it with Forge, spawn (or point) a hive at the package, re-upload the
params, and recompile the dispatchers — seconds of wall, dominated by
compile.  But the final generation's trained members are ALREADY
stacked on device in the cohort engine's member axis, and the serving
tier already has the HBM-to-HBM adoption primitive
(``ResidencyManager.swap_params``, the Evergreen promotion move —
measured 6.9ms vs 0.63s against its reload oracle, ~91x).  This
module extends that move to the GA:

1. the serving scaffold — a registered :class:`HostedModel` with a
   compiled (and optionally warmed) :class:`EnsembleEvalEngine` — is
   built AHEAD of the final generation from the cohort's shared init
   params, off the handoff's critical path;
2. the handoff itself is one jitted member-axis gather (top-K members
   sliced out of the cohort stack, device-to-device) plus one
   ``swap_params`` attribute store;
3. the host member copies the spill/restore machinery needs refresh
   AFTER serving starts (:meth:`GAServingHandoff.refresh_host`), the
   same off-critical-path contract the online promotion uses.

Time-from-last-generation-to-first-served-request is graded in
bench.py's ``ga_handoff`` phase against the reload oracle;
tests/test_engine_core.py pins that the handoff writes no npz and
serves params bitwise-equal to the trained ones.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu import events, telemetry
from veles_tpu.logger import Logger
from veles_tpu.serve.residency import HostedModel, ResidencyManager


class GAServingHandoff(Logger):
    """One model's pre-built serving scaffold + the HBM-to-HBM adopt.

    Construct it BEFORE (or while) the final generation trains: the
    engine build — stacking placeholder params, tracing the serving
    dispatchers, the optional warm-up dispatch — overlaps with
    training, so :meth:`adopt` pays only the gather + swap."""

    def __init__(self, manager: ResidencyManager, name: str,
                 forwards: List[Any],
                 member_params: List[Dict[str, Dict[str, Any]]],
                 meta: Optional[Dict[str, Any]] = None,
                 sample_shape=None, warm_rows: int = 1) -> None:
        self.manager = manager
        self.name = name
        self.k = len(member_params)
        model = HostedModel(name, forwards, member_params, meta=meta,
                            sample_shape=sample_shape)
        manager.register(model)
        #: the pre-built serving engine (compiled, resident, serving
        #: the placeholder params until the first adopt lands)
        self.engine = manager.ensure(name)
        self.engine.attach_batcher(manager.max_batch,
                                   manager.max_wait_s, label=name,
                                   sample_shape=model.sample_shape)
        self._slice = None
        if warm_rows and sample_shape is not None:
            self.warm(warm_rows, sample_shape)

    def warm(self, rows: int, sample_shape) -> None:
        """Push one dummy request through the serving facade so the
        fixed-shape dispatch is compiled before the handoff — the
        whole point is that the first REAL request after adopt pays a
        dispatch, not a trace."""
        dummy = np.zeros((int(rows),) + tuple(sample_shape),
                         np.float32)
        self.engine.submit(dummy).result()

    # -- the handoff ---------------------------------------------------

    def top_k(self, fitness: np.ndarray) -> np.ndarray:
        """The member indices to slice: the K best (lowest — min
        validation n_err for supervised cohorts, min mean quantization
        error for SOM cohorts; every engine's fitness is
        lower-is-better) members, stable order so ties keep the
        cohort's member order, exactly like the per-genome GA's
        sort."""
        order = np.argsort(np.asarray(fitness, np.float64),
                           kind="stable")
        return np.ascontiguousarray(order[:self.k].astype(np.int32))

    def _gather(self, stacked_params: Any, idx: np.ndarray):
        """The jitted member-axis slice (compiled once; the index
        vector is a traced argument, so every adopt reuses the same
        executable)."""
        core = self.engine._core
        if self._slice is None:
            import jax

            def gather(tree, idx):
                import jax.numpy as jnp
                return jax.tree_util.tree_map(
                    lambda a: jnp.take(a, idx, axis=0), tree)

            if self.engine.member_sharded:
                out = core.member_axis_sharding
            elif core.on_mesh:
                out = core.replicated
            else:
                out = None
            self._slice = core.jit(gather, out_shardings=out)
        # a member-sharded engine's stack is padded to a whole
        # per-device tile; pad the gather the same way (repeating the
        # best member — padding rows are never read by the fixed-order
        # member mean)
        pad = self.engine._n_stacked - self.k
        if pad:
            idx = np.concatenate([idx, np.full(pad, idx[0],
                                               np.int32)])
        return self._slice(stacked_params,
                           core.put_replicated(idx))

    def prewarm(self, cohort_engine: Any) -> None:
        """Compile the adopt gather against the LIVE cohort stack —
        callable any time after the cohort engine exists, so the
        trace+compile overlaps training like the rest of the
        scaffold and the timed adopt pays only a dispatch.  The
        gathered placeholder tree is discarded."""
        import jax

        stacked = cohort_engine._params
        if stacked is None:
            raise RuntimeError(
                "cohort engine has no live stacked params to "
                "prewarm the gather against")
        out = self._gather(stacked,
                           np.arange(self.k, dtype=np.int32))
        for leaf in jax.tree_util.tree_leaves(out):
            leaf.block_until_ready()

    def adopt(self, stacked_params: Any,
              member_indices: np.ndarray):
        """Slice ``member_indices`` out of a cohort-stacked param tree
        (device-to-device, one jitted gather — compiled once, the
        index vector is a traced argument) and swap the sliced tree
        into the serving engine.  Returns the engine, already serving
        the trained members; NOTHING touches the host on this path."""
        t0 = time.perf_counter()
        idx = np.asarray(member_indices, np.int32)
        if len(idx) != self.k:
            raise ValueError(
                f"handoff needs exactly {self.k} members (the "
                f"pre-built engine's stack), got {len(idx)}")
        sliced = self._gather(stacked_params, idx)
        engine = self.manager.swap_params(self.name, sliced)
        dt = time.perf_counter() - t0
        telemetry.event(events.EV_GA_HANDOFF, model=self.name,
                        members=self.k, seconds=round(dt, 5))
        self.info("GA handoff: %d members adopted HBM-to-HBM into "
                  "%r in %.2fms", self.k, self.name, 1000.0 * dt)
        return engine

    def adopt_cohort(self, cohort_engine: Any,
                     fitness: np.ndarray):
        """The whole move for a just-trained cohort: top-K by fitness,
        gather, swap.  ``cohort_engine`` is any engine exposing the
        member-stacked ``_params`` tree — ``PopulationTrainEngine``
        (supervised nets AND CD-k RBM cohorts, whose step body the
        shared Keel builders already trace) or
        :class:`~veles_tpu.ops.kohonen.SOMPopulationEngine` — whose
        :meth:`run` returned ``fitness``; its stacked params must
        still be live (adopt BEFORE ``release()``)."""
        stacked = cohort_engine._params
        if stacked is None:
            raise RuntimeError(
                "cohort engine already released its stacked params; "
                "adopt_cohort must run before release()")
        return self.adopt(stacked, self.top_k(fitness))

    def refresh_host(self) -> None:
        """Fetch host member copies of the served params and hand them
        to the residency manager (the spill/restore source of truth) —
        called OFF the handoff critical path, after serving started."""
        stacked = self.engine.stacked_params
        members: List[Dict[str, Dict[str, np.ndarray]]] = []
        for i in range(self.k):
            members.append({
                fn: {pn: np.asarray(arr[i])
                     for pn, arr in d.items()}
                for fn, d in stacked.items()})
        self.manager.refresh_host_params(self.name, members)
