"""The dynamic micro-batching loop.

One request is one or more sample rows; one DISPATCH is one
fixed-shape mask-padded chunk of ``max_batch`` rows.  The batcher sits
between them: concurrent ``submit()`` calls append rows to a queue,
and a dedicated flush thread dispatches a chunk as soon as either

- ``max_batch`` rows have coalesced (throughput bound), or
- the OLDEST queued request has waited long enough (latency bound).

"Long enough" defaults to ADAPTIVE ($VELES_SERVE_ADAPTIVE_WAIT): the
batcher tracks inter-arrival gaps in a local windowed histogram (the
Sentinel delta-quantile estimator pattern) and, once it has an
estimate, compares the quiet time since the last submit against a
PACE bar — 2x the windowed median gap, clamped into [0.1ms,
``max_wait_s``].  Arrivals keeping pace with batch room left STRETCH
the window up to ``$VELES_SERVE_WAIT_STRETCH`` x the knob (more
traffic is provably coming — waiting fills the batch); quiet past the
bar COLLAPSES it and flushes immediately (nothing else is coming —
waiting only adds latency).  The clamp keeps sparse traffic honest: a
lone request reaches the bar at the static deadline at the latest, so
it NEVER waits past ``$VELES_SERVE_MAX_WAIT_MS``, and under clumped
arrivals it usually flushes well before it.  Cold start (no estimate
yet) is exactly the static behavior.

Every dispatch has the SAME array shape (short batches are zero-padded
and the padding discarded host-side), so the engine's jitted dispatch
compiles exactly once — the zero-steady-state-recompile property the
serving bench pins.  Requests larger than ``max_batch`` are split
across consecutive dispatches and their Future resolves when the last
slice lands.

Telemetry (registry names in veles_tpu/events.py): per-request latency
histogram (``serve.request_seconds``), queue-depth gauge, batch-size
histogram (``serve.batch_rows`` — its max > 1 IS the proof requests
coalesced), padded-slot counters for the batch-efficiency ratio, and a
queue-wait histogram (the cost of the coalescing window).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from veles_tpu import events, faults, knobs, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.ops import batching


class DeadlineExpired(RuntimeError):
    """A queued request's ``deadline_ms`` passed before its dispatch.

    The batcher drops the request INSTEAD of computing an answer
    nobody is waiting for — the router-side waiter already gave up (or
    hedged onto a peer), so dispatching it would only steal the window
    from requests that can still make their deadline."""


class _Pending:
    """One submitted request: its rows, result slots, and Future."""

    __slots__ = ("rows", "future", "t0", "results", "taken", "popped",
                 "deadline_ms", "ctx", "wait_s")

    def __init__(self, rows: np.ndarray,
                 deadline_ms: Optional[float] = None,
                 ctx: Optional[trace.TraceContext] = None) -> None:
        self.rows = rows
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        #: absolute unix-epoch milliseconds (the wire clock shared
        #: with the router); None = no deadline
        self.deadline_ms = deadline_ms
        #: Flightline span of this request (None when untraced)
        self.ctx = ctx
        #: queue wait of the first slice (the coalescing-window cost,
        #: split out on the request's trace.serve journal entry)
        self.wait_s: Optional[float] = None
        #: result slices in submission order (multi-dispatch requests)
        self.results: List[np.ndarray] = []
        #: rows already handed to a dispatch
        self.taken = 0
        #: fully taken off the queue (counts toward _inflight)
        self.popped = False


class MicroBatcher:
    """Coalesce concurrent row requests into fixed-shape dispatches.

    ``dispatch(xb) -> np.ndarray`` is the flush callback: it receives
    the padded ``(max_batch, *sample_shape)`` array and returns the
    per-row outputs at the same leading shape (the batcher slices the
    valid rows back out and scatters them to the right Futures).
    """

    def __init__(self, dispatch: Callable[[np.ndarray], np.ndarray],
                 max_batch: int, max_wait_s: float,
                 label: str = "serve",
                 sample_shape: Optional[Tuple[int, ...]] = None
                 ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = max(0.0, float(max_wait_s))
        self.label = label
        self._cond = witness.condition("batcher.queue")
        self._queue: "deque[_Pending]" = deque()
        #: authoritative per-sample shape when the model declares one;
        #: otherwise pinned by the first request
        self._sample_shape = tuple(sample_shape) if sample_shape \
            else None
        #: adaptive-wait state (all mutated under _cond): a LOCAL
        #: inter-arrival-gap histogram — per batcher, never registered
        #: globally — plus the Sentinel-style cached windowed estimate
        self._adaptive = bool(knobs.get(knobs.SERVE_ADAPTIVE_WAIT)) \
            and self.max_wait_s > 0.0
        self._stretch = max(1.0, float(
            knobs.get(knobs.SERVE_WAIT_STRETCH)))
        self._gap_hist = telemetry.Histogram(
            f"batcher.{label}.gap") if self._adaptive else None
        self._last_arrival: Optional[float] = None
        self._gap_base = None
        self._gap_cache: Tuple[Optional[float], float] = (None, 0.0)
        self._queued_rows = 0
        self._inflight = 0          # requests taken but not resolved
        #: monotonic ts of the last submit/resolve — with an empty
        #: queue, "now - last_activity" is the serving idle gap the
        #: online scavenger (veles_tpu/online) steals train steps from
        self.last_activity = time.monotonic()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"hive-batcher-{label}")
        self._thread.start()

    # -- producer side -------------------------------------------------

    def submit(self, rows: Any,
               deadline_ms: Optional[float] = None,
               ctx: Optional[trace.TraceContext] = None) -> Future:
        """Enqueue one request of ``rows`` (one or more samples);
        returns a Future resolving to the per-row outputs in request
        order.  Thread-safe; never blocks on the device.

        ``deadline_ms`` (absolute unix-epoch milliseconds) marks when
        the caller stops waiting: a request still fully queued past it
        is dropped with :class:`DeadlineExpired` instead of
        dispatched.  ``ctx`` (a sampled Flightline span) makes the
        request's coalescing first-class: its ``trace.serve`` journal
        entry splits queue wait from device dispatch, and the batch
        that carried it LINKS its span (``trace.batch``)."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 0 or len(rows) == 0:
            raise ValueError("a request needs at least one sample row")
        p = _Pending(rows, deadline_ms=float(deadline_ms)
                     if deadline_ms is not None else None,
                     ctx=ctx if ctx is not None and ctx.sampled
                     else None)
        with self._cond:
            if self._closed:
                raise RuntimeError(f"batcher {self.label!r} is closed")
            # requests coalesce by CONCATENATION: a mismatched sample
            # shape must bounce here (one error response), never reach
            # the flush thread where it would poison a whole batch
            shape = tuple(rows.shape[1:])
            if self._sample_shape is None:
                self._sample_shape = shape
            elif shape != self._sample_shape:
                raise ValueError(
                    f"request rows have sample shape {shape}, but "
                    f"{self.label!r} serves {self._sample_shape}")
            self._queue.append(p)
            self._queued_rows += len(rows)
            if self._gap_hist is not None:
                now = time.perf_counter()
                if self._last_arrival is not None:
                    self._gap_hist.record(now - self._last_arrival)
                self._last_arrival = now
            self.last_activity = time.monotonic()
            telemetry.gauge(events.GAUGE_SERVE_QUEUE_DEPTH).set(
                self._queued_rows)
            self._cond.notify_all()
        return p.future

    @property
    def pending_rows(self) -> int:
        """Queued rows + in-flight requests, as plain int reads (no
        lock): the scavenger's busy check must never take the batcher
        lock from another thread just to peek."""
        return self._queued_rows + self._inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and every taken request has
        resolved — the graceful-stop path dispatches everything that
        was accepted before the drain began.  False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    def close(self) -> None:
        """Refuse new submissions, drain what was accepted, and stop
        the flush thread."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self.drain()
        self._thread.join(timeout=5.0)

    # -- flush loop ----------------------------------------------------

    def _gap_estimate(self, now: float) -> Optional[float]:
        """Median inter-arrival gap of the RECENT window, seconds —
        the Sentinel hedge-threshold move: a cached value recomputed
        at most every 0.25s from the histogram's bucket deltas since
        the last recompute, falling back to the cumulative median
        while the window is still sparse.  None until enough gaps
        have been observed (cold start = static behavior).  Called
        under ``_cond``; O(buckets) at worst, a tuple read usually."""
        est, recompute_at = self._gap_cache
        if now < recompute_at:
            return est
        h = self._gap_hist
        base, self._gap_base = self._gap_base, h.snapshot_buckets()
        gap = h.delta_quantile(base, 0.5, min_count=8) \
            if base is not None else None
        if gap is None and h.count >= 8:
            gap = h.quantile(0.5)
        self._gap_cache = (gap, now + 0.25)
        return gap

    def _wait_left(self, now: float, oldest: float) -> float:
        """Seconds the flush thread may still wait before dispatching
        the oldest queued request; <= 0 flushes NOW.  Static mode:
        ``max_wait_s`` minus the age.  Adaptive mode is purely
        ADDITIVE on top of the static deadline — no window ever
        flushes before ``max_wait_s`` would have, so the static
        clump-aggregation behaviour is the floor, never degraded:

        - the window STRETCHES to ``stretch x max_wait_s`` only when
          the observed cadence predicts the batch actually FILLS
          inside it (``gap x missing rows`` from now) AND arrivals
          are keeping pace — a trickle that keeps pace but can never
          fill pays the static deadline, not stretch x it;
        - a stretched window whose flow stops (quiet past the PACE
          bar, ``2x the windowed median gap`` clamped into
          ``[max_wait_s/20, max_wait_s]``) COLLAPSES back: it flushes
          at the static deadline or immediately if already past it,
          so an aborted stretch costs at most one pace bar beyond
          static.

        The pace bar can sit far below ``max_wait_s`` without risking
        the static behaviour: it only ever ABORTS a stretch, never
        flushes a window the static policy would still be holding, so
        a tight bar just means stalled stretches give up quickly."""
        limit = self.max_wait_s
        if self._gap_hist is not None:
            gap = self._gap_estimate(now)
            if gap is not None and self._last_arrival is not None:
                need = self.max_batch - self._queued_rows
                fills = 0 < need and \
                    (now - oldest) + gap * need <= \
                    self._stretch * self.max_wait_s
                if fills:
                    stall = now - self._last_arrival
                    pace = min(max(2.0 * gap,
                                   0.05 * self.max_wait_s),
                               self.max_wait_s)
                    if stall >= pace:
                        # flow stopped mid-stretch: abort back to the
                        # static deadline (flush NOW if already past)
                        if now - oldest >= self.max_wait_s:
                            telemetry.counter(
                                events.CTR_SERVE_WAIT_COLLAPSED).inc()
                            return 0.0
                    else:
                        # wake no later than the instant the pace bar
                        # trips: a held-open window must re-check the
                        # stall even when no new submit arrives to
                        # notify the flush thread, or collapse could
                        # never fire mid-sleep
                        limit = self._stretch * self.max_wait_s
                        return min(limit - (now - oldest),
                                   pace - stall)
        return limit - (now - oldest)

    def _take_batch(self) -> Optional[List[Tuple[_Pending, int, int]]]:
        """Wait for a flushable batch; returns [(request, start_row,
        n_rows)] covering up to ``max_batch`` rows, or None when closed
        and empty.  Flush condition: max_batch rows queued, or the
        oldest request older than max_wait_s.  Fully-queued requests
        whose ``deadline_ms`` already passed are dropped with
        :class:`DeadlineExpired` instead of dispatched (a request with
        slices already in flight finishes — that compute is spent)."""
        while True:
            expired: List[_Pending] = []
            with self._cond:
                while True:
                    now_ms = time.time() * 1000.0
                    for p in list(self._queue):
                        if p.taken == 0 and p.deadline_ms is not None \
                                and now_ms > p.deadline_ms:
                            self._queue.remove(p)
                            self._queued_rows -= len(p.rows)
                            expired.append(p)
                    if expired:
                        telemetry.gauge(
                            events.GAUGE_SERVE_QUEUE_DEPTH).set(
                            self._queued_rows)
                        break
                    if self._queue:
                        oldest = self._queue[0].t0
                        if self._queued_rows >= self.max_batch:
                            break
                        now = time.perf_counter()
                        wait_left = self._wait_left(now, oldest)
                        if wait_left <= 0:
                            waited = now - oldest
                            if waited > self.max_wait_s:
                                telemetry.counter(
                                    events.CTR_SERVE_WAIT_STRETCHED
                                ).inc()
                            telemetry.gauge(
                                events.GAUGE_SERVE_EFFECTIVE_WAIT_MS
                            ).set(round(waited * 1000.0, 3))
                            break
                        self._cond.wait(min(wait_left, 0.05))
                    elif self._closed:
                        return None
                    else:
                        self._cond.wait(0.05)
                if not expired:
                    take: List[Tuple[_Pending, int, int]] = []
                    room = self.max_batch
                    while room > 0 and self._queue:
                        p = self._queue[0]
                        rem = len(p.rows) - p.taken
                        if rem > room and take:
                            # whole requests coalesce; only a request
                            # that is ALONE bigger than max_batch ever
                            # splits (its slices lead consecutive
                            # dispatches)
                            break
                        n = min(room, rem)
                        take.append((p, p.taken, n))
                        p.taken += n
                        room -= n
                        self._queued_rows -= n
                        if p.taken >= len(p.rows):
                            self._queue.popleft()
                            p.popped = True
                            self._inflight += 1
                    telemetry.gauge(events.GAUGE_SERVE_QUEUE_DEPTH).set(
                        self._queued_rows)
                    return take
            # outside the lock: the futures' done-callbacks (the
            # hive's error emit) must not run under the batcher lock
            telemetry.counter(events.CTR_SERVE_DEADLINE_DROPPED).inc(
                len(expired))
            now_ms = time.time() * 1000.0
            for p in expired:
                if not p.future.done():
                    p.future.set_exception(DeadlineExpired(
                        f"request expired "
                        f"{now_ms - p.deadline_ms:.0f}ms past its "
                        f"deadline before dispatch"))

    def _loop(self) -> None:
        while True:
            take = self._take_batch()
            if take is None:
                return
            rows = np.concatenate([p.rows[s:s + n]
                                   for p, s, n in take])
            n_valid = len(rows)
            xb, _mask = batching.pad_rows(rows, self.max_batch)
            t_wait = time.perf_counter()
            for p, s, n in take:
                if s == 0:
                    p.wait_s = t_wait - p.t0
                    telemetry.histogram(
                        events.HIST_SERVE_WAIT_SECONDS).record(
                        t_wait - p.t0,
                        exemplar=p.ctx.trace_id if p.ctx else None)
            f = faults.fire("hive.slow_dispatch", label=self.label)
            if f:
                # Faultline gray-failure rehearsal: the dispatch
                # stalls but the process stays alive and heartbeating
                time.sleep(float(f.get("seconds", 0.25)))
            try:
                out = self.dispatch(xb)
            except BaseException as e:  # noqa: BLE001 — a failed
                # dispatch fails exactly the requests it carried; the
                # loop (and the other queued requests) live on
                telemetry.counter(
                    events.CTR_SERVE_REQUEST_ERRORS).inc(len(take))
                self._resolve(take, None, err=e)
                continue
            dispatch_s = time.perf_counter() - t_wait
            links = [{"trace": p.ctx.trace_id, "span": p.ctx.span_id}
                     for p, s, n in take if p.ctx is not None and s == 0]
            if len(links) >= 2:
                # one batch-dispatch span LINKS its member request
                # spans — coalescing is a fan-in, not a parent/child.
                # A singleton batch coalesced nothing: its serve event
                # already carries the dispatch, so skip the link event
                # (per-request journal writes are the tracing overhead
                # the bench gate bounds)
                telemetry.event(events.EV_TRACE_BATCH,
                                span=trace.new_span_id(),
                                label=self.label, rows=n_valid,
                                dispatch_s=round(dispatch_s, 6),
                                links=links)
                trace.record("serve.batch", label=self.label,
                             rows=n_valid,
                             dispatch_s=round(dispatch_s, 6))
            telemetry.counter(events.CTR_SERVE_BATCHES).inc()
            telemetry.counter(events.CTR_SERVE_ROWS).inc(n_valid)
            telemetry.counter(events.CTR_SERVE_BATCH_SLOTS).inc(
                self.max_batch)
            telemetry.histogram(events.HIST_SERVE_BATCH_ROWS).record(
                n_valid)
            self._resolve(take, np.asarray(out), dispatch_s=dispatch_s)

    def _resolve(self, take, out, err=None, dispatch_s=None) -> None:
        off = 0
        done: List[_Pending] = []
        for p, s, n in take:
            if err is None:
                p.results.append(out[off:off + n])
            off += n
            if err is not None:
                if not p.future.done():
                    p.future.set_exception(err)
                done.append(p)
            elif s + n >= len(p.rows):   # request fully covered
                if not p.future.done():  # a prior slice may have erred
                    p.future.set_result(np.concatenate(p.results)
                                        if len(p.results) > 1
                                        else p.results[0])
                done.append(p)
        now = time.perf_counter()
        self.last_activity = time.monotonic()
        for p in done:
            if p.ctx is not None:
                # the request's serve-side span: queue wait vs device
                # dispatch split — the two halves the critical-path
                # renderer attributes (trace.serve)
                telemetry.event(
                    events.EV_TRACE_SERVE,
                    trace=p.ctx.trace_id, span=p.ctx.span_id,
                    parent=p.ctx.parent_id, label=self.label,
                    rows=int(len(p.rows)),
                    wait_s=round(p.wait_s, 6)
                    if p.wait_s is not None else None,
                    dispatch_s=round(dispatch_s, 6)
                    if dispatch_s is not None else None,
                    total_s=round(now - p.t0, 6),
                    error=type(err).__name__ if err is not None
                    else None)
        with self._cond:
            for p in done:
                telemetry.histogram(
                    events.HIST_SERVE_REQUEST_SECONDS).record(
                    now - p.t0,
                    exemplar=p.ctx.trace_id if p.ctx else None)
                if p.popped:
                    self._inflight -= 1
                elif self._queue and self._queue[0] is p:
                    # an erred oversized request still parked at the
                    # head: retire it so its tail never dispatches
                    self._queued_rows -= len(p.rows) - p.taken
                    self._queue.popleft()
                    p.popped = True
            self._cond.notify_all()
