"""Sentinel: gray-failure defense for the serving fleet.

Swarm (veles_tpu/serve/fleet.py) survives CLEAN replica death — reader
EOF or total heartbeat silence fires ``ReplicaDied`` and the monitor
respawns the corpse.  Everything short of death used to be
indistinguishable from health: a replica dispatching at 10x its peers'
latency, a wedged batcher that still heartbeats, a corrupt response.
These gray failures are the dominant real-world failure mode in
serving fleets (the tail-at-scale problem: the slowest 1% of replicas
sets the p99 for everyone).  Sentinel is the router-side defense, three
mechanisms that compose:

- **request deadlines** — every request carries an absolute
  ``deadline_ms`` end-to-end (router -> hive batcher, which drops
  already-expired rows before dispatch), so a waiter burns at most
  ``$VELES_FLEET_DEADLINE_MS`` against a wedged-but-heartbeating
  replica instead of a flat 60s timeout;
- **hedged requests** — a request older than the adaptive hedge
  threshold (the model's measured p95 latency, floored by
  ``$VELES_FLEET_HEDGE_MIN_MS``) is reissued on a second healthy
  replica; the first answer wins and the loser is cancelled by wire id
  (its late response is dropped + counted ``fleet.stale_response``).
  Hedge traffic is capped at ``$VELES_FLEET_HEDGE_BUDGET`` of admitted
  requests so hedging cannot melt an overloaded fleet;
- **outlier ejection** — a per-replica health score folds weighted
  strikes (deadline/timeout misses, ``ReplicaDied`` retries,
  response-integrity failures, hedge losses, latency z-score outliers
  vs fleet peers) with exponential time decay; a replica breaching
  ``$VELES_FLEET_EJECT_THRESHOLD`` is removed from routing, probed
  with synthetic canary requests on backoff, and reinstated only
  after ``$VELES_FLEET_PROBE_OK`` consecutive clean probes.  Ejection
  is capped at N-1 replicas: the fleet degrades, it never
  self-destructs.

The router owns exactly one Sentinel; every routing decision asks
``eligible()``, every outcome reports back through the ``record_*``
methods, and the probe loop runs on the sentinel's own daemon thread.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, knobs, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger

STATE_HEALTHY = "healthy"
STATE_EJECTED = "ejected"
STATE_PROBING = "probing"

#: strike weights — one deadline miss or death is a full strike,
#: integrity failures weigh more (wrong answers beat slow answers),
#: hedge losses and latency outliers accumulate more slowly (they are
#: statistical evidence, not hard failures)
WEIGHT_TIMEOUT = 1.0
WEIGHT_DIED = 1.0
WEIGHT_INTEGRITY = 1.5
WEIGHT_HEDGE_LOSS = 0.75
WEIGHT_SLOW = 0.5

#: health-score strikes decay with this time constant: an isolated
#: blip is forgotten in a minute, a persistent gray failure is not
DECAY_TAU_S = 30.0

#: latency z-score vs fleet peers above which a replica earns a slow
#: strike (std floored at 20% of the peer mean so one quiet fleet
#: can't divide by ~zero), rate-limited to one strike per second
Z_THRESHOLD = 3.0
Z_MIN_GAP_MS = 10.0
Z_STRIKE_MIN_INTERVAL_S = 1.0

#: probe request timeout floor and backoff cap
PROBE_TIMEOUT_MIN_S = 1.0
PROBE_BACKOFF_CAP_S = 10.0


class ReplicaHealth:
    """One replica's decaying health score + probe lifecycle state."""

    __slots__ = ("idx", "state", "score", "last_decay", "strikes",
                 "hedge_wins", "hedge_losses", "lat_ema_s",
                 "last_z_strike", "probe_ok_streak", "probe_fails",
                 "next_probe_at", "probe_backoff_s", "ejections",
                 "reinstatements", "probing")

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.state = STATE_HEALTHY
        self.score = 0.0
        self.last_decay = time.monotonic()
        #: strike counts by kind (timeout/died/integrity/hedge_loss/
        #: slow) — the operator-facing "why is it out of rotation"
        self.strikes: Dict[str, int] = {}
        self.hedge_wins = 0
        self.hedge_losses = 0
        #: EMA of this replica's observed request latency (seconds)
        self.lat_ema_s: Optional[float] = None
        self.last_z_strike = 0.0
        self.probe_ok_streak = 0
        self.probe_fails = 0
        self.next_probe_at = 0.0
        self.probe_backoff_s = 0.0
        self.ejections = 0
        self.reinstatements = 0
        #: True once the first probe of the CURRENT ejection has been
        #: sent (renders the state as "probing" rather than "ejected")
        self.probing = False

    def decayed_score(self, now: float) -> float:
        dt = now - self.last_decay
        if dt > 0:
            self.score *= math.exp(-dt / DECAY_TAU_S)
            self.last_decay = now
        return self.score

    def public_state(self) -> str:
        if self.state == STATE_EJECTED:
            return STATE_PROBING if self.probing else STATE_EJECTED
        return STATE_HEALTHY


class Sentinel(Logger):
    """Per-replica health scoring, hedging governor, ejection+probes.

    ``probe_fn(replica, model, rows) -> (ok, detail)`` is supplied by
    the router: one synthetic canary request aimed straight at the
    replica (bypassing routing), verified end to end — answered inside
    the probe deadline AND integrity-clean.
    """

    def __init__(self, replicas: List[Any],
                 probe_fn: Callable[[Any, str, np.ndarray],
                                    Tuple[bool, str]],
                 hedge_min_ms: Optional[float] = None,
                 hedge_budget: Optional[float] = None,
                 eject_threshold: Optional[float] = None,
                 probe_ok: Optional[int] = None,
                 probe_interval: Optional[float] = None,
                 probe_backoff_cap: float = PROBE_BACKOFF_CAP_S
                 ) -> None:
        self.replicas = replicas
        self.probe_fn = probe_fn
        self.hedge_min_ms = float(hedge_min_ms) \
            if hedge_min_ms is not None \
            else float(knobs.get(knobs.FLEET_HEDGE_MIN_MS))
        self.hedge_budget = float(hedge_budget) \
            if hedge_budget is not None \
            else float(knobs.get(knobs.FLEET_HEDGE_BUDGET))
        self.eject_threshold = float(eject_threshold) \
            if eject_threshold is not None \
            else float(knobs.get(knobs.FLEET_EJECT_THRESHOLD))
        self.probe_ok = int(probe_ok) if probe_ok is not None \
            else int(knobs.get(knobs.FLEET_PROBE_OK))
        self.probe_interval = float(probe_interval) \
            if probe_interval is not None \
            else float(knobs.get(knobs.FLEET_PROBE_INTERVAL))
        self.probe_backoff_cap = float(probe_backoff_cap)
        self._lock = witness.lock("sentinel.health")
        self.health: Dict[int, ReplicaHealth] = {
            r.idx: ReplicaHealth(r.idx) for r in replicas}
        self._requests_seen = 0
        self._hedges_issued = 0
        #: {model: (threshold_ms, recompute_after)} — the p95 scan is
        #: too hot to run per request at fleet QPS
        self._thr_cache: Dict[str, Tuple[float, float]] = {}
        #: {model: histogram snapshot} — the previous recompute's
        #: bucket base, so the p95 is WINDOWED (last ~0.5s), not
        #: cumulative: a cumulative quantile lags a load shift so
        #: badly that most spike requests would cross the threshold
        #: instead of the intended slowest ~5%
        self._thr_base: Dict[str, Any] = {}
        #: last-seen single-row probe template per model (a synthetic
        #: canary must exercise the REAL inference path)
        self._templates: Dict[str, np.ndarray] = {}
        self._closing = False
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="fleet-sentinel-probe")
        self._probe_thread.start()

    # -- elastic membership --------------------------------------------

    def _h(self, idx: int) -> ReplicaHealth:
        """The idx's health record, created on first sight — MUST be
        called under ``_lock``.  An elastic fleet adds members after
        the ctor, and a record the scoring paths race to create must
        never KeyError a request thread."""
        h = self.health.get(idx)
        if h is None:
            h = self.health[idx] = ReplicaHealth(idx)
        return h

    def add_replica(self, replica: Any) -> None:
        """A scale-up joined ``self.replicas`` (the shared list the
        router mutates); give it a fresh health record."""
        with self._lock:
            self.health[replica.idx] = ReplicaHealth(replica.idx)

    def remove_replica(self, replica: Any) -> None:
        """A scale-down left the fleet: drop its record so the peer
        latency stats and the ejected count stop seeing a ghost."""
        with self._lock:
            self.health.pop(replica.idx, None)

    # -- routing-side queries ------------------------------------------

    def eligible(self, replica: Any) -> bool:
        """May the router send this replica traffic?  (Process-level
        health is the ReplicaSet's call; this is the gray-failure
        overlay.)"""
        with self._lock:
            return self._h(replica.idx).state == STATE_HEALTHY

    def ejected_count(self) -> int:
        with self._lock:
            return sum(1 for h in self.health.values()
                       if h.state == STATE_EJECTED)

    def hedge_threshold_ms(self, model: str) -> float:
        """When a request of ``model`` is old enough to hedge: the
        model's measured p95 latency, floored by
        ``$VELES_FLEET_HEDGE_MIN_MS`` — the floor keeps a fast model
        from hedging on microscopic jitter.  The p95 is WINDOWED
        (samples since the previous recompute, ~0.5s) so the
        threshold tracks the live distribution — by construction only
        the slowest ~5% of current traffic outlives it; sparse
        traffic (too few windowed samples) falls back to the
        cumulative p95.  Cached for 0.5s per model: the quantile scan
        is too expensive to run on every request at fleet QPS."""
        now = time.monotonic()
        with self._lock:
            cached = self._thr_cache.get(model)
            if cached is not None and now < cached[1]:
                return cached[0]
        h = telemetry.histogram(f"fleet.model.{model}.request_seconds")
        with self._lock:
            base = self._thr_base.get(model)
            self._thr_base[model] = h.snapshot_buckets()
        p95 = h.delta_quantile(base, 0.95, min_count=20) \
            if base is not None else None
        if p95 is None and h.count >= 20:
            p95 = h.quantile(0.95)
        thr = self.hedge_min_ms
        if p95 is not None:
            thr = max(thr, 1000.0 * p95)
        telemetry.gauge(events.GAUGE_FLEET_HEDGE_THRESHOLD_MS).set(
            round(thr, 3))
        with self._lock:
            self._thr_cache[model] = (thr, now + 0.5)
        return thr

    def note_request(self, model: str, rows: Any) -> None:
        """One admitted request: budget accounting + the probe
        template capture (first row only — a probe is one synthetic
        sample, not a replayed batch)."""
        with self._lock:
            self._requests_seen += 1
            if model not in self._templates:
                try:
                    self._templates[model] = np.asarray(
                        rows, np.float32)[:1].copy()
                except (TypeError, ValueError, IndexError):
                    pass

    def allow_hedge(self) -> bool:
        """May one more hedge be issued under the budget?  Consumes
        the budget slot when it says yes."""
        if self.hedge_budget <= 0:
            return False
        with self._lock:
            if self._hedges_issued + 1 > \
                    self.hedge_budget * self._requests_seen + 1:
                return False
            self._hedges_issued += 1
        return True

    def hedge_rate(self) -> float:
        with self._lock:
            return self._hedges_issued / max(1, self._requests_seen)

    # -- outcome reports -----------------------------------------------

    def record_ok(self, replica: Any, model: str,
                  latency_s: float) -> None:
        """A clean answer: refresh the latency EMA and check the
        latency-outlier signal (a replica consistently far above its
        peers earns slow strikes even when nothing ever times out)."""
        del model
        now = time.monotonic()
        strike = False
        with self._lock:
            h = self._h(replica.idx)
            h.decayed_score(now)
            h.lat_ema_s = latency_s if h.lat_ema_s is None \
                else 0.8 * h.lat_ema_s + 0.2 * latency_s
            peers = [p.lat_ema_s for i, p in self.health.items()
                     if i != replica.idx and p.lat_ema_s is not None]
            if peers and h.lat_ema_s is not None \
                    and now - h.last_z_strike \
                    >= Z_STRIKE_MIN_INTERVAL_S:
                mean = sum(peers) / len(peers)
                var = sum((p - mean) ** 2 for p in peers) / len(peers)
                std = max(math.sqrt(var), 0.2 * mean, 1e-6)
                z = (h.lat_ema_s - mean) / std
                if z > Z_THRESHOLD \
                        and (h.lat_ema_s - mean) * 1000.0 \
                        > Z_MIN_GAP_MS:
                    h.last_z_strike = now
                    strike = True
        if strike:
            self._strike(replica, "slow", WEIGHT_SLOW)

    def record_timeout(self, replica: Any) -> None:
        """The replica failed to answer inside the request deadline
        (router-side expiry or the hive's own expired-drop echo)."""
        telemetry.counter(events.CTR_FLEET_DEADLINE_MISSES).inc()
        self._strike(replica, "timeout", WEIGHT_TIMEOUT)

    def record_died(self, replica: Any) -> None:
        """The replica died under a request (``ReplicaDied``)."""
        self._strike(replica, "died", WEIGHT_DIED)

    def record_integrity(self, replica: Any) -> None:
        """The replica's response failed the row-count/crc echo."""
        telemetry.counter(events.CTR_FLEET_INTEGRITY_STRIKES).inc()
        self._strike(replica, "integrity", WEIGHT_INTEGRITY)

    def record_hedge_win(self, winner: Any, loser: Any) -> None:
        """The hedge answered first: credit the winner, strike the
        replica that sat on the request past the hedge threshold —
        repeated hedge losses ARE the slow-replica signal when the
        deadline is too generous to ever expire."""
        telemetry.counter(events.CTR_FLEET_HEDGE_WINS).inc()
        with self._lock:
            self._h(winner.idx).hedge_wins += 1
            self._h(loser.idx).hedge_losses += 1
        telemetry.counter(
            f"fleet.replica.{winner.idx}.hedge_wins").inc()
        self._strike(loser, "hedge_loss", WEIGHT_HEDGE_LOSS)

    # -- scoring / ejection --------------------------------------------

    def _strike(self, replica: Any, kind: str, weight: float) -> None:
        now = time.monotonic()
        eject = False
        with self._lock:
            h = self._h(replica.idx)
            h.strikes[kind] = h.strikes.get(kind, 0) + 1
            strikes = dict(h.strikes)
            score = h.decayed_score(now) + weight
            h.score = score
            if h.state == STATE_HEALTHY \
                    and score >= self.eject_threshold \
                    and self._can_eject_locked(replica):
                h.state = STATE_EJECTED
                h.probing = False
                h.ejections += 1
                h.probe_ok_streak = 0
                h.probe_backoff_s = self.probe_interval
                h.next_probe_at = now + self.probe_interval
                eject = True
        telemetry.gauge(
            f"fleet.replica.{replica.idx}.health_score").set(
            round(score, 3))
        if eject:
            telemetry.counter(events.CTR_FLEET_EJECTIONS).inc()
            telemetry.gauge(events.GAUGE_FLEET_REPLICAS_EJECTED).set(
                self.ejected_count())
            telemetry.event(
                events.EV_FLEET_REPLICA_EJECTED, replica=replica.idx,
                state=STATE_EJECTED, score=round(score, 3),
                strikes=strikes)
            # an ejection is exactly when an operator asks "what was
            # the fleet doing?" — dump the flight recorder's ring
            # (recent legs, hedges, strikes) alongside the event
            trace.record("sentinel.eject", replica=replica.idx,
                         score=round(score, 3))
            trace.dump("ejection")
            self.warning(
                "replica %d EJECTED from routing (health score %.2f "
                ">= %.2f; strikes %s) — probing on backoff",
                replica.idx, score, self.eject_threshold, strikes)

    def _can_eject_locked(self, replica: Any) -> bool:
        """Ejection is capped at N-1: at least one OTHER replica must
        remain routable (process-healthy and not ejected), else the
        fleet must keep limping on the sick replica rather than
        refusing all traffic."""
        for r in self.replicas:
            if r.idx == replica.idx:
                continue
            if getattr(r, "healthy", False) \
                    and self._h(r.idx).state == STATE_HEALTHY:
                return True
        return False

    # -- probe / reinstate lifecycle -----------------------------------

    def _probe_loop(self) -> None:
        while not self._closing:
            time.sleep(0.05)
            if self._closing:
                return
            now = time.monotonic()
            for r in self.replicas:
                if self._closing:
                    return
                with self._lock:
                    h = self._h(r.idx)
                    due = h.state == STATE_EJECTED \
                        and now >= h.next_probe_at \
                        and getattr(r, "healthy", False)
                if due:
                    self._probe_once(r)

    def _pick_probe(self) -> Optional[Tuple[str, np.ndarray]]:
        with self._lock:
            if not self._templates:
                return None
            model = next(iter(self._templates))
            return model, self._templates[model]

    def _probe_once(self, replica: Any) -> None:
        tpl = self._pick_probe()
        if tpl is None:
            # no traffic observed yet — nothing to probe with; retry
            # on the same schedule
            with self._lock:
                self._h(replica.idx).next_probe_at = \
                    time.monotonic() + self.probe_interval
            return
        model, rows = tpl
        with self._lock:
            self._h(replica.idx).probing = True
        telemetry.counter(events.CTR_FLEET_PROBES).inc()
        try:
            ok, detail = self.probe_fn(replica, model, rows)
        except Exception as e:  # noqa: BLE001 — a probe crash is a
            ok, detail = False, f"{type(e).__name__}: {e}"  # failure
        now = time.monotonic()
        reinstate = False
        with self._lock:
            h = self._h(replica.idx)
            if ok:
                h.probe_ok_streak += 1
                h.probe_backoff_s = self.probe_interval
                if h.probe_ok_streak >= self.probe_ok \
                        and h.state == STATE_EJECTED:
                    h.state = STATE_HEALTHY
                    h.probing = False
                    h.score = 0.0
                    h.last_decay = now
                    h.strikes = {}
                    h.lat_ema_s = None
                    h.reinstatements += 1
                    reinstate = True
            else:
                h.probe_ok_streak = 0
                h.probe_fails += 1
                h.probe_backoff_s = min(
                    self.probe_backoff_cap,
                    max(self.probe_interval, h.probe_backoff_s * 2))
            h.next_probe_at = now + h.probe_backoff_s
            streak = h.probe_ok_streak
        telemetry.counter(events.CTR_FLEET_PROBES_OK if ok
                          else events.CTR_FLEET_PROBES_FAILED).inc()
        telemetry.event(
            events.EV_FLEET_PROBE_RESULT, replica=replica.idx,
            ok=bool(ok), streak=streak, model=model, detail=detail,
            state=STATE_HEALTHY if reinstate else STATE_PROBING)
        if reinstate:
            telemetry.counter(events.CTR_FLEET_REINSTATEMENTS).inc()
            telemetry.gauge(events.GAUGE_FLEET_REPLICAS_EJECTED).set(
                self.ejected_count())
            telemetry.gauge(
                f"fleet.replica.{replica.idx}.health_score").set(0.0)
            telemetry.event(
                events.EV_FLEET_REPLICA_REINSTATED,
                replica=replica.idx, state=STATE_HEALTHY,
                probes_ok=streak)
            self.info("replica %d REINSTATED after %d clean probes",
                      replica.idx, streak)

    # -- introspection / teardown --------------------------------------

    def status(self, replica: Any) -> Dict[str, Any]:
        """The operator's why-is-it-out-of-rotation row."""
        now = time.monotonic()
        with self._lock:
            h = self._h(replica.idx)
            return {
                "state": h.public_state(),
                "health_score": round(h.decayed_score(now), 3),
                "strikes": dict(h.strikes),
                "hedge_wins": h.hedge_wins,
                "hedge_losses": h.hedge_losses,
                "probe_ok_streak": h.probe_ok_streak,
                "probe_fails": h.probe_fails,
                "ejections": h.ejections,
                "reinstatements": h.reinstatements,
                "latency_ema_ms": round(1000.0 * h.lat_ema_s, 3)
                if h.lat_ema_s is not None else None,
            }

    def close(self) -> None:
        self._closing = True
        self._probe_thread.join(timeout=5.0)
