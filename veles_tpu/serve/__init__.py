"""Hive: device-resident multi-model serving with dynamic
micro-batching.

The persistent serving tier of the north star's "heavy traffic" half:
one process owns the chip (the proven ``worker.py --serve`` topology
— hello line, stdin JSONL jobs, heartbeats), keeps many Forge-packaged
models HBM-resident under an LRU residency budget, and coalesces
concurrent requests into fixed-shape mask-padded micro-batches so warm
steady state runs with ZERO recompiles.

- :mod:`veles_tpu.serve.batcher` — the dynamic micro-batching loop
  (``submit(rows) -> Future``; flushes at ``$VELES_SERVE_MAX_BATCH``
  rows or after ``$VELES_SERVE_MAX_WAIT_MS``);
- :mod:`veles_tpu.serve.residency` — the multi-model HBM residency
  manager (budget accounting + LRU spill-to-host);
- :mod:`veles_tpu.serve.hive` — the serving process
  (``python -m veles_tpu --serve-models NAME=PKG ...``);
- :mod:`veles_tpu.serve.client` — the line-protocol client used by
  tests, bench.py, and operators' smoke probes;
- :mod:`veles_tpu.serve.fleet` — replica lifecycle (spawn / monitor /
  respawn) and the model placement policy;
- :mod:`veles_tpu.serve.router` — Swarm, the SLO-aware fleet router
  (``python -m veles_tpu --serve-fleet N NAME=PKG ...``): N hive
  replicas, placement-aware least-loaded routing, once-on-a-peer
  failover, canary traffic mirroring, and admission-control shedding;
- :mod:`veles_tpu.serve.sentinel` — gray-failure defense: per-request
  deadlines, budget-capped hedging, response-integrity verification,
  and outlier ejection with probe-based reinstatement.
"""

from veles_tpu.serve.batcher import (DeadlineExpired,  # noqa: F401
                                     MicroBatcher)
from veles_tpu.serve.client import ReplicaDied  # noqa: F401
from veles_tpu.serve.fleet import PlacementPolicy  # noqa: F401
from veles_tpu.serve.residency import ResidencyManager  # noqa: F401
from veles_tpu.serve.sentinel import Sentinel  # noqa: F401
