"""Line-protocol client for the Hive serving process.

Spawns (or attaches to) a ``--serve-models`` subprocess and multiplexes
CONCURRENT callers over its stdin/stdout: every request draws a wire
id under a lock, a single reader thread routes response lines back to
per-id waiters, and heartbeats/garbage are tolerated as proof of life
(the ChipEvaluatorPool discipline).  This is the surface the serving
tests, bench.py's ``serve_*``/``fleet_*`` phases, the FleetRouter's
replicas (veles_tpu/serve/fleet.py), and operator smoke probes share.

Death semantics: when the replica's stdout reaches EOF (process exit,
SIGKILL, pipe loss), EVERY pending waiter fails immediately with
:class:`ReplicaDied` — never by waiting out its own timeout.  The
fleet router catches exactly that error to retry the request on a
healthy peer.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from veles_tpu import events, telemetry, trace
from veles_tpu.analysis import witness


class ReplicaDied(RuntimeError):
    """The serving subprocess died (EOF/exit) with requests pending.

    Distinguishable from :class:`TimeoutError` by construction: a
    caller blocked on a dead replica is failed the moment the reader
    thread sees EOF, so failover can retry on a peer immediately
    instead of burning the request timeout.  ``rc`` carries the
    replica's exit code when known (None while it is still dying).
    """

    def __init__(self, msg: str, rc: Optional[int] = None) -> None:
        super().__init__(msg)
        self.rc = rc


class HiveClient:
    """Own one serving subprocess; thread-safe request fan-in."""

    def __init__(self, models: Dict[str, str],
                 backend: str = "cpu",
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 hbm_budget: Optional[int] = None,
                 heartbeat_every: Optional[float] = None,
                 metrics_dir: Optional[str] = None,
                 install_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 online: bool = False,
                 mesh: int = 0,
                 start_timeout: float = 120.0) -> None:
        cmd = [sys.executable, "-m", "veles_tpu", "--serve-models"]
        cmd += [f"{name}={path}" for name, path in models.items()]
        cmd += ["-b", backend]
        if online:
            cmd += ["--online"]
        if mesh and mesh > 1:
            cmd += ["--mesh", str(int(mesh))]
        if max_batch is not None:
            cmd += ["--max-batch", str(max_batch)]
        if max_wait_ms is not None:
            cmd += ["--max-wait-ms", str(max_wait_ms)]
        if hbm_budget is not None:
            cmd += ["--hbm-budget", str(hbm_budget)]
        if heartbeat_every is not None:
            cmd += ["--heartbeat-every", str(heartbeat_every)]
        if metrics_dir is not None:
            cmd += ["--metrics-dir", metrics_dir]
        if install_dir is not None:
            # a respawned replica reuses its predecessor's install dir,
            # so the checksum-verified package unpack is warm
            cmd += ["--install-dir", install_dir]
        run_env = dict(os.environ)
        run_env.setdefault("JAX_PLATFORMS", "cpu")
        if env:
            run_env.update(env)
        if mesh and mesh > 1 and \
                run_env.get("JAX_PLATFORMS", "") == "cpu" and \
                "--xla_force_host_platform_device_count" not in \
                run_env.get("XLA_FLAGS", ""):
            # a CPU-backed mesh replica needs N virtual devices the
            # same way dryrun_multichip pins them (a real TPU backend
            # already enumerates its chips)
            run_env["XLA_FLAGS"] = (
                run_env.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={int(mesh)}"
            ).strip()
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1, env=run_env, cwd=cwd)
        self._wlock = witness.lock("client.wire")
        self._cond = witness.condition("client.results")
        self._results: Dict[int, Dict[str, Any]] = {}
        #: async collectors (wire id -> callback) — the canary-mirror
        #: path records telemetry without parking a thread per request
        self._callbacks: Dict[int, Callable[[Optional[Dict[str, Any]],
                                             Optional[BaseException]],
                                            None]] = {}
        #: wire ids whose waiter gave up (timeout cleanup / hedge
        #: loser): the late response is dropped + counted
        #: ``fleet.stale_response`` instead of leaking into _results
        self._cancelled: set = set()
        self._next_id = 0
        self._eof = False
        self.exit_rc: Optional[int] = None
        self.hello: Optional[Dict[str, Any]] = None
        self.heartbeats = 0
        #: monotonic time of the last stdout line (ANY line is proof of
        #: life — the pool/fleet heartbeat-deadline discipline)
        self.last_line_ts = time.monotonic()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name="hive-client-reader")
        self._reader.start()
        deadline = time.monotonic() + start_timeout
        with self._cond:
            while self.hello is None and not self._eof:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(min(left, 0.5))
        if self.hello is None:
            rc = self.proc.poll()
            self.close(kill=True)
            raise RuntimeError(
                f"hive did not come up (rc={rc})")

    # -- wire ----------------------------------------------------------

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid

    @property
    def dead(self) -> bool:
        """True once the reader saw EOF or the process exited."""
        return self._eof or self.proc.poll() is not None

    def _read_loop(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            self.last_line_ts = time.monotonic()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue   # non-protocol noise is proof of life
            cb = None
            stale = False
            with self._cond:
                if msg.get("ready"):
                    self.hello = msg
                elif "hb" in msg:
                    self.heartbeats += 1
                elif msg.get("id") is not None:
                    mid = msg["id"]
                    if mid in self._cancelled:
                        # a hedge loser / timed-out waiter's late
                        # answer: drop it — parking it in _results
                        # would leak it into another waiter forever
                        self._cancelled.discard(mid)
                        stale = True
                    elif not isinstance(mid, int) \
                            or mid > self._next_id:
                        stale = True   # an id this client never drew
                    else:
                        cb = self._callbacks.pop(mid, None)
                        if cb is None:
                            self._results[mid] = msg
                self._cond.notify_all()
            if stale:
                telemetry.counter(
                    events.CTR_FLEET_STALE_RESPONSES).inc()
            if cb is not None:
                self._run_callback(cb, msg, None)
        # EOF: the replica is gone — fail EVERY pending waiter and
        # async collector NOW (a caller must never wait out its own
        # timeout against a dead replica)
        rc = self.proc.poll()
        err = ReplicaDied(
            f"hive pid {self.proc.pid} closed its pipe (rc={rc})",
            rc=rc)
        with self._cond:
            self._eof = True
            self.exit_rc = rc
            callbacks = list(self._callbacks.values())
            self._callbacks.clear()
            self._cancelled.clear()   # nothing late can arrive now
            self._cond.notify_all()
        for cb in callbacks:
            self._run_callback(cb, None, err)

    @staticmethod
    def _run_callback(cb, msg, err) -> None:
        try:
            cb(msg, err)
        except Exception:  # noqa: BLE001 — a collector must not kill
            pass           # the reader thread

    def _send(self, obj: Dict[str, Any]) -> None:
        try:
            with self._wlock:
                self.proc.stdin.write(json.dumps(obj) + "\n")
                self.proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError) as e:
            raise ReplicaDied(
                f"hive pid {self.proc.pid} stdin is gone ({e})",
                rc=self.proc.poll()) from e

    def _draw_id(self) -> int:
        with self._wlock:
            self._next_id += 1
            return self._next_id

    def _wait(self, jid: int, timeout: float) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        with self._cond:
            while jid not in self._results:
                if self._eof:
                    raise ReplicaDied(
                        f"hive pid {self.proc.pid} died before "
                        f"answering request {jid}", rc=self.exit_rc)
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"no response for request {jid} in {timeout}s")
                self._cond.wait(min(left, 0.5))
            return self._results.pop(jid)

    # -- API -----------------------------------------------------------

    def submit(self, model: str, rows: Any,
               deadline_ms: Optional[float] = None,
               label: Optional[Any] = None,
               ctx: Optional[trace.TraceContext] = None) -> int:
        """Fire one request without waiting; returns its wire id
        (collect with :meth:`wait_for` or :meth:`collect_async`).
        ``deadline_ms`` (absolute unix-epoch milliseconds) rides the
        wire: the hive batcher drops the request unanswered once it
        expires instead of computing for an absent waiter.
        ``label`` (per-row ground truth) feeds an ``--online`` hive's
        learning tap.  ``ctx`` (a sampled Flightline span — usually
        the router's per-leg child) stamps the trace-propagation wire
        fields so the hive's spans join the caller's trace."""
        jid = self._draw_id()
        msg = {"id": jid, "model": model,
               "rows": np.asarray(rows, np.float32).tolist()}
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        if label is not None:
            msg["label"] = np.asarray(label).tolist()
        trace.to_wire(msg, ctx)
        self._send(msg)
        return jid

    def send_label(self, jid: int, label: Any) -> None:
        """Deliver late ground truth for an earlier request by wire
        id (fire-and-forget; an ``--online`` hive joins it into the
        replay buffer, anything else ignores it)."""
        self._send({"label_of": jid,
                    "label": np.asarray(label).tolist()})

    def learn(self, timeout: float = 60.0) -> Dict[str, Any]:
        """The online learner's per-model introspection rows
        (op=learn): {} when the hive is not learning."""
        jid = self._draw_id()
        self._send({"op": "learn", "id": jid})
        return self._wait(jid, timeout)["learn"]

    def learner_ctl(self, suspend: bool,
                    timeout: float = 60.0) -> Dict[str, Any]:
        """Suspend or resume the hive's online learner (the elastic
        fleet's first degradation rung — under pressure there are no
        idle gaps to scavenge).  Ack: ``{"suspended": bool,
        "online": bool}``; ``online`` False means no learner is armed
        and the op was a no-op."""
        jid = self._draw_id()
        self._send({"op": "learner_suspend" if suspend
                    else "learner_resume", "id": jid})
        return self._wait(jid, timeout)["learner_ctl"]

    def cancel(self, jid: int) -> bool:
        """Abandon interest in request ``jid`` — the timeout-cleanup /
        hedge-loser path.  Returns True when the response had already
        arrived (it is dropped now); False when it is still pending,
        in which case its eventual arrival is dropped and counted
        ``fleet.stale_response`` instead of leaking into another
        waiter."""
        with self._cond:
            if jid in self._results:
                self._results.pop(jid)
                return True
            self._callbacks.pop(jid, None)
            if not self._eof:
                self._cancelled.add(jid)
            return False

    def collect_async(self, jid: int,
                      callback: Callable[[Optional[Dict[str, Any]],
                                          Optional[BaseException]],
                                         None]) -> None:
        """Route request ``jid``'s response to ``callback(msg, err)``
        on the reader thread instead of a blocking waiter (exactly one
        of msg/err is set; err is :class:`ReplicaDied` when the
        replica dies first).  The callback must be quick and must not
        raise — the fleet's canary mirror records telemetry here."""
        ready = None
        with self._cond:
            if jid in self._results:
                ready = self._results.pop(jid)
            elif self._eof:
                ready = ReplicaDied(
                    f"hive pid {self.proc.pid} died before answering "
                    f"request {jid}", rc=self.exit_rc)
            else:
                self._callbacks[jid] = callback
        if isinstance(ready, ReplicaDied):
            self._run_callback(callback, None, ready)
        elif ready is not None:
            self._run_callback(callback, ready, None)

    def wait_for(self, jid: int,
                 timeout: float = 60.0) -> Dict[str, Any]:
        return self._wait(jid, timeout)

    def request(self, model: str, rows: Any,
                timeout: float = 60.0) -> Dict[str, Any]:
        """One round trip: returns the response dict ({"pred",
        "probs"} or {"error"})."""
        return self.wait_for(self.submit(model, rows), timeout)

    def stats(self, timeout: float = 60.0) -> Dict[str, Any]:
        """The serving process's live telemetry snapshot."""
        jid = self._draw_id()
        self._send({"op": "stats", "id": jid})
        return self._wait(jid, timeout)["stats"]

    def sigterm(self) -> None:
        self.proc.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self.proc.kill()

    def wait(self, timeout: float = 60.0) -> int:
        return self.proc.wait(timeout=timeout)

    def close(self, kill: bool = False) -> None:
        if self.proc.poll() is None:
            try:
                if not kill:
                    self._send({"op": "shutdown"})
                    self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001 — cleanup must not raise
                pass
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def __enter__(self) -> "HiveClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
