"""Line-protocol client for the Hive serving process.

Spawns (or attaches to) a ``--serve-models`` subprocess and multiplexes
CONCURRENT callers over its stdin/stdout: every request draws a wire
id under a lock, a single reader thread routes response lines back to
per-id waiters, and heartbeats/garbage are tolerated as proof of life
(the ChipEvaluatorPool discipline).  This is the surface the serving
tests, bench.py's ``serve_*`` phases, and operator smoke probes share.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class HiveClient:
    """Own one serving subprocess; thread-safe request fan-in."""

    def __init__(self, models: Dict[str, str],
                 backend: str = "cpu",
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 hbm_budget: Optional[int] = None,
                 heartbeat_every: Optional[float] = None,
                 metrics_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 start_timeout: float = 120.0) -> None:
        cmd = [sys.executable, "-m", "veles_tpu", "--serve-models"]
        cmd += [f"{name}={path}" for name, path in models.items()]
        cmd += ["-b", backend]
        if max_batch is not None:
            cmd += ["--max-batch", str(max_batch)]
        if max_wait_ms is not None:
            cmd += ["--max-wait-ms", str(max_wait_ms)]
        if hbm_budget is not None:
            cmd += ["--hbm-budget", str(hbm_budget)]
        if heartbeat_every is not None:
            cmd += ["--heartbeat-every", str(heartbeat_every)]
        if metrics_dir is not None:
            cmd += ["--metrics-dir", metrics_dir]
        run_env = dict(os.environ)
        run_env.setdefault("JAX_PLATFORMS", "cpu")
        if env:
            run_env.update(env)
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1, env=run_env, cwd=cwd)
        self._wlock = threading.Lock()
        self._cond = threading.Condition()
        self._results: Dict[int, Dict[str, Any]] = {}
        self._next_id = 0
        self._eof = False
        self.hello: Optional[Dict[str, Any]] = None
        self.heartbeats = 0
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name="hive-client-reader")
        self._reader.start()
        deadline = time.monotonic() + start_timeout
        with self._cond:
            while self.hello is None and not self._eof:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(min(left, 0.5))
        if self.hello is None:
            rc = self.proc.poll()
            self.close(kill=True)
            raise RuntimeError(
                f"hive did not come up (rc={rc})")

    # -- wire ----------------------------------------------------------

    def _read_loop(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue   # non-protocol noise is proof of life
            with self._cond:
                if msg.get("ready"):
                    self.hello = msg
                elif "hb" in msg:
                    self.heartbeats += 1
                elif msg.get("id") is not None:
                    self._results[msg["id"]] = msg
                self._cond.notify_all()
        with self._cond:
            self._eof = True
            self._cond.notify_all()

    def _send(self, obj: Dict[str, Any]) -> None:
        with self._wlock:
            self.proc.stdin.write(json.dumps(obj) + "\n")
            self.proc.stdin.flush()

    def _draw_id(self) -> int:
        with self._wlock:
            self._next_id += 1
            return self._next_id

    def _wait(self, jid: int, timeout: float) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        with self._cond:
            while jid not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"no response for request {jid} in {timeout}s")
                if self._eof and jid not in self._results:
                    raise RuntimeError(
                        "hive closed the pipe before answering "
                        f"request {jid}")
                self._cond.wait(min(left, 0.5))
            return self._results.pop(jid)

    # -- API -----------------------------------------------------------

    def submit(self, model: str, rows: Any) -> int:
        """Fire one request without waiting; returns its wire id
        (collect with :meth:`wait_for`).  The SIGTERM-drain test and
        the sustained-QPS bench issue bursts through this."""
        jid = self._draw_id()
        self._send({"id": jid, "model": model,
                    "rows": np.asarray(rows, np.float32).tolist()})
        return jid

    def wait_for(self, jid: int,
                 timeout: float = 60.0) -> Dict[str, Any]:
        return self._wait(jid, timeout)

    def request(self, model: str, rows: Any,
                timeout: float = 60.0) -> Dict[str, Any]:
        """One round trip: returns the response dict ({"pred",
        "probs"} or {"error"})."""
        return self.wait_for(self.submit(model, rows), timeout)

    def stats(self, timeout: float = 60.0) -> Dict[str, Any]:
        """The serving process's live telemetry snapshot."""
        jid = self._draw_id()
        self._send({"op": "stats", "id": jid})
        return self._wait(jid, timeout)["stats"]

    def sigterm(self) -> None:
        self.proc.send_signal(signal.SIGTERM)

    def wait(self, timeout: float = 60.0) -> int:
        return self.proc.wait(timeout=timeout)

    def close(self, kill: bool = False) -> None:
        if self.proc.poll() is None:
            try:
                if not kill:
                    self._send({"op": "shutdown"})
                    self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001 — cleanup must not raise
                pass
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def __enter__(self) -> "HiveClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
