"""Multi-model HBM residency: budget accounting + LRU spill-to-host.

Serving keeps every model's stacked f32 member params resident so a
request costs one dispatch and zero uploads — but HBM is finite and
the fleet of models is not.  This manager extends the residency-budget
idea of the uint8 ingest work (loader/quantize.py shrank DATASET
residency 4x against ``$VELES_MAX_RESIDENT_BYTES``) to MODELS: each
model's device cost is known exactly before upload
(``batching.stacked_param_bytes``), the budget is the device's
reported ``bytes_limit`` or ``$VELES_SERVE_HBM_BUDGET``, and when
admitting a model would overflow it, the least-recently-USED resident
model spills — its engine keeps the compiled dispatchers and only
drops the stacked params (the manager holds the immutable host
copies), so a later restore pays one H2D upload, NOT a recompile.

Every transition journals (``serve.model_loaded`` /
``serve.model_spilled`` / ``serve.model_restored`` /
``serve.model_sharded_resident``) and the ``serve.models_resident`` /
``serve.resident_bytes`` / ``serve.resident_bytes_per_device`` gauges
track the live set.

On a mesh replica (``--serve-models --mesh N``, the Prism arm) the
budget stays PER DEVICE and a model's charge depends on its placement:
replicated params cost ``param_bytes`` on every device, while a
member-sharded model (``$VELES_SERVE_MESH_SHARD``) costs
``padded/N`` per device — so a model over ONE device's budget but
under ``total/N`` goes member-sharded-RESIDENT instead of LRU
spilling, and capacity scales with the mesh instead of replicating it
(the Lattice move applied to serving).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from veles_tpu import events, knobs, telemetry
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger

#: the arbiter's ledger pools — every byte resident on the device is
#: charged to exactly one: served model stacks (``serve``), training /
#: online-shadow state (``train``), GA cohort stacks (``cohort``), and
#: everything else (replay buffers, probes: ``scratch``)
POOLS = ("serve", "train", "cohort", "scratch")


class HostedModel:
    """One servable model: the pure forward chain, the immutable host
    member params, and (when resident) the vmapped engine."""

    def __init__(self, name: str, forwards: List[Any],
                 member_params: List[Dict[str, Dict[str, Any]]],
                 meta: Optional[Dict[str, Any]] = None,
                 sample_shape=None) -> None:
        from veles_tpu.ops import batching
        self.name = name
        self.forwards = list(forwards)
        self.member_params = member_params
        self.meta = dict(meta or {})
        #: per-sample input shape (from the template loader) — lets
        #: the batcher bounce mis-shaped requests at submit time
        self.sample_shape = tuple(sample_shape) if sample_shape \
            else None
        self.engine = None   # EnsembleEvalEngine once first admitted
        self.param_bytes = batching.stacked_param_bytes(member_params)
        self.last_used = 0.0

    @property
    def resident(self) -> bool:
        return self.engine is not None and self.engine.resident


class ResidencyManager(Logger):
    """Admit models under the HBM budget; spill the LRU one over it."""

    def __init__(self, device: Any,
                 budget_bytes: Optional[int] = None,
                 max_batch: int = 64,
                 max_wait_s: float = 0.005) -> None:
        self.device = device
        self.budget_bytes = int(budget_bytes) if budget_bytes \
            else self._device_budget(device)
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.models: Dict[str, HostedModel] = {}
        #: serializes registry state, spill victim selection, and the
        #: online promotion's param swap (PR 14): the swap and the
        #: spill decision racing unlocked is exactly how a dispatch
        #: could lose its params mid-flight.  Blocking work (engine
        #: drain, compile, H2D upload) stays OUTSIDE this lock.
        self._lock = witness.lock("residency.state")
        #: side charges against the budget that are not stacked model
        #: params — name -> (bytes, pool): the online tier's shadow
        #: params + replay buffers, and (PR 18) every ExecutionCore's
        #: footprint, so training, GA cohorts, and serving draw on ONE
        #: ledger instead of per-subsystem budget fictions
        self.reserved: Dict[str, Tuple[int, str]] = {}
        #: devices the replica's device owns (1 off-mesh): budgets are
        #: per device, so a member-sharded model charges padded/N here
        self.n_devices = int(getattr(device, "n_devices", 1))

    @staticmethod
    def _device_budget(device: Any) -> int:
        """The device's reported HBM limit, else the declared knob
        default — the same accounting the GA cohort sizing uses.
        PER DEVICE by construction: on a mesh device the probed
        ``jax_device`` is one chip, and a replicated model costs its
        full ``param_bytes`` on EVERY chip, so charging one device's
        budget against one copy's bytes stays honest (the Lattice
        convention — capacity multiplies only for SHARDED placements,
        and served model params replicate)."""
        unified = int(knobs.get(knobs.HBM_BUDGET))
        if unified:
            # the set-wins unified arbiter budget: one number for
            # training, GA cohorts, and serving alike
            return unified
        jdev = getattr(device, "jax_device", None)
        if jdev is not None:
            try:
                limit = int((jdev.memory_stats() or {})
                            .get("bytes_limit", 0))
                if limit:
                    # half held back for activations + micro-batches
                    return limit // 2
            except Exception:  # noqa: BLE001 — CPU backends report none
                pass
        return int(knobs.get(knobs.SERVE_HBM_BUDGET))

    # -- registry ------------------------------------------------------

    def register(self, model: HostedModel) -> None:
        with self._lock:
            if model.name in self.models:
                raise ValueError(
                    f"duplicate model name {model.name!r}")
            self.models[model.name] = model

    def reserve(self, name: str, nbytes: int,
                pool: str = "scratch") -> None:
        """Charge (or re-charge) a named side allocation against the
        budget, tagged with its ledger ``pool`` — the online tier's
        shadow params and replay-buffer bytes stack on the model
        residency cost exactly like the uint8 ingest charge stacks on
        the dataset budget; since PR 18 every ExecutionCore charges
        its params/opt footprint here too, making this THE process
        HBM ledger."""
        if pool not in POOLS:
            raise ValueError(f"unknown arbiter pool {pool!r} "
                             f"(declared: {POOLS})")
        with self._lock:
            self.reserved[name] = (int(nbytes), pool)
        self._update_gauges()

    def release(self, name: str) -> None:
        """Drop a named charge (engine/core release path); unknown
        names are a no-op — a release must never fail a teardown."""
        with self._lock:
            self.reserved.pop(name, None)
        self._update_gauges()

    def ledger(self) -> Dict[str, int]:
        """Per-pool resident bytes — the arbiter's public read: the
        ``serve`` pool carries the resident stacked models plus any
        serve-tagged reserves; the other pools are pure reserve
        sums.  Rendered by /api/metrics and the obs fleet view so an
        over-budget reserve is visible BEFORE it OOMs."""
        with self._lock:
            reserved = list(self.reserved.values())
            model_bytes = sum(self._charge(m)
                              for m in self.models.values()
                              if m.resident)
        out = {pool: 0 for pool in POOLS}
        out["serve"] = model_bytes
        for nbytes, pool in reserved:
            out[pool] += nbytes
        return out

    # -- placement / charging ------------------------------------------

    def _shard_members(self, m: HostedModel) -> bool:
        """The Prism placement decision for ``m``'s stacked member
        axis, mirroring ``$VELES_MESH_SHARD_DATA``'s surface: `always`
        shards every model on a mesh replica, `never` keeps the
        replicated placement, and `auto` shards exactly the models
        that overflow ONE device's budget — the case that was an LRU
        spill (or a loud over-budget admit) before the mesh existed.
        An already-built engine's placement is fixed (restore lands on
        the same sharding, so dispatchers never retrace)."""
        live = getattr(m.engine, "member_sharded", None)
        if live is not None:
            return bool(live)
        mesh = getattr(self.device, "mesh", None)
        if mesh is None or self.n_devices < 2:
            return False
        from veles_tpu.parallel.mesh import shard_mode
        mode = shard_mode(knobs.get(knobs.SERVE_MESH_SHARD))
        if mode == "never":
            return False
        if mode == "always":
            return True
        return m.param_bytes > self.budget_bytes

    def _charge(self, m: HostedModel) -> int:
        """``m``'s residency cost PER DEVICE under its (decided or
        live) placement: the full stack when replicated, padded/N
        when member-sharded."""
        live = getattr(m.engine, "param_bytes_per_device", None)
        if live is not None:
            return int(live)
        if self._shard_members(m):
            p = len(m.member_params)
            p_pad = -(-p // self.n_devices) * self.n_devices
            return (m.param_bytes // p * p_pad) // self.n_devices
        return m.param_bytes

    def resident_bytes(self) -> int:
        # PER-DEVICE charge (identical to the total off-mesh).
        # snapshot the dicts first: gauges read this from the main
        # loop while the scavenger re-charges its buffer reservation
        return sum(self._charge(m) for m in list(self.models.values())
                   if m.resident) + sum(
            v[0] for v in tuple(self.reserved.values()))

    def resident_count(self) -> int:
        return sum(1 for m in list(self.models.values())
                   if m.resident)

    def _update_gauges(self) -> None:
        led = self.ledger()
        telemetry.gauge(events.GAUGE_ARBITER_BUDGET_BYTES).set(
            self.budget_bytes)
        telemetry.gauge(events.GAUGE_ARBITER_RESIDENT_BYTES).set(
            sum(led.values()))
        for pool, nbytes in led.items():
            telemetry.gauge(
                f"arbiter.pool.{pool}.resident_bytes").set(nbytes)
        if not self.models:
            # a training-only process arbiter: publishing serve.*
            # gauges from it would pollute the obs fleet rows with a
            # phantom zero-model replica
            return
        telemetry.gauge(events.GAUGE_SERVE_MODELS_RESIDENT).set(
            self.resident_count())
        telemetry.gauge(events.GAUGE_SERVE_RESIDENT_BYTES).set(
            self.resident_bytes())
        telemetry.gauge(
            events.GAUGE_SERVE_RESIDENT_BYTES_PER_DEVICE).set(
            self.resident_bytes())
        telemetry.gauge(events.GAUGE_SERVE_MESH_DEVICES).set(
            self.n_devices)

    # -- admission -----------------------------------------------------

    def ensure(self, name: str):
        """The serving entry point: return ``name``'s ready engine,
        admitting (or restoring) it under the budget first.  Raises
        KeyError for an unregistered name.

        Victim SELECTION happens under the residency lock (so it can
        never race a promotion swap), but the spill itself — which
        drains the victim's in-flight dispatches — and the engine
        build/restore — compile + H2D — run outside it: blocking work
        under the registry lock is the batcher-stall class Lockstep
        exists to forbid."""
        wait_deadline = None
        while True:
            with self._lock:
                m = self.models[name]
                m.last_used = time.monotonic()
                if m.resident:
                    return m.engine
                victim, blocked = self._pick_victim(m)
            if victim is not None:
                self._spill(victim)
                continue
            if not blocked:
                break
            # over budget but every candidate is mid-flight: WAIT for
            # one to go quiet rather than admit over budget — the
            # busy window is normally microseconds (a request that
            # just resolved), and the budget invariant the LRU test
            # pins must survive it.  Under genuinely continuous
            # traffic the wait caps out and we admit over budget
            # (with the loud warning) instead of starving.
            now = time.monotonic()
            if wait_deadline is None:
                wait_deadline = now + 2.0
            if now >= wait_deadline:
                break
            time.sleep(0.002)
        if m.engine is None:
            from veles_tpu.ops.fused import EnsembleEvalEngine
            t0 = time.perf_counter()
            shard = self._shard_members(m)
            engine = EnsembleEvalEngine(m.forwards, m.member_params,
                                        self.device,
                                        shard_members=shard)
            engine.attach_batcher(self.max_batch, self.max_wait_s,
                                  label=name,
                                  sample_shape=m.sample_shape)
            with self._lock:
                if m.engine is None:
                    m.engine = engine
            telemetry.event(events.EV_SERVE_MODEL_LOADED, model=name,
                            members=m.engine.n_members,
                            param_bytes=m.param_bytes,
                            seconds=round(time.perf_counter() - t0, 4))
            if m.engine.member_sharded:
                telemetry.event(
                    events.EV_SERVE_MODEL_SHARDED, model=name,
                    devices=self.n_devices,
                    param_bytes=m.param_bytes,
                    per_device=m.engine.param_bytes_per_device)
                self.info(
                    "model %r member-sharded over %d devices: %.2f "
                    "MiB total, %.2f MiB/device — resident where a "
                    "single device would spill", name, self.n_devices,
                    m.param_bytes / (1 << 20),
                    m.engine.param_bytes_per_device / (1 << 20))
            self.info("model %r loaded: %d members, %.2f MiB stacked",
                      name, m.engine.n_members,
                      m.param_bytes / (1 << 20))
        elif not m.resident:
            t0 = time.perf_counter()
            m.engine.restore_params(m.member_params)
            telemetry.event(events.EV_SERVE_MODEL_RESTORED, model=name,
                            param_bytes=m.param_bytes,
                            seconds=round(time.perf_counter() - t0, 4))
            self.info("model %r restored from host spill (%.2f MiB)",
                      name, m.param_bytes / (1 << 20))
        self._update_gauges()
        return m.engine

    def _pick_victim(self, incoming: HostedModel) \
            -> tuple:
        """Called under the lock: ``(victim, blocked)`` — the least-
        recently-used resident model to spill for ``incoming`` (None
        when it already fits, or when nothing is safely evictable;
        ``blocked`` is True in the latter over-budget case).  BUSY
        engines — rows queued or a dispatch in flight — are never
        victims: spilling one would pull the stacked params out from
        under its flush thread mid-request (the PR 14 promotion/LRU
        race, pinned by tests/test_online.py).  A model that alone
        exceeds the budget is admitted anyway (with a loud warning) —
        refusing it would make the budget knob a denial-of-service on
        itself.  On a mesh replica the charge is taken AFTER the
        placement decision: a member-sharded model needs padded/N per
        device, so the over-one-device's-budget case stops being a
        spill (or a warning) and becomes a resident placement."""
        need = self._charge(incoming)
        if need > self.budget_bytes:
            self.warning(
                "model %r needs %d bytes/device, over the residency "
                "budget (%d) — admitting alone; consider raising "
                "$VELES_SERVE_HBM_BUDGET%s", incoming.name, need,
                self.budget_bytes,
                "" if self.n_devices > 1 else
                " or serving on a mesh (--mesh N)")
        if self.resident_bytes() + need <= self.budget_bytes:
            return None, False
        candidates = [m for m in self.models.values()
                      if m.resident and m is not incoming]
        victims = [m for m in candidates if not m.engine.busy]
        if not victims:
            return None, bool(candidates)
        return min(victims, key=lambda m: m.last_used), False

    def swap_params(self, name: str, stacked_params: Any):
        """The online promotion's atomic dispatcher swap: hand an
        already-device-resident stacked param pytree to ``name``'s
        serving engine, under the SAME lock spill decisions take — a
        concurrent ensure() either sees the model resident (and
        leaves it alone) or picks its victim after the swap landed.
        Returns the engine.  Raises RuntimeError when the model is
        not resident (a spilled model has nothing to swap into; the
        gate retries after the next restore)."""
        with self._lock:
            m = self.models[name]
            if m.engine is None or not m.resident:
                raise RuntimeError(
                    f"model {name!r} is not resident; cannot swap "
                    f"promoted params into a spilled engine")
            m.engine.adopt_stacked_params(stacked_params)
            m.last_used = time.monotonic()
            return m.engine

    def refresh_host_params(self, name: str,
                            member_params: List[Dict[str, Dict[
                                str, Any]]]) -> None:
        """Adopt new host member copies after a promotion (the
        spill/restore source of truth) — called OFF the swap path."""
        with self._lock:
            m = self.models[name]
            m.member_params = member_params

    def _spill(self, m: HostedModel) -> None:
        # outstanding requests first: the engine's queued micro-batches
        # must dispatch while the params are still on device
        m.engine.drain()
        m.engine.spill_params()
        telemetry.counter(events.CTR_SERVE_SPILLS).inc()
        telemetry.event(events.EV_SERVE_MODEL_SPILLED, model=m.name,
                        param_bytes=m.param_bytes)
        self.info("model %r spilled to host (LRU, freeing %.2f MiB)",
                  m.name, m.param_bytes / (1 << 20))
        self._update_gauges()

    def drain_all(self, timeout: float = 30.0) -> bool:
        """Drain every model's batcher (the SIGTERM path)."""
        ok = True
        for m in self.models.values():
            if m.engine is not None:
                ok = m.engine.drain(timeout) and ok
        return ok

    def close(self) -> None:
        for m in self.models.values():
            if m.engine is not None:
                m.engine.release()
                m.engine = None


# -- the process-wide arbiter ------------------------------------------
# ONE ResidencyManager per process is THE HBM arbiter: a hive installs
# its (model-hosting) manager at startup, while a training/GA process
# lazily gets a model-less one the first time an ExecutionCore charges
# its footprint.  Either way the ledger() pools and the arbiter.*
# gauges read the same single source of truth.

_process_arbiter: Optional[ResidencyManager] = None
_arbiter_lock = witness.lock("residency.arbiter")


def install_process_arbiter(manager: ResidencyManager) \
        -> ResidencyManager:
    """Make ``manager`` THE process arbiter (the hive calls this with
    its model-hosting manager before serving starts, so training
    charges land on the ledger the LRU spill reads)."""
    global _process_arbiter
    with _arbiter_lock:
        _process_arbiter = manager
    return manager


def process_arbiter(device: Any = None) -> ResidencyManager:
    """The process-wide HBM arbiter, created on first use when no
    hive installed one — a model-less manager whose reserve ledger
    still budgets and gauges every ExecutionCore's footprint."""
    global _process_arbiter
    with _arbiter_lock:
        if _process_arbiter is None:
            _process_arbiter = ResidencyManager(device)
        return _process_arbiter
