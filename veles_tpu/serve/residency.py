"""Multi-model HBM residency: budget accounting + LRU spill-to-host.

Serving keeps every model's stacked f32 member params resident so a
request costs one dispatch and zero uploads — but HBM is finite and
the fleet of models is not.  This manager extends the residency-budget
idea of the uint8 ingest work (loader/quantize.py shrank DATASET
residency 4x against ``$VELES_MAX_RESIDENT_BYTES``) to MODELS: each
model's device cost is known exactly before upload
(``batching.stacked_param_bytes``), the budget is the device's
reported ``bytes_limit`` or ``$VELES_SERVE_HBM_BUDGET``, and when
admitting a model would overflow it, the least-recently-USED resident
model spills — its engine keeps the compiled dispatchers and only
drops the stacked params (the manager holds the immutable host
copies), so a later restore pays one H2D upload, NOT a recompile.

Every transition journals (``serve.model_loaded`` /
``serve.model_spilled`` / ``serve.model_restored``) and the
``serve.models_resident`` / ``serve.resident_bytes`` gauges track the
live set.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from veles_tpu import events, knobs, telemetry
from veles_tpu.logger import Logger


class HostedModel:
    """One servable model: the pure forward chain, the immutable host
    member params, and (when resident) the vmapped engine."""

    def __init__(self, name: str, forwards: List[Any],
                 member_params: List[Dict[str, Dict[str, Any]]],
                 meta: Optional[Dict[str, Any]] = None,
                 sample_shape=None) -> None:
        from veles_tpu.ops import batching
        self.name = name
        self.forwards = list(forwards)
        self.member_params = member_params
        self.meta = dict(meta or {})
        #: per-sample input shape (from the template loader) — lets
        #: the batcher bounce mis-shaped requests at submit time
        self.sample_shape = tuple(sample_shape) if sample_shape \
            else None
        self.engine = None   # EnsembleEvalEngine once first admitted
        self.param_bytes = batching.stacked_param_bytes(member_params)
        self.last_used = 0.0

    @property
    def resident(self) -> bool:
        return self.engine is not None and self.engine.resident


class ResidencyManager(Logger):
    """Admit models under the HBM budget; spill the LRU one over it."""

    def __init__(self, device: Any,
                 budget_bytes: Optional[int] = None,
                 max_batch: int = 64,
                 max_wait_s: float = 0.005) -> None:
        self.device = device
        self.budget_bytes = int(budget_bytes) if budget_bytes \
            else self._device_budget(device)
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.models: Dict[str, HostedModel] = {}

    @staticmethod
    def _device_budget(device: Any) -> int:
        """The device's reported HBM limit, else the declared knob
        default — the same accounting the GA cohort sizing uses."""
        jdev = getattr(device, "jax_device", None)
        if jdev is not None:
            try:
                limit = int((jdev.memory_stats() or {})
                            .get("bytes_limit", 0))
                if limit:
                    # half held back for activations + micro-batches
                    return limit // 2
            except Exception:  # noqa: BLE001 — CPU backends report none
                pass
        return int(knobs.get(knobs.SERVE_HBM_BUDGET))

    # -- registry ------------------------------------------------------

    def register(self, model: HostedModel) -> None:
        if model.name in self.models:
            raise ValueError(f"duplicate model name {model.name!r}")
        self.models[model.name] = model

    def resident_bytes(self) -> int:
        return sum(m.param_bytes for m in self.models.values()
                   if m.resident)

    def resident_count(self) -> int:
        return sum(1 for m in self.models.values() if m.resident)

    def _update_gauges(self) -> None:
        telemetry.gauge(events.GAUGE_SERVE_MODELS_RESIDENT).set(
            self.resident_count())
        telemetry.gauge(events.GAUGE_SERVE_RESIDENT_BYTES).set(
            self.resident_bytes())

    # -- admission -----------------------------------------------------

    def ensure(self, name: str):
        """The serving entry point: return ``name``'s ready engine,
        admitting (or restoring) it under the budget first.  Raises
        KeyError for an unregistered name."""
        m = self.models[name]
        m.last_used = time.monotonic()
        if m.resident:
            return m.engine
        self._make_room(m)
        if m.engine is None:
            from veles_tpu.ops.fused import EnsembleEvalEngine
            t0 = time.perf_counter()
            m.engine = EnsembleEvalEngine(m.forwards, m.member_params,
                                          self.device)
            m.engine.attach_batcher(self.max_batch, self.max_wait_s,
                                    label=name,
                                    sample_shape=m.sample_shape)
            telemetry.event(events.EV_SERVE_MODEL_LOADED, model=name,
                            members=m.engine.n_members,
                            param_bytes=m.param_bytes,
                            seconds=round(time.perf_counter() - t0, 4))
            self.info("model %r loaded: %d members, %.2f MiB stacked",
                      name, m.engine.n_members,
                      m.param_bytes / (1 << 20))
        else:
            t0 = time.perf_counter()
            m.engine.restore_params(m.member_params)
            telemetry.event(events.EV_SERVE_MODEL_RESTORED, model=name,
                            param_bytes=m.param_bytes,
                            seconds=round(time.perf_counter() - t0, 4))
            self.info("model %r restored from host spill (%.2f MiB)",
                      name, m.param_bytes / (1 << 20))
        self._update_gauges()
        return m.engine

    def _make_room(self, incoming: HostedModel) -> None:
        """Spill least-recently-used resident models until ``incoming``
        fits the budget.  A model that alone exceeds the budget is
        admitted anyway (with a loud warning) — refusing it would make
        the budget knob a denial-of-service on itself."""
        need = incoming.param_bytes
        if need > self.budget_bytes:
            self.warning(
                "model %r needs %d bytes, over the whole residency "
                "budget (%d) — admitting alone; consider raising "
                "$VELES_SERVE_HBM_BUDGET", incoming.name, need,
                self.budget_bytes)
        while self.resident_bytes() + need > self.budget_bytes:
            victims = [m for m in self.models.values()
                       if m.resident and m is not incoming]
            if not victims:
                break
            lru = min(victims, key=lambda m: m.last_used)
            self._spill(lru)

    def _spill(self, m: HostedModel) -> None:
        # outstanding requests first: the engine's queued micro-batches
        # must dispatch while the params are still on device
        m.engine.drain()
        m.engine.spill_params()
        telemetry.counter(events.CTR_SERVE_SPILLS).inc()
        telemetry.event(events.EV_SERVE_MODEL_SPILLED, model=m.name,
                        param_bytes=m.param_bytes)
        self.info("model %r spilled to host (LRU, freeing %.2f MiB)",
                  m.name, m.param_bytes / (1 << 20))
        self._update_gauges()

    def drain_all(self, timeout: float = 30.0) -> bool:
        """Drain every model's batcher (the SIGTERM path)."""
        ok = True
        for m in self.models.values():
            if m.engine is not None:
                ok = m.engine.drain(timeout) and ok
        return ok

    def close(self) -> None:
        for m in self.models.values():
            if m.engine is not None:
                m.engine.release()
                m.engine = None
