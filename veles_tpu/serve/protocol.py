"""The central registry of JSONL wire-protocol keys.

Every field name that crosses a serving-tier pipe — hive and fleet
hello lines, heartbeats, requests, responses, the stats/fleet
introspection ops — is declared HERE, the same move knobs.py made for
env vars and events.py for telemetry names.  PR 10-12 grew the wire
by hand (``deadline_ms``, ``rows_n``, ``crc``, ``expired``, ...), and
an ad-hoc key is the emitter/reader typo class: a misspelled field is
emitted forever and read never, and nothing fails until a drill
happens to cross it.  Veleslint's ``wire-protocol`` rule
(veles_tpu/analysis/concurrency.py) flags any undeclared string key
in a dict literal flowing to the wire in router/client/hive/batcher/
sentinel.

Declaration, not routing, is the contract (the knobs.py precedent):
call sites keep writing ``{"id": jid, ...}`` literals — the registry
exists so the checker can tell a field from a typo, and so THIS file
is the one place the protocol is enumerated for a reader.
"""

from __future__ import annotations

from typing import Set

WIRE_KEYS: Set[str] = set()


def _k(name: str) -> str:
    WIRE_KEYS.add(name)
    return name


# -- envelope (every message) ------------------------------------------

K_ID = _k("id")                  #: wire id drawn by the client
K_OP = _k("op")                  #: stats / fleet / shutdown
K_ERROR = _k("error")            #: error text (terminal per-request)

# -- requests ----------------------------------------------------------

K_MODEL = _k("model")            #: registered model name
K_ROWS = _k("rows")              #: f32 sample rows, nested lists
K_DEADLINE_MS = _k("deadline_ms")  #: absolute unix-epoch deadline

K_LABEL = _k("label")            #: optional ground-truth (per row or
#: one scalar for the whole request) — feeds the online-learning tap
K_LABEL_OF = _k("label_of")      #: late label join: the wire id of
#: the earlier request these labels belong to

# -- responses ---------------------------------------------------------

K_PRED = _k("pred")              #: argmax per row
K_PROBS = _k("probs")            #: f32 probability payload
K_ROWS_N = _k("rows_n")          #: integrity echo: payload row count
K_CRC = _k("crc")                #: integrity echo: crc32 of clean f32
K_EXPIRED = _k("expired")        #: dropped past deadline_ms, unanswered
K_OVERLOADED = _k("overloaded")  #: admission-control shed
K_EST_MS = _k("est_ms")          #: estimated completion behind a shed
K_TIMEOUT = _k("timeout")        #: router-side deadline exceeded

# -- hello lines -------------------------------------------------------

K_READY = _k("ready")
K_PID = _k("pid")
K_BACKEND = _k("backend")
K_PLATFORM = _k("platform")
K_MODELS = _k("models")
K_MAX_BATCH = _k("max_batch")
K_MAX_WAIT_MS = _k("max_wait_ms")
K_MEMBERS = _k("members")        #: per-model: ensemble member count
K_PARAM_BYTES = _k("param_bytes")
K_RESIDENT = _k("resident")
K_VERSION = _k("version")
K_SHARDED = _k("sharded")        #: per-model: member-sharded placement
# Prism hello extras: a replica advertises its real capacity so the
# placement policy can mix 1-device and N-device replicas
K_DEVICES = _k("devices")        #: devices the replica's mesh owns
K_DEVICE_BUDGET = _k("device_budget")  #: residency budget PER device
# fleet hello extras
K_FLEET = _k("fleet")            #: replica count (hello) / status (op)
K_REPLICA_PIDS = _k("replica_pids")
K_PLACEMENT = _k("placement")
K_CANARIES = _k("canaries")
K_OF = _k("of")                  #: canary: primary model name
K_FRACTION = _k("fraction")      #: canary: mirrored traffic fraction
K_SLO_P99_MS = _k("slo_p99_ms")
K_MAX_INFLIGHT = _k("max_inflight")

K_ONLINE = _k("online")          #: hello: the learning tier is armed

# -- trace context (Flightline) ----------------------------------------
# minted at the Swarm router, propagated on EVERY wire hop (requests,
# hedge copies, failover retries, GA cohort jobs); veleslint's
# trace-wire-key rule pins veles_tpu/trace.py's WIRE_FIELDS to this
# registry so a propagation key can never ship undeclared

K_TRACE = _k("trace")            #: 16-hex trace id (one request tree)
K_SPAN = _k("span")              #: 8-hex span id of the sending hop
K_PARENT = _k("parent")          #: span id of the causing hop
K_SAMPLED = _k("sampled")        #: head-based sampling bit

# -- heartbeats --------------------------------------------------------

K_HB = _k("hb")                  #: heartbeat sequence number

# -- introspection (op=stats / op=fleet) -------------------------------

K_STATS = _k("stats")
K_REPLICAS = _k("replicas")
K_REPLICA = _k("replica")
K_HEALTHY = _k("healthy")
K_INFLIGHT = _k("inflight")
K_ROUTED = _k("routed")
K_DEATHS = _k("deaths")
K_EMA_DISPATCH_MS = _k("ema_dispatch_ms")
K_DEADLINE_MS_CFG = _k("deadline_ms")  # shared with requests
K_HEDGE_RATE = _k("hedge_rate")
K_SENTINEL = _k("sentinel")
# the sentinel's per-replica health row (router op=fleet)
K_STATE = _k("state")
K_HEALTH_SCORE = _k("health_score")
K_STRIKES = _k("strikes")
K_HEDGE_WINS = _k("hedge_wins")
K_HEDGE_LOSSES = _k("hedge_losses")
K_PROBE_OK_STREAK = _k("probe_ok_streak")
K_PROBE_FAILS = _k("probe_fails")
K_EJECTIONS = _k("ejections")
K_REINSTATEMENTS = _k("reinstatements")
K_LATENCY_EMA_MS = _k("latency_ema_ms")
# the learner's per-model introspection row (hive op=learn) — buffer
# fill, scavenged steps, and the promotion gate's live standing
K_LEARN = _k("learn")            #: op=learn: {model: learner row}
K_BUFFER_ROWS = _k("buffer_rows")
K_HOLDOUT_ROWS = _k("holdout_rows")
K_BUFFER_BYTES = _k("buffer_bytes")
K_TAPPED_ROWS = _k("tapped_rows")
K_LABELED_ROWS = _k("labeled_rows")
K_STEPS = _k("steps")
K_PROMOTIONS = _k("promotions")
K_ROLLBACKS = _k("rollbacks")
K_SHADOW_ERROR_PCT = _k("shadow_error_pct")
K_INCUMBENT_ERROR_PCT = _k("incumbent_error_pct")
K_MARGIN = _k("margin")          #: promote margin the gate holds
K_TIME_TO_SERVE_MS = _k("time_to_serve_ms")

# -- Gauntlet: the elastic fleet + the degradation ladder --------------

K_LEARNER_CTL = _k("learner_ctl")  #: op=learner_suspend/resume ack
K_SUSPENDED = _k("suspended")    #: learner_ctl: the learner's state
K_DEGRADED = _k("degraded")      #: shed carried a ladder rung's mark
K_RETIRING = _k("retiring")      #: fleet row: replica is draining out
K_N_REPLICAS = _k("n_replicas")  #: fleet status: live member count
K_HEDGING_ENABLED = _k("hedging_enabled")  #: ladder rung 2 lever
K_SHED_TAIL = _k("shed_tail")    #: ladder rung 3 lever
K_WARM_DIRS = _k("warm_dirs")    #: retained install dirs for respawn

# -- Gauntlet: the traffic trace file (veles-traffic-v1 JSONL) ---------
# one header line {format, spec, n} then one line per arrival — the
# byte-identical replay contract shares the wire-key registry

K_I = _k("i")                    #: arrival index (dense, 0-based)
K_T = _k("t")                    #: scheduled offset secs from day start
K_ROW_SEED = _k("row_seed")      #: per-arrival input row generator seed
K_BURST = _k("burst")            #: arrival fell in a burst window
K_FORMAT = _k("format")          #: trace header: format tag
K_SPEC = _k("spec")              #: trace header: TrafficSpec dict
K_N = _k("n")                    #: trace header: arrival count


def known(key: str) -> bool:
    """Is ``key`` a declared wire-protocol field?"""
    return key in WIRE_KEYS


def all_keys() -> frozenset:
    return frozenset(WIRE_KEYS)
