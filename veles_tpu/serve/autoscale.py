"""Gauntlet's elastic fleet: the scale controller and the ladder.

Swarm provisions for peak: ``--serve-fleet N`` spawns N replicas and
keeps N until shutdown, so a 10x diurnal swing means 90% of the fleet
idles through the trough.  The router already measures exactly the
signals an autoscaler needs — per-replica queue depth and the
EMA-smoothed dispatch cadence behind ``estimated_total_ms()``, the
same estimate admission control sheds on.  This module feeds them
into three composable pieces:

- :class:`ScaleController` — a PURE hysteresis controller (no
  threads, no wall clock; the caller passes ``now``): scale **up**
  when the best candidate replica's estimated completion stays above
  ``$VELES_FLEET_SCALE_UP_MS`` for ``..._UP_SUSTAIN`` seconds; scale
  **down** when it stays below ``..._DOWN_MS`` for
  ``..._DOWN_SUSTAIN`` seconds; never below ``..._MIN`` or above
  ``..._MAX`` replicas; and at most one action per
  ``..._COOLDOWN`` seconds — the refractory period that keeps a
  flapping replica's respawn backoff (``fleet.replica_flap``) from
  compounding into a spawn storm.

- :class:`DegradationLadder` — for the window where demand outruns
  the ceiling: three rungs engaged strictly in order and released
  strictly in reverse — suspend the online learner (reclaim its idle
  gaps), disable hedging (stop amplifying load), shed long-tail
  models (explicit ``overloaded`` for the tail so the hot prefix
  holds its p99).  Pure LIFO state; the autoscaler journals every
  engage/release with its cause.

- :class:`FleetAutoscaler` — the daemon thread wiring both to a live
  :class:`~veles_tpu.serve.router.FleetRouter`: polls fleet pressure
  every ``$VELES_FLEET_SCALE_INTERVAL`` seconds, spawns via
  ``router.add_replica()`` (warm install dirs), retires via
  ``router.retire_replica()`` (drain + re-place THEN SIGTERM), and
  walks the ladder at the bounds.  Every action lands in the
  Sightline journal (``fleet.scale.up`` / ``.down`` /
  ``fleet.degrade.engage`` / ``.release``) with the pressure reading
  that caused it — the gauntlet's accountability check refuses any
  replica-count change these events do not explain.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from veles_tpu import events, knobs, telemetry
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger

#: controller verdicts (the autoscaler maps them onto router calls)
ACT_UP = "up"              # spawn a replica
ACT_DOWN = "down"          # retire a replica
ACT_SATURATED = "saturated"  # pressure at the max bound: engage a rung
ACT_RELAX = "relax"        # idle with rungs engaged: release a rung


class ScaleController:
    """Hysteresis + cooldown + bounds over a scalar pressure signal.

    ``observe(pressure_ms, n_replicas, now)`` returns one of
    :data:`ACT_UP` / :data:`ACT_DOWN` / :data:`ACT_SATURATED` /
    :data:`ACT_RELAX` / ``None``.  The caller owns the clock and the
    consequences; the controller owns WHEN — sustained-signal windows
    reset whenever the signal leaves the band, any verdict starts the
    cooldown, and the bounds turn up-at-max into ``saturated`` and
    down-at-min into ``relax`` (the ladder's levers) instead of
    silently clamping to ``None``.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_ms: float = 200.0, down_ms: float = 25.0,
                 up_sustain_s: float = 1.0, down_sustain_s: float = 3.0,
                 cooldown_s: float = 5.0) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if down_ms >= up_ms:
            raise ValueError(
                "hysteresis band inverted: down_ms must be < up_ms")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_ms = up_ms
        self.down_ms = down_ms
        self.up_sustain_s = up_sustain_s
        self.down_sustain_s = down_sustain_s
        self.cooldown_s = cooldown_s
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_at: Optional[float] = None

    def _in_cooldown(self, now: float) -> bool:
        return (self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s)

    def _acted(self, now: float) -> None:
        self._last_action_at = now
        self._above_since = None
        self._below_since = None

    def observe(self, pressure_ms: float, n_replicas: int,
                now: float) -> Optional[str]:
        if pressure_ms >= self.up_ms:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (now - self._above_since >= self.up_sustain_s
                    and not self._in_cooldown(now)):
                self._acted(now)
                return (ACT_UP if n_replicas < self.max_replicas
                        else ACT_SATURATED)
            return None
        if pressure_ms <= self.down_ms:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (now - self._below_since >= self.down_sustain_s
                    and not self._in_cooldown(now)):
                self._acted(now)
                return (ACT_DOWN if n_replicas > self.min_replicas
                        else ACT_RELAX)
            return None
        # inside the hysteresis band: both windows reset — a signal
        # that wanders in and out never accumulates sustain
        self._above_since = None
        self._below_since = None
        return None

    @classmethod
    def from_knobs(cls, environ=None) -> "ScaleController":
        g = lambda k: knobs.get(k, environ=environ)  # noqa: E731
        return cls(min_replicas=g(knobs.FLEET_SCALE_MIN),
                   max_replicas=g(knobs.FLEET_SCALE_MAX),
                   up_ms=g(knobs.FLEET_SCALE_UP_MS),
                   down_ms=g(knobs.FLEET_SCALE_DOWN_MS),
                   up_sustain_s=g(knobs.FLEET_SCALE_UP_SUSTAIN),
                   down_sustain_s=g(knobs.FLEET_SCALE_DOWN_SUSTAIN),
                   cooldown_s=g(knobs.FLEET_SCALE_COOLDOWN))


#: the ladder's rungs, in ENGAGE order; release is strictly reversed.
#: Ordered by cost-of-degradation: the learner only consumes idle
#: gaps that no longer exist; hedging spends duplicate capacity to
#: shave tail latency the fleet can no longer afford; shedding the
#: long tail is the first rung a user can SEE — and the hot prefix
#: is the last thing standing.
RUNGS = ("learner", "hedge", "shed_tail")


class DegradationLadder:
    """Strict-LIFO rung state.  ``engage()`` returns the next rung to
    pull (None when exhausted); ``release()`` returns the most recent
    rung to restore (None when fully recovered).  The ladder holds no
    levers itself — callers map rung names onto subsystem switches —
    so the ordering invariant is testable without a fleet."""

    def __init__(self) -> None:
        self.engaged: List[str] = []

    @property
    def depth(self) -> int:
        return len(self.engaged)

    def engage(self) -> Optional[str]:
        if len(self.engaged) >= len(RUNGS):
            return None
        rung = RUNGS[len(self.engaged)]
        self.engaged.append(rung)
        return rung

    def release(self) -> Optional[str]:
        if not self.engaged:
            return None
        return self.engaged.pop()


class FleetAutoscaler(Logger):
    """The daemon thread that makes the fleet track the load curve."""

    def __init__(self, router, controller: Optional[ScaleController] = None,
                 interval_s: Optional[float] = None) -> None:
        self.router = router
        self.controller = controller or ScaleController.from_knobs()
        self.ladder = DegradationLadder()
        self.interval_s = (knobs.get(knobs.FLEET_SCALE_INTERVAL)
                           if interval_s is None else interval_s)
        self._lock = witness.lock("autoscale.state")
        #: every action taken, for the accountability check:
        #: {"t", "action", "rung"|"replica", "pressure_ms", "n"}
        self.journal: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-autoscaler")

    def start(self) -> "FleetAutoscaler":
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)

    # -- signals -------------------------------------------------------

    def pressure_ms(self) -> float:
        """The BEST candidate's estimated completion — the admission
        estimate: if even the least-loaded eligible replica is slow,
        more capacity helps; one hot replica among idle peers does
        not call for a spawn (that is Sentinel's problem)."""
        vals = [r.estimated_total_ms()
                for r in list(self.router.replicas)
                if r.healthy and not r.retiring
                and self.router.sentinel.eligible(r)]
        if not vals:
            # no routable replica at all: maximal pressure (the fleet
            # monitor is respawning; the controller's sustain window
            # still gates the reaction)
            return float("inf")
        return min(vals)

    def n_live(self) -> int:
        return sum(1 for r in list(self.router.replicas)
                   if not r.retiring)

    # -- the loop ------------------------------------------------------

    def _journal(self, action: str, pressure: float,
                 **extra: Any) -> None:
        rec = {"t": time.time(), "action": action,
               "pressure_ms": pressure, "n": self.n_live()}
        rec.update(extra)
        with self._lock:
            self.journal.append(rec)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — scaling must not die
                self.exception("autoscaler tick failed")

    def _tick(self) -> None:
        pressure = self.pressure_ms()
        n = self.n_live()
        telemetry.gauge(events.GAUGE_FLEET_SCALE_PRESSURE_MS).set(
            0.0 if pressure == float("inf") else pressure)
        telemetry.gauge(events.GAUGE_FLEET_REPLICAS_TOTAL).set(n)
        telemetry.gauge(events.GAUGE_FLEET_DEGRADE_RUNGS).set(
            self.ladder.depth)
        act = self.controller.observe(pressure, n, time.monotonic())
        if act is None:
            return
        p = 0.0 if pressure == float("inf") else round(pressure, 3)
        if act == ACT_UP:
            # any engaged rungs STAY engaged across a scale-up: they
            # release only once pressure actually relieves
            self.info("scale-up: pressure %.1fms >= %.1fms at n=%d",
                      p, self.controller.up_ms, n)
            r = self.router.add_replica(cause="pressure",
                                        pressure_ms=p)
            self._journal(ACT_UP, p, replica=r.idx if r else None)
        elif act == ACT_SATURATED:
            rung = self.ladder.engage()
            if rung is None:
                return  # fully degraded; nothing left to pull
            self.info("degrade ENGAGE %s: pressure %.1fms at the "
                      "max bound n=%d", rung, p, n)
            self.router.apply_degradation(rung, True, cause="pressure",
                                          pressure_ms=p)
            self._journal("degrade_engage", p, rung=rung)
        elif act == ACT_DOWN:
            if self.ladder.depth:
                # recover service levels BEFORE shrinking: the rungs
                # were the emergency, spare capacity pays them back
                # first — strict reverse engage order
                rung = self.ladder.release()
                self.info("degrade RELEASE %s: pressure %.1fms", rung,
                          p)
                self.router.apply_degradation(rung, False,
                                              cause="recovered",
                                              pressure_ms=p)
                self._journal("degrade_release", p, rung=rung)
                return
            self.info("scale-down: pressure %.1fms <= %.1fms at n=%d",
                      p, self.controller.down_ms, n)
            idx = self.router.retire_replica(cause="idle",
                                             pressure_ms=p)
            self._journal(ACT_DOWN, p, replica=idx)
        elif act == ACT_RELAX:
            rung = self.ladder.release()
            if rung is None:
                return
            self.info("degrade RELEASE %s: pressure %.1fms", rung, p)
            self.router.apply_degradation(rung, False,
                                          cause="recovered",
                                          pressure_ms=p)
            self._journal("degrade_release", p, rung=rung)
