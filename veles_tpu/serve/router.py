"""Swarm: the fleet router — N Hive replicas behind one front end.

``python -m veles_tpu --serve-fleet N NAME=PKG.vpkg [NAME=PKG ...]``

PR 10's Hive is ONE chip-owning process, so serving throughput is
capped by one process no matter how many cores/chips the box has.
:class:`FleetRouter` owns N Hive replicas
(:mod:`veles_tpu.serve.fleet` spawns and supervises them) and fans
concurrent requests out across them:

- **placement-aware routing**: a :class:`~veles_tpu.serve.fleet.
  PlacementPolicy` replicates hot models on every replica and
  partitions the long tail; a request goes to the LEAST-LOADED healthy
  replica holding the model (router-side in-flight queue depth per
  replica), falling back to any healthy replica (which LRU-loads the
  model under its own residency budget);
- **failover**: a replica death (reader EOF or heartbeat deadline)
  fails its in-flight requests with ``ReplicaDied`` *immediately*;
  the router retries each exactly once on a healthy peer (inference
  is idempotent) while the fleet monitor respawns the replica with a
  warm install dir — pending waiters NEVER hang;
- **admission control**: a bounded per-replica router queue plus an
  SLO target (``$VELES_FLEET_SLO_P99_MS``): when the estimated
  completion (queue depth x observed per-dispatch time + batching
  window) would blow the target on even the least-loaded candidate,
  the request is shed with an explicit ``overloaded`` response
  instead of letting p99 run away;
- **canary / shadow**: a model registered as ``canary-of:NAME``
  receives a sampled fraction of NAME's traffic as asynchronous
  mirrors; per-model QPS/latency/error telemetry is split
  (``fleet.model.<name>.*``) so the A/B reads directly from
  ``obs_report --fleet``.

The CLI front end speaks the same JSONL protocol as a single hive
(hello line, heartbeats, ``{"id", "model", "rows"}`` in /
``{"id", "pred", "probs"}`` out), so every Hive client — including
another router — can point at a fleet unchanged.  Shed responses are
``{"id", "error": "overloaded", "overloaded": true}``.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, knobs, telemetry
from veles_tpu.logger import Logger
from veles_tpu.serve.client import ReplicaDied
from veles_tpu.serve.fleet import PlacementPolicy, Replica, ReplicaSet
from veles_tpu.supervisor import EXIT_PREEMPTED


class FleetRouter(Logger):
    """Own N Hive replicas; route, shed, mirror, and fail over."""

    def __init__(self, models: Dict[str, str], n_replicas: int,
                 backend: str = "cpu",
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 hbm_budget: Optional[int] = None,
                 heartbeat_every: Optional[float] = None,
                 metrics_dir: Optional[str] = None,
                 cwd: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 canaries: Optional[Dict[str,
                                         Tuple[str,
                                               Optional[float]]]] = None,
                 placement: Optional[PlacementPolicy] = None,
                 slo_p99_ms: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 heartbeat_deadline: Optional[float] = None,
                 respawn_backoff: Optional[float] = None,
                 start_timeout: float = 300.0) -> None:
        if n_replicas < 1:
            raise ValueError(f"a fleet needs >= 1 replica, got "
                             f"{n_replicas}")
        if not models:
            raise ValueError("a fleet needs at least one model")
        self.models = dict(models)
        self.n_replicas = int(n_replicas)
        #: {canary_name: (primary_name, fraction)} — validated here
        self.canaries: Dict[str, Tuple[str, float]] = {}
        default_frac = float(knobs.get(knobs.FLEET_CANARY_FRACTION))
        for cname, (primary, frac) in (canaries or {}).items():
            if cname not in self.models:
                raise ValueError(f"canary {cname!r} is not a "
                                 f"registered model")
            if primary not in self.models:
                raise ValueError(f"canary {cname!r} mirrors unknown "
                                 f"model {primary!r}")
            if cname == primary:
                raise ValueError(f"{cname!r} cannot canary itself")
            f = default_frac if frac is None else float(frac)
            if not 0.0 <= f <= 1.0:
                raise ValueError(f"canary fraction must be in [0, 1], "
                                 f"got {f} for {cname!r}")
            self.canaries[cname] = (primary, f)
        self._mirrors_by_primary: Dict[str, List[Tuple[str, float]]] \
            = {}
        for cname, (primary, f) in self.canaries.items():
            self._mirrors_by_primary.setdefault(primary, []).append(
                (cname, f))
        #: admission knobs — plain mutable attributes so an operator
        #: embedding the router (or a test) can retune a live fleet
        self.slo_p99_ms = float(slo_p99_ms) if slo_p99_ms is not None \
            else float(knobs.get(knobs.FLEET_SLO_P99_MS))
        self.max_inflight = int(max_inflight) \
            if max_inflight is not None \
            else int(knobs.get(knobs.FLEET_MAX_INFLIGHT))
        if metrics_dir:
            telemetry.configure(metrics_dir)
        self.metrics_dir = metrics_dir

        self.replicas = [
            Replica(i, self.models, backend=backend,
                    max_batch=max_batch, max_wait_ms=max_wait_ms,
                    hbm_budget=hbm_budget,
                    heartbeat_every=heartbeat_every,
                    metrics_dir=metrics_dir, cwd=cwd, env=env,
                    start_timeout=start_timeout)
            for i in range(self.n_replicas)]
        self.fleet = ReplicaSet(
            self.replicas, heartbeat_deadline=heartbeat_deadline,
            respawn_backoff=respawn_backoff)
        hellos = self.fleet.start()
        self.hello_models = hellos[0].get("models", {})

        #: routing affinity: hot models on all replicas, long tail
        #: partitioned (any healthy replica remains a fallback)
        policy = placement or PlacementPolicy(budget_bytes=hbm_budget)
        self.placement = policy.assign(
            {name: self.hello_models.get(name, {})
             .get("param_bytes", 0) for name in self.models},
            self.n_replicas)
        self._lock = threading.Lock()
        self._routed = [0] * self.n_replicas
        self._mirror_acc: Dict[str, float] = {}
        self._closed = False
        telemetry.event(events.EV_FLEET_PLACEMENT,
                        placement=self.placement)
        telemetry.event(
            events.EV_FLEET_READY, replicas=self.n_replicas,
            pids=[h.get("pid") for h in hellos],
            models=sorted(self.models),
            canaries={c: {"of": p, "fraction": f}
                      for c, (p, f) in self.canaries.items()},
            slo_p99_ms=self.slo_p99_ms,
            max_inflight=self.max_inflight)
        self.info("fleet up: %d replicas (pids %s), %d models, "
                  "placement %s", self.n_replicas,
                  [h.get("pid") for h in hellos], len(self.models),
                  self.placement)

    # -- routing -------------------------------------------------------

    def _pick(self, model: str,
              exclude: Tuple[Replica, ...] = ()) -> Optional[Replica]:
        """The least-loaded healthy replica holding ``model``; any
        healthy replica when none of the placed set is (the fallback
        LRU-loads the model on arrival)."""
        placed = set(self.placement.get(model, ()))
        healthy = [r for r in self.fleet.healthy()
                   if r not in exclude]
        candidates = [r for r in healthy if r.idx in placed] \
            or healthy
        if not candidates:
            return None
        return min(candidates, key=lambda r: (r.inflight, r.idx))

    def _shed(self, r: Replica) -> Optional[float]:
        """Admission control on the picked (least-loaded) candidate:
        returns the estimated completion in ms when the request must
        be shed, None when it is admitted.  Checking only the pick is
        sound because _pick minimizes queue depth — if the best
        replica sheds, every other candidate is deeper."""
        if r.inflight >= self.max_inflight:
            return float(r.estimated_total_ms())
        if self.slo_p99_ms > 0:
            est = r.estimated_total_ms()
            telemetry.gauge(events.GAUGE_FLEET_EST_WAIT_MS).set(
                round(est, 3))
            if est > self.slo_p99_ms:
                return float(est)
        return None

    def request(self, model: str, rows: Any,
                timeout: float = 60.0) -> Dict[str, Any]:
        """One routed round trip; returns the replica's response dict
        ({"pred", "probs"}), an {"error": ...} dict, or the shed
        response {"error": "overloaded", "overloaded": True}.  Never
        raises for replica death or overload — the protocol carries
        both."""
        telemetry.counter(events.CTR_FLEET_REQUESTS).inc()
        telemetry.counter(f"fleet.model.{model}.requests").inc()
        t0 = time.perf_counter()
        resp = self._dispatch(model, rows, timeout)
        if resp.get("overloaded"):
            telemetry.counter(events.CTR_FLEET_SHED).inc()
            telemetry.counter(f"fleet.model.{model}.shed").inc()
        elif "error" in resp:
            telemetry.counter(events.CTR_FLEET_REQUEST_ERRORS).inc()
            telemetry.counter(f"fleet.model.{model}.errors").inc()
        else:
            dt = time.perf_counter() - t0
            telemetry.histogram(
                events.HIST_FLEET_REQUEST_SECONDS).record(dt)
            telemetry.histogram(
                f"fleet.model.{model}.request_seconds").record(dt)
            self._maybe_mirror(model, rows, timeout)
        return resp

    def _dispatch(self, model: str, rows: Any,
                  timeout: float) -> Dict[str, Any]:
        r = self._pick(model)
        if r is None:
            return {"error": "no healthy replica", "model": model}
        est = self._shed(r)
        if est is not None:
            return {"error": "overloaded", "overloaded": True,
                    "model": model, "est_ms": round(est, 2)}
        tried: Tuple[Replica, ...] = ()
        for attempt in (0, 1):
            cur = r
            with self._lock:
                self._routed[cur.idx] += 1
            cur.acquire()
            telemetry.gauge(events.GAUGE_FLEET_INFLIGHT).set(
                self.inflight_total())
            try:
                return cur.client.wait_for(
                    cur.client.submit(model, rows), timeout)
            except ReplicaDied:
                # the monitor will respawn it; this request retries
                # ONCE on a healthy peer (idempotent inference) — the
                # admission gate is not re-run, the request was
                # already accepted
                cur.mark_dead()
                tried = tried + (cur,)
                if attempt == 0:
                    telemetry.counter(events.CTR_FLEET_RETRIES).inc()
                    peer = self._pick(model, exclude=tried)
                    if peer is None:
                        return {"error": "replica died and no "
                                         "healthy peer",
                                "model": model}
                    r = peer
            except TimeoutError:
                return {"error": f"timeout after {timeout}s",
                        "model": model}
            finally:
                cur.release()
        return {"error": "replica died twice", "model": model}

    def _maybe_mirror(self, primary: str, rows: Any,
                      timeout: float) -> None:
        """Mirror a deterministic sampled fraction of ``primary``'s
        admitted traffic to each of its canaries, asynchronously (the
        caller's latency never carries the mirror; the reader thread
        records the canary-side telemetry)."""
        pairs = self._mirrors_by_primary.get(primary)
        if not pairs:
            return
        for cname, frac in pairs:
            with self._lock:
                acc = self._mirror_acc.get(cname, 0.0) + frac
                fire = acc >= 1.0
                self._mirror_acc[cname] = acc - 1.0 if fire else acc
            if not fire:
                continue
            r = self._pick(cname)
            if r is None:
                continue
            telemetry.counter(events.CTR_FLEET_MIRRORED).inc()
            telemetry.counter(f"fleet.model.{cname}.requests").inc()
            telemetry.counter(f"fleet.model.{cname}.mirrored").inc()
            t0 = time.perf_counter()
            r.acquire()
            try:
                jid = r.client.submit(cname, rows)
            except ReplicaDied:
                r.release()
                r.mark_dead()
                telemetry.counter(
                    f"fleet.model.{cname}.errors").inc()
                continue

            def _collect(msg, err, r=r, cname=cname, t0=t0):
                r.release()
                if err is not None or (msg and "error" in msg):
                    telemetry.counter(
                        f"fleet.model.{cname}.errors").inc()
                else:
                    telemetry.histogram(
                        f"fleet.model.{cname}.request_seconds"
                    ).record(time.perf_counter() - t0)

            r.client.collect_async(jid, _collect)

    # -- introspection -------------------------------------------------

    def routed_counts(self) -> List[int]:
        """Requests routed per replica index (request spreading)."""
        with self._lock:
            return list(self._routed)

    def inflight_total(self) -> int:
        return sum(r.inflight for r in self.replicas)

    def replica_stats(self, timeout: float = 30.0) \
            -> List[Optional[Dict[str, Any]]]:
        """Each healthy replica's live telemetry snapshot (None for a
        dead slot) — the bench's per-replica recompile audit."""
        out: List[Optional[Dict[str, Any]]] = []
        for r in self.replicas:
            if r.healthy and r.client is not None:
                try:
                    out.append(r.client.stats(timeout=timeout))
                    continue
                except (ReplicaDied, TimeoutError):
                    pass
            out.append(None)
        return out

    def fleet_status(self) -> Dict[str, Any]:
        """One JSON-ready view of the fleet (the CLI's op=fleet)."""
        return {
            "replicas": [
                {"replica": r.idx, "pid": r.pid,
                 "healthy": r.healthy, "inflight": r.inflight,
                 "routed": self.routed_counts()[r.idx],
                 "deaths": r.deaths,
                 "ema_dispatch_ms": round(
                     1000 * r.ema_dispatch_s, 3)
                 if r.ema_dispatch_s else None}
                for r in self.replicas],
            "placement": self.placement,
            "canaries": {c: {"of": p, "fraction": f}
                         for c, (p, f) in self.canaries.items()},
            "slo_p99_ms": self.slo_p99_ms,
            "max_inflight": self.max_inflight,
        }

    # -- teardown ------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for every in-flight request to resolve."""
        deadline = time.monotonic() + timeout
        while self.inflight_total() > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def close(self, kill: bool = False, reason: Optional[str] = None,
              code: int = 0) -> None:
        if self._closed:
            return
        self._closed = True
        self.fleet.close(kill=kill)
        telemetry.event(events.EV_FLEET_SHUTDOWN,
                        routed=self.routed_counts(), reason=reason,
                        code=code)
        telemetry.flush()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- the CLI front end -------------------------------------------------

def parse_canary(spec: str) -> Tuple[str, str, Optional[float]]:
    """``CNAME=PRIMARY[:FRACTION]`` -> (cname, primary, fraction)."""
    cname, _, rest = spec.partition("=")
    primary, _, frac_s = rest.partition(":")
    if not cname or not primary:
        raise ValueError(
            f"bad --canary spec {spec!r} (want "
            f"CANARY=PRIMARY[:FRACTION])")
    frac = None
    if frac_s:
        frac = float(frac_s)
    return cname, primary, frac


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu --serve-fleet",
        description="Swarm: SLO-aware fleet router over N Hive "
                    "replicas")
    p.add_argument("replicas", type=int,
                   help="replica count (each is one --serve-models "
                        "subprocess)")
    p.add_argument("models", nargs="+", metavar="NAME=PKG",
                   help="model name = Forge ensemble package path; "
                        "DECLARATION ORDER is the placement hotness "
                        "order")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("--canary", action="append", default=[],
                   metavar="CNAME=PRIMARY[:FRACTION]",
                   help="register model CNAME as canary-of:PRIMARY, "
                        "mirroring FRACTION of PRIMARY's traffic "
                        "(default $VELES_FLEET_CANARY_FRACTION)")
    p.add_argument("--hot", action="append", default=None,
                   metavar="NAME",
                   help="override the placement hot set (repeatable); "
                        "hot models replicate on every replica")
    p.add_argument("--max-batch", type=int,
                   default=int(knobs.get(knobs.SERVE_MAX_BATCH)))
    p.add_argument("--max-wait-ms", type=float,
                   default=float(knobs.get(knobs.SERVE_MAX_WAIT_MS)))
    p.add_argument("--hbm-budget", type=int, default=0,
                   help="per-replica residency budget override")
    p.add_argument("--slo-p99-ms", type=float,
                   default=float(knobs.get(knobs.FLEET_SLO_P99_MS)),
                   help="admission-control SLO target "
                        "($VELES_FLEET_SLO_P99_MS; 0 disables "
                        "shedding)")
    p.add_argument("--max-inflight", type=int,
                   default=int(knobs.get(knobs.FLEET_MAX_INFLIGHT)),
                   help="per-replica in-flight bound "
                        "($VELES_FLEET_MAX_INFLIGHT)")
    p.add_argument("--heartbeat-every", type=float,
                   default=float(knobs.get(knobs.HEARTBEAT_EVERY)))
    p.add_argument("--metrics-dir", default=None,
                   help="fleet Sightline dir; each replica writes "
                        "into replica-<i>/ under it")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from concurrent.futures import ThreadPoolExecutor

    from veles_tpu.logger import setup_logging

    args = build_parser().parse_args(argv)
    setup_logging(10 if args.verbose else 20)
    if args.replicas < 1:
        print(f"--serve-fleet: replica count must be >= 1 "
              f"(got {args.replicas})", file=sys.stderr)
        return 2
    specs: Dict[str, str] = {}
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not name or not path:
            print(f"--serve-fleet: bad model spec {spec!r} "
                  f"(want NAME=PACKAGE.vpkg)", file=sys.stderr)
            return 2
        if not os.path.isfile(path):
            print(f"--serve-fleet: no such package {path!r}",
                  file=sys.stderr)
            return 2
        specs[name] = path
    canaries: Dict[str, Tuple[str, Optional[float]]] = {}
    try:
        for cspec in args.canary:
            cname, primary, frac = parse_canary(cspec)
            canaries[cname] = (primary, frac)
        router = FleetRouter(
            specs, args.replicas, backend=args.backend,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            hbm_budget=args.hbm_budget or None,
            heartbeat_every=args.heartbeat_every,
            metrics_dir=args.metrics_dir,
            canaries=canaries,
            placement=PlacementPolicy(
                budget_bytes=args.hbm_budget or None,
                hot=set(args.hot) if args.hot else None),
            slo_p99_ms=args.slo_p99_ms,
            max_inflight=args.max_inflight)
    except (ValueError, RuntimeError) as e:
        print(f"--serve-fleet: {e}", file=sys.stderr)
        return 2

    emit_lock = threading.Lock()

    def emit(obj: Dict[str, Any]) -> None:
        with emit_lock:
            print(json.dumps(obj), flush=True)

    emit({"ready": True, "pid": os.getpid(),
          "fleet": args.replicas,
          "replica_pids": [r.pid for r in router.replicas],
          "models": router.hello_models,
          "placement": router.placement,
          "canaries": {c: {"of": p, "fraction": f}
                       for c, (p, f) in router.canaries.items()},
          "slo_p99_ms": router.slo_p99_ms,
          "max_inflight": router.max_inflight})
    telemetry.flush()

    stop = {"signal": None}
    stop_event = threading.Event()

    def _on_term(signum, frame) -> None:
        if stop["signal"] is not None:
            os.write(2, b"fleet: second signal - hard exit\n")
            os._exit(EXIT_PREEMPTED)
        stop["signal"] = signum
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except (ValueError, OSError):   # embedded / non-main thread
        pass

    hb_stop = threading.Event()

    def _hb_loop() -> None:
        n = 0
        while not hb_stop.wait(args.heartbeat_every):
            emit({"hb": n, "pid": os.getpid()})
            n += 1

    if args.heartbeat_every > 0:
        threading.Thread(target=_hb_loop, daemon=True,
                         name="fleet-heartbeat").start()

    jobs: "queue.Queue[Optional[str]]" = queue.Queue()

    def _read_stdin() -> None:
        for line in sys.stdin:
            jobs.put(line)
        jobs.put(None)   # EOF

    threading.Thread(target=_read_stdin, daemon=True,
                     name="fleet-stdin").start()

    pool = ThreadPoolExecutor(
        max_workers=min(64, 8 * args.replicas),
        thread_name_prefix="fleet-route")

    def handle(line: str) -> bool:
        """One request line; returns False when the loop should end."""
        line = line.strip()
        if not line:
            return True
        try:
            job = json.loads(line)
        except ValueError:
            emit({"error": f"bad request line: {line[:120]!r}"})
            return True
        op = job.get("op")
        if op == "shutdown":
            return False
        if op == "stats":
            emit({"id": job.get("id"), "stats": telemetry.snapshot()})
            return True
        if op == "fleet":
            emit({"id": job.get("id"),
                  "fleet": router.fleet_status()})
            return True
        jid = job.get("id")
        try:
            model = job["model"]
            rows = np.asarray(job["rows"], np.float32)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — a bad request
            emit({"id": jid, "error": f"{type(e).__name__}: {e}"})
            return True

        def _route(jid=jid, model=model, rows=rows) -> None:
            resp = router.request(model, rows)
            resp = dict(resp)
            resp["id"] = jid
            emit(resp)

        pool.submit(_route)
        return True

    rc = 0
    while not stop_event.is_set():
        try:
            line = jobs.get(timeout=0.2)
        except queue.Empty:
            continue
        if line is None:      # stdin closed: the parent went away
            break
        if not handle(line):
            break

    # -- drain: accept what is already on the wire, then let every
    # in-flight routed request resolve before the replicas go down
    if stop_event.is_set():
        time.sleep(0.3)
    n_late = 0
    while True:
        try:
            line = jobs.get_nowait()
        except queue.Empty:
            break
        if line is None:
            continue
        n_late += 1
        handle(line)
    pool.shutdown(wait=True)
    drained = router.drain()
    telemetry.event(events.EV_FLEET_DRAIN, late_requests=n_late,
                    complete=bool(drained))
    reason = None
    if stop["signal"] is not None:
        try:
            reason = signal.Signals(stop["signal"]).name
        except ValueError:
            reason = f"sig{stop['signal']}"
        rc = EXIT_PREEMPTED
    router.close(reason=reason, code=rc)
    hb_stop.set()
    telemetry.flush()
    if rc:
        # the Phoenix preemption contract: a supervised fleet resumes
        # with warm replica install dirs
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
