"""Swarm: the fleet router — N Hive replicas behind one front end.

``python -m veles_tpu --serve-fleet N NAME=PKG.vpkg [NAME=PKG ...]``

PR 10's Hive is ONE chip-owning process, so serving throughput is
capped by one process no matter how many cores/chips the box has.
:class:`FleetRouter` owns N Hive replicas
(:mod:`veles_tpu.serve.fleet` spawns and supervises them) and fans
concurrent requests out across them:

- **placement-aware routing**: a :class:`~veles_tpu.serve.fleet.
  PlacementPolicy` replicates hot models on every replica and
  partitions the long tail; a request goes to the LEAST-LOADED healthy
  replica holding the model (router-side in-flight queue depth per
  replica), falling back to any healthy replica (which LRU-loads the
  model under its own residency budget);
- **failover**: a replica death (reader EOF or heartbeat deadline)
  fails its in-flight requests with ``ReplicaDied`` *immediately*;
  the router retries each exactly once on a healthy peer (inference
  is idempotent) while the fleet monitor respawns the replica with a
  warm install dir — pending waiters NEVER hang;
- **admission control**: a bounded per-replica router queue plus an
  SLO target (``$VELES_FLEET_SLO_P99_MS``): when the estimated
  completion (queue depth x observed per-dispatch time + batching
  window) would blow the target on even the least-loaded candidate,
  the request is shed with an explicit ``overloaded`` response
  instead of letting p99 run away;
- **canary / shadow**: a model registered as ``canary-of:NAME``
  receives a sampled fraction of NAME's traffic as asynchronous
  mirrors; per-model QPS/latency/error telemetry is split
  (``fleet.model.<name>.*``) so the A/B reads directly from
  ``obs_report --fleet``;
- **gray-failure defense** (:mod:`veles_tpu.serve.sentinel`): every
  request carries an absolute ``deadline_ms`` end-to-end (the hive
  batcher drops expired rows before dispatch), a request older than
  the adaptive hedge threshold is reissued on a second replica under
  the ``$VELES_FLEET_HEDGE_BUDGET`` cap (first answer wins, the loser
  is cancelled by wire id), responses are integrity-verified against
  their row-count/crc echo, and a replica accumulating strikes —
  deadline misses, deaths, integrity failures, hedge losses, latency
  outliers — is EJECTED from routing, probed with synthetic canaries,
  and reinstated after ``$VELES_FLEET_PROBE_OK`` clean probes.

The CLI front end speaks the same JSONL protocol as a single hive
(hello line, heartbeats, ``{"id", "model", "rows"}`` in /
``{"id", "pred", "probs"}`` out), so every Hive client — including
another router — can point at a fleet unchanged.  Shed responses are
``{"id", "error": "overloaded", "overloaded": true}``.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import events, knobs, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger
from veles_tpu.serve.client import ReplicaDied
from veles_tpu.serve.fleet import PlacementPolicy, Replica, ReplicaSet
from veles_tpu.serve.sentinel import Sentinel
from veles_tpu.supervisor import EXIT_PREEMPTED


class FleetRouter(Logger):
    """Own N Hive replicas; route, shed, mirror, and fail over."""

    def __init__(self, models: Dict[str, str], n_replicas: int,
                 backend: str = "cpu",
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 hbm_budget: Optional[int] = None,
                 heartbeat_every: Optional[float] = None,
                 metrics_dir: Optional[str] = None,
                 cwd: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 canaries: Optional[Dict[str,
                                         Tuple[str,
                                               Optional[float]]]] = None,
                 placement: Optional[PlacementPolicy] = None,
                 slo_p99_ms: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 heartbeat_deadline: Optional[float] = None,
                 respawn_backoff: Optional[float] = None,
                 start_timeout: float = 300.0,
                 deadline_ms: Optional[float] = None,
                 hedge_min_ms: Optional[float] = None,
                 hedge_budget: Optional[float] = None,
                 eject_threshold: Optional[float] = None,
                 probe_ok: Optional[int] = None,
                 probe_interval: Optional[float] = None,
                 probe_backoff_cap: Optional[float] = None,
                 env_overrides: Optional[Dict[int, Dict[str, str]]]
                 = None,
                 mesh: Optional[Any] = None) -> None:
        if n_replicas < 1:
            raise ValueError(f"a fleet needs >= 1 replica, got "
                             f"{n_replicas}")
        if not models:
            raise ValueError("a fleet needs at least one model")
        self.models = dict(models)
        self.n_replicas = int(n_replicas)
        #: {canary_name: (primary_name, fraction)} — validated here
        self.canaries: Dict[str, Tuple[str, float]] = {}
        default_frac = float(knobs.get(knobs.FLEET_CANARY_FRACTION))
        for cname, (primary, frac) in (canaries or {}).items():
            if cname not in self.models:
                raise ValueError(f"canary {cname!r} is not a "
                                 f"registered model")
            if primary not in self.models:
                raise ValueError(f"canary {cname!r} mirrors unknown "
                                 f"model {primary!r}")
            if cname == primary:
                raise ValueError(f"{cname!r} cannot canary itself")
            f = default_frac if frac is None else float(frac)
            if not 0.0 <= f <= 1.0:
                raise ValueError(f"canary fraction must be in [0, 1], "
                                 f"got {f} for {cname!r}")
            self.canaries[cname] = (primary, f)
        self._mirrors_by_primary: Dict[str, List[Tuple[str, float]]] \
            = {}
        for cname, (primary, f) in self.canaries.items():
            self._mirrors_by_primary.setdefault(primary, []).append(
                (cname, f))
        #: admission knobs — plain mutable attributes so an operator
        #: embedding the router (or a test) can retune a live fleet
        self.slo_p99_ms = float(slo_p99_ms) if slo_p99_ms is not None \
            else float(knobs.get(knobs.FLEET_SLO_P99_MS))
        self.max_inflight = int(max_inflight) \
            if max_inflight is not None \
            else int(knobs.get(knobs.FLEET_MAX_INFLIGHT))
        #: default per-request deadline budget (ms); a request's
        #: absolute deadline_ms = now + min(this, caller timeout)
        self.deadline_ms = float(deadline_ms) \
            if deadline_ms is not None \
            else float(knobs.get(knobs.FLEET_DEADLINE_MS))
        if metrics_dir:
            telemetry.configure(metrics_dir)
        self.metrics_dir = metrics_dir

        def _replica_env(i: int) -> Optional[Dict[str, str]]:
            # per-replica overrides (gray-failure drills arm ONE
            # replica's VELES_FAULTS without touching its peers)
            over = (env_overrides or {}).get(i)
            if not over:
                return env
            merged = dict(env or {})
            merged.update(over)
            return merged

        def _replica_mesh(i: int) -> int:
            # the Prism topology knob: an int meshes every replica,
            # a {replica_index: devices} dict mixes 1-device and
            # N-device replicas in one fleet
            if mesh is None:
                return 0
            if isinstance(mesh, dict):
                return int(mesh.get(i, 0))
            return int(mesh)

        def _make_replica(i: int,
                          install_dir: Optional[str] = None) -> Replica:
            return Replica(i, self.models, backend=backend,
                           max_batch=max_batch,
                           max_wait_ms=max_wait_ms,
                           hbm_budget=hbm_budget,
                           heartbeat_every=heartbeat_every,
                           metrics_dir=metrics_dir, cwd=cwd,
                           env=_replica_env(i), mesh=_replica_mesh(i),
                           start_timeout=start_timeout,
                           install_dir=install_dir)

        #: the scale-up path re-uses the ctor's replica recipe (same
        #: models/env/backend), so an elastic member is
        #: indistinguishable from a founding one
        self._make_replica = _make_replica
        self.replicas = [_make_replica(i)
                         for i in range(self.n_replicas)]
        self.fleet = ReplicaSet(
            self.replicas, heartbeat_deadline=heartbeat_deadline,
            respawn_backoff=respawn_backoff)
        hellos = self.fleet.start()
        self.hello_models = hellos[0].get("models", {})

        #: routing affinity: hot models on all replicas, long tail
        #: partitioned (any healthy replica remains a fallback).
        #: Capacities come from each replica's OWN hello — a --mesh N
        #: replica advertises devices x per-device budget, so the
        #: split places against real, heterogeneous capacity
        policy = placement or PlacementPolicy(budget_bytes=hbm_budget)
        self.placement = policy.assign(
            {name: self.hello_models.get(name, {})
             .get("param_bytes", 0) for name in self.models},
            self.n_replicas,
            capacities=[r.capacity_bytes for r in self.replicas])
        self._lock = witness.lock("router.state")
        self._routed = [0] * self.n_replicas
        self._mirror_acc: Dict[str, float] = {}
        self._closed = False
        # -- elastic-fleet state (Gauntlet) ---------------------------
        #: next replica index to mint: indices are NEVER reused, so
        #: ``_routed``/telemetry rows stay unambiguous across the day
        self._next_idx = self.n_replicas
        #: install dirs of retired replicas — a scale-up pops one so
        #: the package unpack (and compile cache) stays warm
        self._warm_dirs: List[str] = []
        #: the replicated hot prefix at placement time: a scale-up
        #: joins these; everything else is the sheddable long tail
        self._hot_models = {
            m for m, placed in self.placement.items()
            if len(placed) == self.n_replicas}
        #: degradation-ladder levers (the autoscaler flips these via
        #: ``apply_degradation``; mutable for tests/operators too)
        self.hedging_enabled = True
        self.shed_tail = False
        #: gray-failure defense: health scoring, hedging governor,
        #: ejection + probe/reinstate lifecycle
        sentinel_kw = {}
        if probe_backoff_cap is not None:
            sentinel_kw["probe_backoff_cap"] = probe_backoff_cap
        self.sentinel = Sentinel(
            self.replicas, probe_fn=self._probe_replica,
            hedge_min_ms=hedge_min_ms, hedge_budget=hedge_budget,
            eject_threshold=eject_threshold, probe_ok=probe_ok,
            probe_interval=probe_interval, **sentinel_kw)
        telemetry.event(events.EV_FLEET_PLACEMENT,
                        placement=self.placement)
        telemetry.event(
            events.EV_FLEET_READY, replicas=self.n_replicas,
            pids=[h.get("pid") for h in hellos],
            models=sorted(self.models),
            canaries={c: {"of": p, "fraction": f}
                      for c, (p, f) in self.canaries.items()},
            slo_p99_ms=self.slo_p99_ms,
            max_inflight=self.max_inflight)
        self.info("fleet up: %d replicas (pids %s), %d models, "
                  "placement %s", self.n_replicas,
                  [h.get("pid") for h in hellos], len(self.models),
                  self.placement)

    # -- routing -------------------------------------------------------

    def _pick(self, model: str,
              exclude: Tuple[Replica, ...] = ()) -> Optional[Replica]:
        """The least-loaded healthy, non-ejected replica holding
        ``model``; any eligible replica when none of the placed set is
        (the fallback LRU-loads the model on arrival).  A
        sentinel-ejected replica sheds route around it — only probes
        reach it until it is reinstated."""
        placed = set(self.placement.get(model, ()))
        healthy = [r for r in self.fleet.healthy()
                   if r not in exclude and not r.retiring
                   and self.sentinel.eligible(r)]
        candidates = [r for r in healthy if r.idx in placed] \
            or healthy
        if not candidates:
            return None
        return min(candidates, key=lambda r: (r.inflight, r.idx))

    def _shed(self, r: Replica) -> Optional[float]:
        """Admission control on the picked (least-loaded) candidate:
        returns the estimated completion in ms when the request must
        be shed, None when it is admitted.  Checking only the pick is
        sound because _pick minimizes queue depth — if the best
        replica sheds, every other candidate is deeper."""
        if r.inflight >= self.max_inflight:
            return float(r.estimated_total_ms())
        if self.slo_p99_ms > 0:
            est = r.estimated_total_ms()
            telemetry.gauge(events.GAUGE_FLEET_EST_WAIT_MS).set(
                round(est, 3))
            if est > self.slo_p99_ms:
                return float(est)
        return None

    def request(self, model: str, rows: Any,
                timeout: float = 60.0,
                deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """One routed round trip; returns the replica's response dict
        ({"pred", "probs"}), an {"error": ...} dict, or the shed
        response {"error": "overloaded", "overloaded": True}.  Never
        raises for replica death, overload, or a blown deadline — the
        protocol carries all three.

        ``deadline_ms`` is the request's ABSOLUTE unix-epoch deadline;
        when None it is stamped as now + min($VELES_FLEET_DEADLINE_MS,
        ``timeout``*1000) and rides the wire end-to-end."""
        telemetry.counter(events.CTR_FLEET_REQUESTS).inc()
        telemetry.counter(f"fleet.model.{model}.requests").inc()
        rows = np.asarray(rows, np.float32)
        self.sentinel.note_request(model, rows)
        if deadline_ms is None:
            budget = self.deadline_ms if self.deadline_ms > 0 \
                else 1000.0 * timeout
            if timeout:
                budget = min(budget, 1000.0 * timeout)
            deadline_ms = time.time() * 1000.0 + budget
        # the trace ROOT is minted here, at the fleet's admission
        # edge: every leg (hedge copies, failover retries, canary
        # mirrors) derives a child span from it, so one request
        # assembles into ONE cross-process tree however it was routed
        ctx = trace.mint()
        t0 = time.perf_counter()
        with trace.use(ctx):
            resp = self._dispatch(model, rows, float(deadline_ms))
            if resp.get("overloaded"):
                telemetry.counter(events.CTR_FLEET_SHED).inc()
                telemetry.counter(f"fleet.model.{model}.shed").inc()
            elif "error" in resp:
                telemetry.counter(
                    events.CTR_FLEET_REQUEST_ERRORS).inc()
                telemetry.counter(f"fleet.model.{model}.errors").inc()
            else:
                dt = time.perf_counter() - t0
                telemetry.histogram(
                    events.HIST_FLEET_REQUEST_SECONDS).record(
                    dt, exemplar=ctx.trace_id if ctx.sampled else None)
                telemetry.histogram(
                    f"fleet.model.{model}.request_seconds").record(dt)
                self._maybe_mirror(model, rows, timeout)
            if ctx.sampled:
                outcome = ("shed" if resp.get("overloaded")
                           else "timeout" if resp.get("timeout")
                           else "error" if "error" in resp else "ok")
                telemetry.event(
                    events.EV_TRACE_REQUEST, trace=ctx.trace_id,
                    span=ctx.span_id, model=model,
                    rows=int(len(rows)), outcome=outcome,
                    seconds=round(time.perf_counter() - t0, 6))
                trace.record("fleet.request", ctx=ctx, model=model,
                             outcome=outcome)
        return resp

    def _dispatch(self, model: str, rows: Any,
                  deadline_ms: float) -> Dict[str, Any]:
        if self.shed_tail and model not in self._hot_models:
            # the ladder's last rung: the long tail is explicitly shed
            # so the hot prefix keeps its p99 — an honest overloaded
            # response, never a timeout and never a 404
            return {"error": "overloaded", "overloaded": True,
                    "degraded": True, "model": model}
        r = self._pick(model)
        if r is None:
            return {"error": "no healthy replica", "model": model}
        est = self._shed(r)
        if est is not None:
            return {"error": "overloaded", "overloaded": True,
                    "model": model, "est_ms": round(est, 2)}
        tried: Tuple[Replica, ...] = ()
        cur = r
        resp: Dict[str, Any] = {"error": "unroutable", "model": model}
        for attempt in (0, 1):
            resp, verdict = self._routed_round(model, rows, cur,
                                               deadline_ms, tried)
            if verdict in ("ok", "timeout"):
                # a blown deadline is FINAL: the budget is spent, a
                # retry would only answer after nobody is waiting
                return resp
            # verdict died/integrity: retry ONCE on a healthy peer
            # (idempotent inference) — the admission gate is not
            # re-run, the request was already accepted
            tried = tried + (cur,)
            if attempt == 0:
                telemetry.counter(events.CTR_FLEET_RETRIES).inc()
                peer = self._pick(model, exclude=tried)
                if peer is None:
                    return {"error": f"replica failed ({verdict}) "
                                     f"and no healthy peer",
                            "model": model}
                cur = peer
        return resp

    def _routed_round(self, model: str, rows: np.ndarray,
                      primary: Replica, deadline_ms: float,
                      tried: Tuple[Replica, ...]
                      ) -> Tuple[Dict[str, Any], str]:
        """One routed attempt with hedging: submit to ``primary``;
        once the request's age crosses the adaptive hedge threshold
        (and the hedge budget allows), issue a second copy on a
        different replica, take the FIRST clean answer, and cancel the
        loser by wire id.  Returns (response, verdict) with verdict
        one of ``ok`` (also replica-side request errors — they are
        deterministic, not gray), ``timeout`` (deadline blown),
        ``died``, ``integrity``.

        Structured in two phases so the HOT path (the answer beats the
        hedge threshold, i.e. almost always) costs exactly what the
        pre-sentinel router did — one submit + one blocking wait; the
        per-request fan-in queue exists only for the rare request that
        actually hedges."""
        n_rows = int(len(rows))
        t_start = time.perf_counter()
        # the round runs on the thread that minted the root (or a pool
        # worker under ``trace.use``); each LEG — primary attempt,
        # hedge copy, failover retry — gets its own child span, so the
        # assembled trace shows every replica the request touched
        rctx = trace.current()

        def leg_ctx() -> Optional[trace.TraceContext]:
            return rctx.child() \
                if rctx is not None and rctx.sampled else None

        def leg_event(rep: Replica,
                      lctx: Optional[trace.TraceContext],
                      verdict: str, t0_leg: float,
                      hedge: bool = False,
                      winner: bool = False) -> None:
            if lctx is None:
                return
            telemetry.event(
                events.EV_TRACE_LEG, trace=lctx.trace_id,
                span=lctx.span_id, parent=lctx.parent_id,
                replica=rep.idx, verdict=verdict,
                seconds=round(time.perf_counter() - t0_leg, 6),
                hedge=bool(hedge), winner=bool(winner))

        def timeout_resp() -> Tuple[Dict[str, Any], str]:
            return ({"error": "deadline exceeded", "model": model,
                     "timeout": True,
                     "deadline_ms": round(deadline_ms, 1)}, "timeout")

        def evaluate(rep: Replica, msg: Dict[str, Any]) \
                -> Tuple[Dict[str, Any], str]:
            """Judge one ANSWERED leg (the caller already released)."""
            if "error" in msg:
                if msg.get("expired"):
                    # the hive's own batcher dropped it past deadline:
                    # that replica's queue blew the budget
                    self.sentinel.record_timeout(rep)
                    return timeout_resp()
                # a deterministic request error (bad shape, unknown
                # model): return it as-is — no strike, no retry
                return msg, "ok"
            if not self._verify_integrity(msg, n_rows):
                self.sentinel.record_integrity(rep)
                return ({"error": "response failed integrity check",
                         "model": model}, "integrity")
            self.sentinel.record_ok(
                rep, model, time.perf_counter() - t_start)
            return msg, "ok"

        remain_s = (deadline_ms - time.time() * 1000.0) / 1000.0
        if remain_s <= 0:
            return timeout_resp()
        with self._lock:
            self._routed[primary.idx] += 1
        primary.acquire()
        telemetry.gauge(events.GAUGE_FLEET_INFLIGHT).set(
            self.inflight_total())
        pctx = leg_ctx()
        t_leg0 = time.perf_counter()
        try:
            jid = primary.client.submit(model, rows,
                                        deadline_ms=deadline_ms,
                                        ctx=pctx)
        except ReplicaDied:
            primary.release()
            primary.mark_dead()
            self.sentinel.record_died(primary)
            leg_event(primary, pctx, "died", t_leg0)
            return {"error": "replica died", "model": model}, "died"
        # -- phase 1: plain wait until the hedge threshold ------------
        hedge_thr_s = self.sentinel.hedge_threshold_ms(model) / 1000.0
        try:
            msg = primary.client.wait_for(
                jid, timeout=max(0.001, min(hedge_thr_s, remain_s)))
            primary.release()
            out = evaluate(primary, msg)
            leg_event(primary, pctx, out[1], t_leg0,
                      winner=out[1] == "ok")
            return out
        except TimeoutError:
            pass   # outlived the hedge threshold: fall through
        except ReplicaDied:
            # the reader already failed every waiter, but the wire id
            # must still be retired — uniform waiter discipline, and
            # a respawned client can never collide with it
            primary.client.cancel(jid)
            primary.release()
            primary.mark_dead()
            self.sentinel.record_died(primary)
            leg_event(primary, pctx, "died", t_leg0)
            return {"error": "replica died", "model": model}, "died"
        # -- the request outlived the hedge threshold -----------------
        remain_s = (deadline_ms - time.time() * 1000.0) / 1000.0
        if remain_s <= 0:
            primary.client.cancel(jid)
            primary.release()
            self.sentinel.record_timeout(primary)
            leg_event(primary, pctx, "timeout", t_leg0)
            return timeout_resp()
        peer: Optional[Replica] = None
        if self.hedging_enabled and self.sentinel.hedge_budget > 0:
            cand = self._pick(model, exclude=tried + (primary,))
            # a hedge duplicates load: it must pass the SAME admission
            # gate a fresh request would — hedging fights tail
            # latency, never overload (an overloaded peer would only
            # queue the copy)
            if cand is not None and self._shed(cand) is None \
                    and self.sentinel.allow_hedge():
                peer = cand
            elif cand is not None:
                telemetry.counter(events.CTR_FLEET_HEDGE_DENIED).inc()
        if peer is None:
            # no hedge possible: wait the primary out to the deadline
            try:
                msg = primary.client.wait_for(
                    jid, timeout=max(0.001, remain_s))
            except TimeoutError:
                primary.client.cancel(jid)
                primary.release()
                self.sentinel.record_timeout(primary)
                leg_event(primary, pctx, "timeout", t_leg0)
                return timeout_resp()
            except ReplicaDied:
                primary.client.cancel(jid)
                primary.release()
                primary.mark_dead()
                self.sentinel.record_died(primary)
                leg_event(primary, pctx, "died", t_leg0)
                return ({"error": "replica died", "model": model},
                        "died")
            primary.release()
            out = evaluate(primary, msg)
            leg_event(primary, pctx, out[1], t_leg0,
                      winner=out[1] == "ok")
            return out
        # -- phase 2: the hedged fan-in (the rare, already-slow case) -
        telemetry.counter(events.CTR_FLEET_HEDGES).inc()
        trace.record("fleet.hedge", ctx=rctx, model=model,
                     primary=primary.idx, peer=peer.idx)
        results: "queue.SimpleQueue[Tuple[Replica, int, Any, Any]]" \
            = queue.SimpleQueue()
        outstanding: Dict[Tuple[int, int], Replica] = {}
        # per-leg trace span + submit time, keyed like ``outstanding``
        # — BOTH hedge legs are recorded and the winner attributed
        legmeta: Dict[Tuple[int, int],
                      Tuple[Optional[trace.TraceContext],
                            float, bool]] = {}
        outstanding[(primary.idx, jid)] = primary
        legmeta[(primary.idx, jid)] = (pctx, t_leg0, False)
        primary.client.collect_async(
            jid, lambda m, e, rep=primary, j=jid:
            results.put((rep, j, m, e)))
        with self._lock:
            self._routed[peer.idx] += 1
        peer.acquire()
        hctx = leg_ctx()
        t_hleg0 = time.perf_counter()
        try:
            hjid = peer.client.submit(model, rows,
                                      deadline_ms=deadline_ms,
                                      ctx=hctx)
        except ReplicaDied:
            peer.release()
            peer.mark_dead()
            self.sentinel.record_died(peer)
            leg_event(peer, hctx, "died", t_hleg0, hedge=True)
        else:
            # registered ONLY after the submit succeeded: the except
            # arm above covers exactly the risky call, so a hedge id
            # can never be created and then forgotten
            outstanding[(peer.idx, hjid)] = peer
            legmeta[(peer.idx, hjid)] = (hctx, t_hleg0, True)
            peer.client.collect_async(
                hjid, lambda m, e, rep=peer, j=hjid:
                results.put((rep, j, m, e)))

        def drop_outstanding(score_timeout: bool) -> None:
            for (idx, ojid), rep in list(outstanding.items()):
                rep.client.cancel(ojid)
                rep.release()
                if score_timeout:
                    self.sentinel.record_timeout(rep)
                lctx, t0l, hedged = legmeta.get(
                    (idx, ojid), (None, t_start, False))
                leg_event(rep, lctx,
                          "timeout" if score_timeout else "cancelled",
                          t0l, hedge=hedged)
            outstanding.clear()

        fail: Optional[Tuple[Dict[str, Any], str]] = None
        while outstanding:
            remain_s = (deadline_ms - time.time() * 1000.0) / 1000.0
            if remain_s <= 0:
                drop_outstanding(score_timeout=True)
                return timeout_resp()
            try:
                rep, rjid, msg, err = results.get(
                    timeout=max(0.001, remain_s))
            except queue.Empty:
                continue
            if (rep.idx, rjid) not in outstanding:
                continue   # already cancelled
            outstanding.pop((rep.idx, rjid))
            rep.release()
            lctx, t0l, hedged = legmeta.get(
                (rep.idx, rjid), (None, t_start, False))
            if err is not None:
                rep.mark_dead()
                self.sentinel.record_died(rep)
                leg_event(rep, lctx, "died", t0l, hedge=hedged)
                fail = ({"error": "replica died", "model": model},
                        "died")
                continue   # the other leg may still answer
            out = evaluate(rep, msg)
            leg_event(rep, lctx, out[1], t0l, hedge=hedged,
                      winner=out[1] == "ok")
            if out[1] == "ok":
                if rep is peer and "probs" in out[0]:
                    self.sentinel.record_hedge_win(rep, primary)
                drop_outstanding(score_timeout=False)
                return out
            fail = out   # expired / integrity: other leg may save it
        return fail if fail is not None \
            else ({"error": "replica died", "model": model}, "died")

    @staticmethod
    def _verify_integrity(msg: Dict[str, Any], n_rows: int) -> bool:
        """The response-integrity echo: the probability payload must
        carry exactly the requested row count and match the crc32 the
        hive computed over its clean float32 payload (float32
        round-trips JSON exactly, so any wire/compute corruption
        breaks the checksum).  Responses from pre-echo hives (no crc
        field) pass — the row-count check still applies."""
        if "probs" not in msg:
            return True
        try:
            probs = np.asarray(msg["probs"], np.float32)
        except (TypeError, ValueError):
            return False
        if probs.ndim < 1 or len(probs) != n_rows:
            return False
        rows_n = msg.get("rows_n")
        if rows_n is not None and int(rows_n) != len(probs):
            return False
        crc = msg.get("crc")
        if crc is not None \
                and zlib.crc32(probs.tobytes()) != int(crc):
            return False
        return True

    def _probe_replica(self, r: Replica, model: str,
                       rows: np.ndarray) -> Tuple[bool, str]:
        """One synthetic canary request aimed STRAIGHT at replica
        ``r`` (bypassing routing — it is ejected) — the sentinel's
        reinstatement evidence.  Clean = answered inside the probe
        deadline AND integrity-verified."""
        timeout_s = max(1.0,
                        4.0 * self.sentinel.hedge_threshold_ms(model)
                        / 1000.0)
        if not r.healthy or r.client is None:
            return False, "replica process down"
        deadline_ms = time.time() * 1000.0 + 1000.0 * timeout_s
        try:
            jid = r.client.submit(model, rows,
                                  deadline_ms=deadline_ms)
        except ReplicaDied as e:
            return False, f"died at submit: {e}"
        try:
            msg = r.client.wait_for(jid, timeout=timeout_s)
        except TimeoutError:
            r.client.cancel(jid)
            return False, f"no answer in {timeout_s:.1f}s"
        except ReplicaDied as e:
            r.client.cancel(jid)
            return False, f"died: {e}"
        if "error" in msg:
            return False, f"error: {msg['error']}"
        if not self._verify_integrity(msg, int(len(rows))):
            return False, "integrity mismatch"
        return True, "clean"

    def _maybe_mirror(self, primary: str, rows: Any,
                      timeout: float) -> None:
        """Mirror a deterministic sampled fraction of ``primary``'s
        admitted traffic to each of its canaries, asynchronously (the
        caller's latency never carries the mirror; the reader thread
        records the canary-side telemetry)."""
        pairs = self._mirrors_by_primary.get(primary)
        if not pairs:
            return
        for cname, frac in pairs:
            with self._lock:
                acc = self._mirror_acc.get(cname, 0.0) + frac
                fire = acc >= 1.0
                self._mirror_acc[cname] = acc - 1.0 if fire else acc
            if not fire:
                continue
            r = self._pick(cname)
            if r is None:
                continue
            telemetry.counter(events.CTR_FLEET_MIRRORED).inc()
            telemetry.counter(f"fleet.model.{cname}.requests").inc()
            telemetry.counter(f"fleet.model.{cname}.mirrored").inc()
            t0 = time.perf_counter()
            # the mirror leg joins the primary request's trace (a
            # child of the root minted in request()) so a canary
            # regression can be tied back to the traffic that hit it
            c = trace.current()
            mctx = c.child() if c is not None and c.sampled else None
            r.acquire()
            try:
                jid = r.client.submit(
                    cname, rows,
                    deadline_ms=time.time() * 1000.0
                    + self.deadline_ms if self.deadline_ms > 0
                    else None,
                    ctx=mctx)
            except ReplicaDied:
                r.release()
                r.mark_dead()
                telemetry.counter(
                    f"fleet.model.{cname}.errors").inc()
                continue

            def _collect(msg, err, r=r, cname=cname, t0=t0):
                r.release()
                if err is not None or (msg and "error" in msg):
                    telemetry.counter(
                        f"fleet.model.{cname}.errors").inc()
                else:
                    telemetry.histogram(
                        f"fleet.model.{cname}.request_seconds"
                    ).record(time.perf_counter() - t0)

            r.client.collect_async(jid, _collect)

    # -- elastic scaling (Gauntlet) ------------------------------------

    def add_replica(self, cause: str = "manual",
                    **info: Any) -> Optional[Replica]:
        """Scale up by one replica: spawn (into a warm install dir
        from a retired peer when one is pooled), join it into the hot
        placement, and hand it to the monitor + sentinel.  The spawn
        runs on the caller's thread (the autoscaler's loop) — routing
        never sees the replica until its hello arrived.  Returns the
        new Replica, or None when the spawn failed (the controller's
        cooldown spaces the retry)."""
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
            warm = self._warm_dirs.pop() if self._warm_dirs else None
        r = self._make_replica(idx, install_dir=warm)
        try:
            hello = r.spawn()
        except Exception as e:  # noqa: BLE001 — a failed scale-up
            self.error("scale-up replica %d failed to spawn: %s: %s",
                       idx, type(e).__name__, e)
            try:
                r.close(kill=True)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            return None
        with self._lock:
            while len(self._routed) <= idx:
                self._routed.append(0)
            # the new member serves the replicated hot prefix; the
            # partitioned tail keeps its existing owners
            for m in self._hot_models:
                placed = self.placement.get(m)
                if placed is not None and idx not in placed:
                    placed.append(idx)
        # health record BEFORE routing can see it (fleet.add puts it
        # in the shared replicas list every picker iterates)
        self.sentinel.add_replica(r)
        self.fleet.add(r)
        telemetry.counter(events.CTR_FLEET_SCALE_UPS).inc()
        telemetry.event(events.EV_FLEET_REPLICA_SPAWNED,
                        replica=idx, pid=hello.get("pid"),
                        models=sorted(r.models))
        telemetry.event(events.EV_FLEET_SCALE_UP, replica=idx,
                        pid=hello.get("pid"), cause=cause,
                        warm_dir=warm is not None,
                        n_replicas=len(self.replicas), **info)
        telemetry.event(events.EV_FLEET_PLACEMENT,
                        placement=self.placement)
        self.info("scale-up: replica %d (pid %s, %s install dir) — "
                  "fleet now %d", idx, hello.get("pid"),
                  "warm" if warm else "cold", len(self.replicas))
        return r

    def retire_replica(self, cause: str = "manual",
                       drain_timeout: float = 30.0,
                       **info: Any) -> Optional[int]:
        """Scale down by one replica, in the only safe order:

        1. mark it ``retiring`` — routing and the hedge/mirror picks
           exclude it immediately, the monitor stops supervising it;
        2. re-place its EXCLUSIVE models onto a survivor (every
           replica spawns with the full model set, so the survivor
           LRU-loads on first request — a shrunk fleet can never 404
           a tail model);
        3. drain its router-side in-flight queue (the requests it
           already accepted finish normally);
        4. only THEN remove it from supervision and SIGTERM it — the
           hive's graceful-stop path drains its batcher, dumps the
           flight recorder, and exits 14;
        5. pool its install dir for the next scale-up.

        Returns the retired replica's idx, or None when the fleet has
        no retirable member (a lone or all-unhealthy fleet)."""
        with self._lock:
            # the youngest healthy member retires: founding replicas
            # carry the longest stats history (and any CLI pins)
            cands = [r for r in self.replicas
                     if not r.retiring and r.healthy]
            if len(cands) < 2:
                return None
            victim = max(cands, key=lambda r: r.idx)
            victim.retiring = True
        survivors = [r for r in self.replicas
                     if r is not victim and not r.retiring]
        with self._lock:
            replaced = []
            for m, placed in self.placement.items():
                kept = [i for i in placed if i != victim.idx]
                if not kept:
                    # exclusive model: hand it to the least-loaded
                    # live survivor BEFORE any traffic can miss it
                    tgt = min(
                        (r for r in survivors if r.healthy),
                        key=lambda r: (r.inflight, r.idx),
                        default=survivors[0])
                    kept = [tgt.idx]
                    replaced.append((m, tgt.idx))
                self.placement[m] = kept
        if replaced:
            telemetry.event(events.EV_FLEET_PLACEMENT,
                            placement=self.placement)
            self.info("retire: re-placed exclusive models %s off "
                      "replica %d", replaced, victim.idx)
        # drain what it already accepted — new work stopped at step 1
        deadline = time.monotonic() + drain_timeout
        while victim.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        drained = victim.inflight == 0
        self.fleet.remove(victim)
        self.sentinel.remove_replica(victim)
        rc = None
        if victim.client is not None and victim.alive:
            victim.client.sigterm()
            try:
                rc = victim.client.wait(timeout=30.0)
            except Exception:  # noqa: BLE001 — stuck in its drain
                pass
        victim.close(kill=True)   # idempotent reap
        with self._lock:
            self._warm_dirs.append(victim.install_dir)
        telemetry.counter(events.CTR_FLEET_SCALE_DOWNS).inc()
        telemetry.counter(events.CTR_FLEET_RETIRED).inc()
        telemetry.event(events.EV_FLEET_REPLICA_RETIRED,
                        replica=victim.idx, rc=rc, drained=drained,
                        replaced=[m for m, _ in replaced])
        telemetry.event(events.EV_FLEET_SCALE_DOWN,
                        replica=victim.idx, cause=cause, rc=rc,
                        n_replicas=len(self.replicas), **info)
        self.info("scale-down: replica %d retired (drained=%s, "
                  "rc=%s) — fleet now %d", victim.idx, drained, rc,
                  len(self.replicas))
        return victim.idx

    def apply_degradation(self, rung: str, engage: bool,
                          cause: str = "manual",
                          **info: Any) -> None:
        """Flip one ladder rung's lever.  ``learner`` fans the
        suspend/resume op to every live replica; ``hedge`` gates the
        hedged-request path; ``shed_tail`` sheds non-hot models with
        an explicit degraded/overloaded response."""
        if rung == "learner":
            for r in list(self.replicas):
                if not r.healthy or r.client is None or r.retiring:
                    continue
                try:
                    r.client.learner_ctl(engage, timeout=10.0)
                except Exception as e:  # noqa: BLE001 — a replica
                    # mid-respawn just misses the rung; the monitor's
                    # respawn spawns with the learner in default state
                    self.warning("learner_ctl(%s) failed on replica "
                                 "%d: %s", engage, r.idx, e)
        elif rung == "hedge":
            self.hedging_enabled = not engage
        elif rung == "shed_tail":
            self.shed_tail = engage
        else:
            raise ValueError(f"unknown degradation rung {rung!r}")
        telemetry.event(
            events.EV_FLEET_DEGRADE_ENGAGE if engage
            else events.EV_FLEET_DEGRADE_RELEASE,
            rung=rung, cause=cause, **info)

    # -- introspection -------------------------------------------------

    def routed_counts(self) -> List[int]:
        """Requests routed per replica index (request spreading)."""
        with self._lock:
            return list(self._routed)

    def inflight_total(self) -> int:
        return sum(r.inflight for r in list(self.replicas))

    def replica_stats(self, timeout: float = 30.0) \
            -> List[Optional[Dict[str, Any]]]:
        """Each healthy replica's live telemetry snapshot (None for a
        dead slot) — the bench's per-replica recompile audit."""
        out: List[Optional[Dict[str, Any]]] = []
        for r in list(self.replicas):
            if r.healthy and r.client is not None:
                try:
                    out.append(r.client.stats(timeout=timeout))
                    continue
                except (ReplicaDied, TimeoutError):
                    pass
            out.append(None)
        return out

    def fleet_status(self) -> Dict[str, Any]:
        """One JSON-ready view of the fleet (the CLI's op=fleet),
        including each replica's sentinel health row — the operator's
        answer to "why is replica i out of rotation"."""
        return {
            "replicas": [
                {"replica": r.idx, "pid": r.pid,
                 "healthy": r.healthy, "inflight": r.inflight,
                 "routed": self.routed_counts()[r.idx],
                 "deaths": r.deaths,
                 "devices": r.devices,
                 "device_budget": (r.capacity_bytes // r.devices
                                   if r.capacity_bytes else None),
                 "ema_dispatch_ms": round(
                     1000 * r.ema_dispatch_s, 3)
                 if r.ema_dispatch_s else None,
                 "retiring": r.retiring,
                 "sentinel": self.sentinel.status(r)}
                for r in list(self.replicas)],
            "n_replicas": len(self.replicas),
            "hedging_enabled": self.hedging_enabled,
            "shed_tail": self.shed_tail,
            "warm_dirs": len(self._warm_dirs),
            "placement": self.placement,
            "canaries": {c: {"of": p, "fraction": f}
                         for c, (p, f) in self.canaries.items()},
            "slo_p99_ms": self.slo_p99_ms,
            "max_inflight": self.max_inflight,
            "deadline_ms": self.deadline_ms,
            "hedge_rate": round(self.sentinel.hedge_rate(), 4),
        }

    # -- teardown ------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for every in-flight request to resolve."""
        deadline = time.monotonic() + timeout
        while self.inflight_total() > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def close(self, kill: bool = False, reason: Optional[str] = None,
              code: int = 0) -> None:
        if self._closed:
            return
        self._closed = True
        self.sentinel.close()
        self.fleet.close(kill=kill)
        telemetry.event(events.EV_FLEET_SHUTDOWN,
                        routed=self.routed_counts(), reason=reason,
                        code=code)
        telemetry.flush()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- the CLI front end -------------------------------------------------

def parse_mesh(specs: List[str]) -> Any:
    """``--mesh`` specs -> the FleetRouter ``mesh`` argument: a bare
    ``N`` meshes every replica with N devices; ``I=N`` (repeatable)
    meshes only replica I — the mixed 1-device / N-device fleet."""
    if not specs:
        return None
    per: Dict[int, int] = {}
    uniform: Optional[int] = None
    for s in specs:
        idx, eq, n = s.partition("=")
        if eq:
            per[int(idx)] = int(n)
        else:
            uniform = int(s)
    if per and uniform is not None:
        raise ValueError(
            "mix of bare N and I=N --mesh specs; use one form")
    return per if per else uniform


def parse_canary(spec: str) -> Tuple[str, str, Optional[float]]:
    """``CNAME=PRIMARY[:FRACTION]`` -> (cname, primary, fraction)."""
    cname, _, rest = spec.partition("=")
    primary, _, frac_s = rest.partition(":")
    if not cname or not primary:
        raise ValueError(
            f"bad --canary spec {spec!r} (want "
            f"CANARY=PRIMARY[:FRACTION])")
    frac = None
    if frac_s:
        frac = float(frac_s)
    return cname, primary, frac


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu --serve-fleet",
        description="Swarm: SLO-aware fleet router over N Hive "
                    "replicas")
    p.add_argument("replicas", type=int,
                   help="replica count (each is one --serve-models "
                        "subprocess)")
    p.add_argument("models", nargs="+", metavar="NAME=PKG",
                   help="model name = Forge ensemble package path; "
                        "DECLARATION ORDER is the placement hotness "
                        "order")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("--mesh", action="append", default=[],
                   metavar="N|I=N",
                   help="devices per replica: a bare N meshes EVERY "
                        "replica, I=N (repeatable) meshes only "
                        "replica I — the fleet topology becomes "
                        "replicas x mesh and placement follows each "
                        "replica's advertised capacity "
                        "($VELES_SERVE_MESH)")
    p.add_argument("--canary", action="append", default=[],
                   metavar="CNAME=PRIMARY[:FRACTION]",
                   help="register model CNAME as canary-of:PRIMARY, "
                        "mirroring FRACTION of PRIMARY's traffic "
                        "(default $VELES_FLEET_CANARY_FRACTION)")
    p.add_argument("--hot", action="append", default=None,
                   metavar="NAME",
                   help="override the placement hot set (repeatable); "
                        "hot models replicate on every replica")
    p.add_argument("--max-batch", type=int,
                   default=int(knobs.get(knobs.SERVE_MAX_BATCH)))
    p.add_argument("--max-wait-ms", type=float,
                   default=float(knobs.get(knobs.SERVE_MAX_WAIT_MS)))
    p.add_argument("--hbm-budget", type=int, default=0,
                   help="per-replica residency budget override")
    p.add_argument("--slo-p99-ms", type=float,
                   default=float(knobs.get(knobs.FLEET_SLO_P99_MS)),
                   help="admission-control SLO target "
                        "($VELES_FLEET_SLO_P99_MS; 0 disables "
                        "shedding)")
    p.add_argument("--max-inflight", type=int,
                   default=int(knobs.get(knobs.FLEET_MAX_INFLIGHT)),
                   help="per-replica in-flight bound "
                        "($VELES_FLEET_MAX_INFLIGHT)")
    p.add_argument("--deadline-ms", type=float,
                   default=float(knobs.get(knobs.FLEET_DEADLINE_MS)),
                   help="default per-request deadline budget "
                        "($VELES_FLEET_DEADLINE_MS); a request's "
                        "own deadline_ms field overrides it")
    p.add_argument("--heartbeat-every", type=float,
                   default=float(knobs.get(knobs.HEARTBEAT_EVERY)))
    p.add_argument("--metrics-dir", default=None,
                   help="fleet Sightline dir; each replica writes "
                        "into replica-<i>/ under it")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from concurrent.futures import ThreadPoolExecutor

    from veles_tpu.logger import setup_logging

    args = build_parser().parse_args(argv)
    setup_logging(10 if args.verbose else 20)
    if args.replicas < 1:
        print(f"--serve-fleet: replica count must be >= 1 "
              f"(got {args.replicas})", file=sys.stderr)
        return 2
    specs: Dict[str, str] = {}
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not name or not path:
            print(f"--serve-fleet: bad model spec {spec!r} "
                  f"(want NAME=PACKAGE.vpkg)", file=sys.stderr)
            return 2
        if not os.path.isfile(path):
            print(f"--serve-fleet: no such package {path!r}",
                  file=sys.stderr)
            return 2
        specs[name] = path
    canaries: Dict[str, Tuple[str, Optional[float]]] = {}
    try:
        for cspec in args.canary:
            cname, primary, frac = parse_canary(cspec)
            canaries[cname] = (primary, frac)
        router = FleetRouter(
            specs, args.replicas, backend=args.backend,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            hbm_budget=args.hbm_budget or None,
            heartbeat_every=args.heartbeat_every,
            metrics_dir=args.metrics_dir,
            canaries=canaries,
            mesh=parse_mesh(args.mesh),
            placement=PlacementPolicy(
                budget_bytes=args.hbm_budget or None,
                hot=set(args.hot) if args.hot else None),
            slo_p99_ms=args.slo_p99_ms,
            max_inflight=args.max_inflight,
            deadline_ms=args.deadline_ms)
    except (ValueError, RuntimeError) as e:
        print(f"--serve-fleet: {e}", file=sys.stderr)
        return 2

    emit_lock = witness.lock("router.emit")

    def emit(obj: Dict[str, Any]) -> None:
        with emit_lock:
            print(json.dumps(obj), flush=True)

    emit({"ready": True, "pid": os.getpid(),
          "fleet": args.replicas,
          "replica_pids": [r.pid for r in router.replicas],
          "models": router.hello_models,
          "placement": router.placement,
          "canaries": {c: {"of": p, "fraction": f}
                       for c, (p, f) in router.canaries.items()},
          "slo_p99_ms": router.slo_p99_ms,
          "max_inflight": router.max_inflight})
    telemetry.flush()

    stop = {"signal": None}
    stop_event = threading.Event()

    def _on_term(signum, frame) -> None:
        if stop["signal"] is not None:
            os.write(2, b"fleet: second signal - hard exit\n")
            os._exit(EXIT_PREEMPTED)
        stop["signal"] = signum
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except (ValueError, OSError):   # embedded / non-main thread
        pass

    hb_stop = threading.Event()

    def _hb_loop() -> None:
        n = 0
        while not hb_stop.wait(args.heartbeat_every):
            emit({"hb": n, "pid": os.getpid()})
            telemetry.maybe_flush()
            n += 1

    if args.heartbeat_every > 0:
        threading.Thread(target=_hb_loop, daemon=True,
                         name="fleet-heartbeat").start()

    jobs: "queue.Queue[Optional[str]]" = queue.Queue()

    def _read_stdin() -> None:
        for line in sys.stdin:
            jobs.put(line)
        jobs.put(None)   # EOF

    threading.Thread(target=_read_stdin, daemon=True,
                     name="fleet-stdin").start()

    pool = ThreadPoolExecutor(
        max_workers=min(64, 8 * args.replicas),
        thread_name_prefix="fleet-route")

    def handle(line: str) -> bool:
        """One request line; returns False when the loop should end."""
        line = line.strip()
        if not line:
            return True
        try:
            job = json.loads(line)
        except ValueError:
            emit({"error": f"bad request line: {line[:120]!r}"})
            return True
        op = job.get("op")
        if op == "shutdown":
            return False
        if op == "stats":
            emit({"id": job.get("id"), "stats": telemetry.snapshot()})
            return True
        if op == "fleet":
            emit({"id": job.get("id"),
                  "fleet": router.fleet_status()})
            return True
        jid = job.get("id")
        try:
            model = job["model"]
            rows = np.asarray(job["rows"], np.float32)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — a bad request
            emit({"id": jid, "error": f"{type(e).__name__}: {e}"})
            return True

        def _route(jid=jid, model=model, rows=rows,
                   dl=job.get("deadline_ms")) -> None:
            # a client-supplied deadline_ms rides through unchanged —
            # the fleet front end is deadline-transparent
            resp = router.request(model, rows, deadline_ms=dl)
            resp = dict(resp)
            resp["id"] = jid
            emit(resp)

        def _route_done(f, jid=jid) -> None:
            # a routing thread that died past router.request (broken
            # stdout, encode error) must not vanish into the
            # executor: count it, so "answers stopped" has a signal
            err = f.exception()
            if err is not None:
                telemetry.counter(
                    events.CTR_FLEET_REQUEST_ERRORS).inc()
                print(f"fleet: request {jid} route thread died: "
                      f"{type(err).__name__}: {err}",
                      file=sys.stderr)

        pool.submit(_route).add_done_callback(_route_done)
        return True

    rc = 0
    while not stop_event.is_set():
        try:
            line = jobs.get(timeout=0.2)
        except queue.Empty:
            continue
        if line is None:      # stdin closed: the parent went away
            break
        if not handle(line):
            break

    # -- drain: accept what is already on the wire, then let every
    # in-flight routed request resolve before the replicas go down
    if stop_event.is_set():
        time.sleep(0.3)
    n_late = 0
    while True:
        try:
            line = jobs.get_nowait()
        except queue.Empty:
            break
        if line is None:
            continue
        n_late += 1
        handle(line)
    pool.shutdown(wait=True)
    drained = router.drain()
    telemetry.event(events.EV_FLEET_DRAIN, late_requests=n_late,
                    complete=bool(drained))
    reason = None
    if stop["signal"] is not None:
        try:
            reason = signal.Signals(stop["signal"]).name
        except ValueError:
            reason = f"sig{stop['signal']}"
        rc = EXIT_PREEMPTED
        # flight-recorder SIGTERM hook: the router's recent legs and
        # hedges reach disk before the replicas are torn down
        trace.dump("sigterm")
    router.close(reason=reason, code=rc)
    hb_stop.set()
    telemetry.flush()
    if rc:
        # the Phoenix preemption contract: a supervised fleet resumes
        # with warm replica install dirs
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
