"""Gauntlet traffic replay: seeded, deterministic, OPEN-LOOP arrivals.

Every robustness claim so far was measured under CLOSED-LOOP load —
worker threads that wait for each answer before sending the next
request, so a slow server quietly throttles its own load and the p99
flatters itself.  A production day is open-loop: users arrive on
*their* clock.  This module generates that day and drives it:

- :class:`TrafficSpec` + :func:`generate` — a pure function from
  (spec, seed) to an arrival schedule composing three shapes:

  * **diurnal sine**: rate(t) sweeps trough → peak → trough over
    ``period_s`` with ``swing`` = peak/trough (the ≥10x production
    bar), via raised-cosine ``trough + (peak-trough)·(1-cos)/2``;
  * **Poisson bursts**: burst windows are themselves a Poisson
    process (mean gap ``burst_every_s``, length ``burst_len_s``);
    inside a window the instantaneous rate multiplies by
    ``burst_mult`` — flash crowds ON TOP of the curve;
  * **Zipf model mix**: arrival k asks for model rank r with
    probability ∝ 1/r^``zipf_s`` — the hot-prefix / long-tail skew
    that gives shed-tail-before-hot degradation its meaning.

  Arrivals are drawn by thinning a homogeneous Poisson process at
  ``lambda_max = peak_rps·burst_mult``: candidate gaps are
  Exponential(1/lambda_max) and a candidate at t survives with
  probability rate(t)/lambda_max.  Everything — gaps, thinning
  coins, burst placement, model choice, per-request row seeds —
  draws from ONE ``numpy.random.default_rng(seed)`` in a fixed
  order, so the schedule is bit-reproducible.

- :func:`write_trace` / :func:`read_trace` — the schedule as a JSONL
  trace file (header line carries the spec; one line per arrival).
  Serialization is canonical (sorted keys, repr floats), so two
  generations from the same spec produce byte-identical files — the
  determinism pin is ``filecmp`` on the trace, and a logged day
  replays exactly.

- :class:`OpenLoopDriver` — fires each arrival at ``t0 +
  arrival.t`` on the schedule's clock *whether or not earlier
  requests have answered* (a bounded worker pool applies the
  back-pressure a real frontend's socket backlog would), and records
  per-arrival outcomes with latency measured FROM THE SCHEDULED
  ARRIVAL TIME — queueing delay the server caused is charged to the
  server, not silently absorbed by a late send.

No wall-clock leaks into generation; the driver is the only part
that touches real time.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from veles_tpu import events, knobs, snapshotter, telemetry
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger

TRACE_FORMAT = "veles-traffic-v1"


class TrafficSpec:
    """The production day's shape — a value object; every field
    participates in generation, so equal specs + equal seeds mean
    byte-equal traces."""

    FIELDS = ("seed", "duration_s", "peak_rps", "swing", "period_s",
              "burst_every_s", "burst_len_s", "burst_mult", "models",
              "zipf_s")

    def __init__(self, seed: int = 0, duration_s: float = 60.0,
                 peak_rps: float = 60.0, swing: float = 10.0,
                 period_s: Optional[float] = None,
                 burst_every_s: float = 20.0,
                 burst_len_s: float = 3.0, burst_mult: float = 2.0,
                 models: Optional[List[str]] = None,
                 zipf_s: float = 1.1) -> None:
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.peak_rps = float(peak_rps)
        self.swing = max(1.0, float(swing))
        #: one full trough→peak→trough sweep; defaults to the whole
        #: day so a short CI run still sees the full swing
        self.period_s = float(period_s if period_s is not None
                              else duration_s)
        self.burst_every_s = float(burst_every_s)
        self.burst_len_s = float(burst_len_s)
        self.burst_mult = max(1.0, float(burst_mult))
        self.models = list(models or ["default"])
        self.zipf_s = float(zipf_s)

    @classmethod
    def from_knobs(cls, models: List[str],
                   environ=None) -> "TrafficSpec":
        g = lambda k: knobs.get(k, environ=environ)  # noqa: E731
        return cls(seed=g(knobs.TRAFFIC_SEED),
                   duration_s=g(knobs.TRAFFIC_DURATION_S),
                   peak_rps=g(knobs.TRAFFIC_PEAK_RPS),
                   swing=g(knobs.TRAFFIC_SWING),
                   burst_mult=g(knobs.TRAFFIC_BURST_MULT),
                   models=models, zipf_s=g(knobs.TRAFFIC_ZIPF_S))

    def to_dict(self) -> Dict[str, Any]:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrafficSpec":
        return cls(**{f: d[f] for f in cls.FIELDS if f in d})

    # -- the instantaneous arrival rate --------------------------------

    @property
    def trough_rps(self) -> float:
        return self.peak_rps / self.swing

    def diurnal_rate(self, t: float) -> float:
        """rate(t) WITHOUT bursts: raised cosine from trough to peak,
        peaking at period_s/2 (mid-day)."""
        lo = self.trough_rps
        phase = 0.5 * (1.0 - np.cos(
            2.0 * np.pi * (t % self.period_s) / self.period_s))
        return lo + (self.peak_rps - lo) * float(phase)

    def model_weights(self) -> np.ndarray:
        """Zipf popularity over ``models`` in registration order:
        rank 1 is the hot prefix, the rest are the long tail."""
        ranks = np.arange(1, len(self.models) + 1, dtype=np.float64)
        w = 1.0 / ranks ** self.zipf_s
        return w / w.sum()


class Arrival:
    """One scheduled request: fire at ``t`` seconds into the day,
    against ``model``, with rows derived from ``row_seed``."""

    __slots__ = ("i", "t", "model", "row_seed", "burst")

    def __init__(self, i: int, t: float, model: str, row_seed: int,
                 burst: bool) -> None:
        self.i = i
        self.t = t
        self.model = model
        self.row_seed = row_seed
        self.burst = burst

    def to_dict(self) -> Dict[str, Any]:
        return {"i": self.i, "t": self.t, "model": self.model,
                "row_seed": self.row_seed,
                "burst": int(self.burst)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Arrival":
        return cls(int(d["i"]), float(d["t"]), str(d["model"]),
                   int(d["row_seed"]), bool(d.get("burst", 0)))


def _burst_windows(spec: TrafficSpec, rng) -> List[Any]:
    """Poisson-placed burst windows [(start, end), ...] covering the
    day.  Drawn FIRST from the rng so the window layout is independent
    of how many arrivals thinning accepts."""
    wins = []
    if spec.burst_every_s <= 0 or spec.burst_len_s <= 0 \
            or spec.burst_mult <= 1.0:
        return wins
    t = float(rng.exponential(spec.burst_every_s))
    while t < spec.duration_s:
        wins.append((t, min(t + spec.burst_len_s, spec.duration_s)))
        t += spec.burst_len_s + float(
            rng.exponential(spec.burst_every_s))
    return wins


def generate(spec: TrafficSpec) -> List[Arrival]:
    """The whole day as a list of arrivals — pure in (spec, seed)."""
    rng = np.random.default_rng(spec.seed)
    windows = _burst_windows(spec, rng)

    def in_burst(t: float) -> bool:
        return any(a <= t < b for a, b in windows)

    def rate(t: float) -> float:
        r = spec.diurnal_rate(t)
        return r * spec.burst_mult if in_burst(t) else r

    lam_max = spec.peak_rps * spec.burst_mult
    weights = spec.model_weights()
    arrivals: List[Arrival] = []
    t = 0.0
    while True:
        # thinning: candidate gap at lam_max, accept at rate(t)/lam_max
        t += float(rng.exponential(1.0 / lam_max))
        if t >= spec.duration_s:
            break
        accept = float(rng.random())
        if accept >= rate(t) / lam_max:
            continue
        m = int(rng.choice(len(weights), p=weights))
        arrivals.append(Arrival(
            i=len(arrivals), t=t, model=spec.models[m],
            row_seed=int(rng.integers(0, 2 ** 31 - 1)),
            burst=in_burst(t)))
    return arrivals


def _canon(obj: Dict[str, Any]) -> str:
    # canonical JSON: sorted keys, no spaces, repr floats — the
    # byte-equality contract of the determinism pin
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def write_trace(path: str, spec: TrafficSpec,
                arrivals: List[Arrival]) -> None:
    """Serialize the day: header line (format + spec + count), then
    one line per arrival, canonical JSON throughout."""
    with snapshotter.atomic_write(path, "w") as f:
        f.write(_canon({"format": TRACE_FORMAT,
                        "spec": spec.to_dict(),
                        "n": len(arrivals)}) + "\n")
        for a in arrivals:
            f.write(_canon(a.to_dict()) + "\n")


def read_trace(path: str):
    """-> (spec, arrivals); validates the header format and count so a
    torn trace file fails loudly instead of replaying a partial day."""
    with open(path, "r", encoding="utf-8") as f:
        header = json.loads(f.readline())
        if header.get("format") != TRACE_FORMAT:
            raise ValueError(
                f"{path}: not a {TRACE_FORMAT} trace "
                f"(format={header.get('format')!r})")
        spec = TrafficSpec.from_dict(header["spec"])
        arrivals = [Arrival.from_dict(json.loads(line))
                    for line in f if line.strip()]
    if len(arrivals) != header["n"]:
        raise ValueError(
            f"{path}: torn trace — header says {header['n']} "
            f"arrivals, file holds {len(arrivals)}")
    return spec, arrivals


class OpenLoopDriver(Logger):
    """Fire a schedule at a request function on the schedule's clock.

    ``request_fn(arrival) -> response dict`` runs on one of
    ``workers`` pool threads; the scheduler thread NEVER waits for an
    answer before releasing the next arrival.  Every arrival gets
    exactly one outcome record::

        {"i", "t", "model", "burst", "status", "latency_s",
         "queue_delay_s", "response"}

    where ``status`` is ``ok`` / ``shed`` / ``error`` and
    ``latency_s`` counts from the SCHEDULED arrival time (t0 + t) —
    if the pool was saturated and the send went out late, that delay
    is part of the measured latency, exactly as a queued user would
    experience it.
    """

    def __init__(self, request_fn: Callable[[Arrival], Dict[str, Any]],
                 workers: int = 64) -> None:
        self.request_fn = request_fn
        self.workers = workers
        self._lock = witness.lock("traffic.results")
        self.results: List[Dict[str, Any]] = []

    def _classify(self, resp: Dict[str, Any]) -> str:
        if resp.get("overloaded"):
            return "shed"
        if resp.get("error"):
            return "error"
        return "ok" if "probs" in resp or "pred" in resp else "error"

    def run(self, arrivals: List[Arrival],
            stop: Optional[threading.Event] = None) -> List[Dict[str, Any]]:
        from concurrent.futures import ThreadPoolExecutor

        t0 = time.monotonic()
        pool = ThreadPoolExecutor(
            max_workers=self.workers,
            thread_name_prefix="traffic-worker")

        def _fire(a: Arrival, sched: float) -> None:
            start = time.monotonic()
            try:
                resp = self.request_fn(a)
            except Exception as e:  # noqa: BLE001 — the outcome ledger
                resp = {"error": f"{type(e).__name__}: {e}"}
            done = time.monotonic()
            rec = {"i": a.i, "t": a.t, "model": a.model,
                   "burst": a.burst,
                   "status": self._classify(resp),
                   "latency_s": done - sched,
                   "queue_delay_s": start - sched,
                   "response": resp}
            with self._lock:
                self.results.append(rec)

        sent = late = 0
        futures = []
        try:
            for a in arrivals:
                if stop is not None and stop.is_set():
                    break
                sched = t0 + a.t
                delay = sched - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                elif delay < -0.05:
                    # open-loop honesty: we are behind the schedule's
                    # clock (scheduler starvation, not server queueing)
                    late += 1
                futures.append(pool.submit(_fire, a, sched))
                sent += 1
        finally:
            pool.shutdown(wait=True)
            for fut in futures:
                fut.result()  # _fire never raises; surface if it does
        telemetry.counter(events.CTR_TRAFFIC_SENT).inc(sent)
        if late:
            telemetry.counter(events.CTR_TRAFFIC_LATE).inc(late)
        telemetry.event(events.EV_TRAFFIC_DONE, sent=sent, late=late,
                        results=len(self.results))
        with self._lock:
            return sorted(self.results, key=lambda r: r["i"])
