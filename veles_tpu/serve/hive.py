"""The Hive serving process: one chip, many models, sustained QPS.

``python -m veles_tpu --serve-models NAME=PKG.vpkg [NAME=PKG ...]``

Topology is the proven chip-owning evaluator shape
(genetics/worker.py ``--serve``): ONE persistent process acquires the
device at startup, announces itself with a hello line, emits heartbeat
lines from a daemon thread, and speaks JSON lines over stdin/stdout —
so the pool-style supervision and the ``--supervise`` resume recipe
both apply unchanged.  What is new is what the process does between
lines:

- every model is a **Forge ensemble package** (``pack_ensemble`` —
  manifest + workflow entry + members npz): install, rebuild the
  config tree, build the template workflow ONCE, strip its training
  state, and register the pure forward chain + host member params
  with the residency manager;
- requests (``{"id", "model", "rows"}``) route through the model's
  ``EnsembleEvalEngine.submit()`` — the dynamic micro-batching loop
  coalesces concurrent requests into ONE fixed-shape mask-padded
  dispatch, so warm steady state has zero recompiles;
- models stay HBM-resident under the residency budget; the LRU one
  spills to host when a colder request set needs the space;
- SIGTERM drains: in-flight and already-accepted requests finish,
  telemetry flushes, and the process exits ``EXIT_PREEMPTED`` (14) so
  ``--supervise`` restarts it with warm caches.

Protocol lines (stdout; all writes serialized under one lock):

    {"ready": true, "pid", "platform", "backend", "models": {...},
     "max_batch", "max_wait_ms"}                      -- hello
    {"hb": n, "pid"}                                  -- heartbeat
    {"id", "model", "pred": [...], "probs": [[...]],
     "rows_n": n, "crc": c}                           -- response
    {"id", "error": "..."}                            -- failed request
    {"id", "error": "...", "expired": true}           -- past deadline
    {"id", "stats": <telemetry snapshot>}             -- op=stats

Requests (stdin): ``{"id", "model", "rows": [[...], ...]}`` with an
optional ``"deadline_ms"`` (absolute unix-epoch milliseconds — the
batcher drops a request still queued past it instead of computing an
answer nobody is waiting for), ``{"op": "stats", "id"}``,
``{"op": "shutdown"}``.  ``rows_n``/``crc`` are the response-integrity
echo: the row count and the crc32 of the float32 probability payload,
recomputed by the fleet router — a mismatch is an integrity strike
against this replica (Sentinel, veles_tpu/serve/sentinel.py).

With ``--online`` (Evergreen, veles_tpu/online): a request may carry
``"label"`` (per-row ground truth) to feed the learning tap, truth
known only later joins by wire id via ``{"label_of": <id>,
"label": [...]}`` (no response line), and ``{"op": "learn", "id"}``
answers ``{"id", "learn": {model: {state, steps, buffer_rows,
...}}}`` — the learner's per-model introspection row.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import queue
import signal
import sys
import threading
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu import events, faults, knobs, telemetry, trace
from veles_tpu.analysis import witness
from veles_tpu.serve.batcher import DeadlineExpired
from veles_tpu.supervisor import EXIT_PREEMPTED


class _FL:
    """The launcher stand-in ``create_workflow`` expects."""
    workflow = None


def _strip_training_state(w) -> None:
    """Free every device buffer the template workflow's initialize
    uploaded that serving will never touch: the resident dataset and
    the training-side param/optimizer state (the engine re-uploads
    member params stacked).  A multi-model process cannot afford one
    training run's HBM per model."""
    fused = getattr(w, "fused", None)
    if fused is not None and hasattr(fused, "release_device_state"):
        fused.release_device_state()
    ld = getattr(w, "loader", None)
    for vec_name in ("original_data", "original_labels",
                     "original_targets"):
        vec = getattr(ld, vec_name, None)
        if vec is not None and hasattr(vec, "reset"):
            vec.reset()


def load_model_package(name: str, pkg_path: str, device,
                       install_dir: str, pristine: Dict[str, Any]):
    """One Forge ensemble package -> a registered-ready HostedModel.

    Installs (checksum-verified), rebuilds the global config tree from
    ``pristine`` + the package's config files (per-model isolation, the
    worker.py idiom), builds + initializes the template workflow on the
    shared device, strips its training state, and pairs the pure
    forward chain with the npz members."""
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.ensemble.packaging import load_members
    from veles_tpu.forge import ForgePackage
    from veles_tpu.launcher import apply_config_file, \
        load_workflow_module
    from veles_tpu.serve.residency import HostedModel

    manifest = ForgePackage.install(pkg_path, install_dir)
    pkg_root = manifest["root"]
    snap = manifest.get("snapshot")
    if not snap or not snap.endswith(".npz"):
        raise ValueError(
            f"{pkg_path}: serving needs an ENSEMBLE package (members "
            f"npz snapshot via ensemble.packaging.pack_ensemble); this "
            f"one carries {snap!r}")
    members = load_members(os.path.join(pkg_root, snap))

    root.__dict__.clear()
    root.__dict__.update(copy.deepcopy(pristine))
    for cf in manifest.get("configs", []):
        apply_config_file(os.path.join(pkg_root, cf))
    prng.seed_all(int(members[0].get("seed", 1234)))
    mod = load_workflow_module(os.path.join(pkg_root,
                                            manifest["entry"]))
    create = getattr(mod, "create_workflow", None)
    if create is None:
        raise ValueError(
            f"{pkg_path}: entry {manifest['entry']!r} exposes no "
            f"create_workflow(launcher)")
    w = create(_FL())
    w.initialize(device=device)
    try:
        sample_shape = tuple(w.loader.original_data.shape[1:])
    except (AttributeError, RuntimeError):
        sample_shape = None   # streaming loaders: first request pins
    _strip_training_state(w)
    return HostedModel(
        name, w.forwards, [m["params"] for m in members],
        meta={"workflow": w, "version": manifest.get("version"),
              "package": os.path.basename(pkg_path),
              # the package seed keys the online tier's deterministic
              # sample stream (the offline-oracle replay contract)
              "seed": int(members[0].get("seed", 1234))},
        sample_shape=sample_shape)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu --serve-models",
        description="Hive: device-resident multi-model serving with "
                    "dynamic micro-batching")
    p.add_argument("models", nargs="+", metavar="NAME=PKG",
                   help="model name = Forge ensemble package path "
                        "(.vpkg from ensemble.packaging.pack_ensemble)")
    p.add_argument("-b", "--backend", default="auto")
    p.add_argument("--mesh", type=int,
                   default=int(knobs.get(knobs.SERVE_MESH)),
                   help="devices this replica owns ($VELES_SERVE_MESH): "
                        ">1 binds an N-device mesh — residency budgets "
                        "charge per device and an over-budget model "
                        "serves member-sharded-resident instead of "
                        "LRU-spilling ($VELES_SERVE_MESH_SHARD)")
    p.add_argument("--max-batch", type=int,
                   default=int(knobs.get(knobs.SERVE_MAX_BATCH)),
                   help="rows per micro-batch — the ONE fixed dispatch "
                        "shape ($VELES_SERVE_MAX_BATCH)")
    p.add_argument("--max-wait-ms", type=float,
                   default=float(knobs.get(knobs.SERVE_MAX_WAIT_MS)),
                   help="longest a queued request waits for "
                        "co-batchable traffic ($VELES_SERVE_MAX_WAIT_MS)")
    p.add_argument("--hbm-budget", type=int, default=0,
                   help="residency budget override in bytes (default: "
                        "device bytes_limit/2 or "
                        "$VELES_SERVE_HBM_BUDGET)")
    p.add_argument("--heartbeat-every", type=float,
                   default=float(knobs.get(knobs.HEARTBEAT_EVERY)),
                   help="seconds between heartbeat lines "
                        "($VELES_HEARTBEAT_EVERY; 0 disables)")
    p.add_argument("--online", action="store_true",
                   default=bool(knobs.get(knobs.ONLINE)),
                   help="arm the Evergreen online-learning tier: tap "
                        "labeled traffic, fine-tune in serving idle "
                        "gaps, promote HBM-to-HBM through the gate "
                        "(also $VELES_ONLINE; knobs: "
                        "$VELES_ONLINE_TAP_FRAC, "
                        "$VELES_ONLINE_PROMOTE_MARGIN, ...)")
    p.add_argument("--install-dir", default=None,
                   help="package install/staging directory (default: "
                        "a temp dir)")
    p.add_argument("--metrics-dir", default=None,
                   help="arm Sightline persistence (also "
                        "$VELES_METRICS_DIR)")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from veles_tpu.backends import make_device
    from veles_tpu.config import root
    from veles_tpu.logger import setup_logging
    from veles_tpu.serve.residency import ResidencyManager

    args = build_parser().parse_args(argv)
    setup_logging(10 if args.verbose else 20)
    if args.metrics_dir:
        telemetry.configure(args.metrics_dir)
    install_dir = args.install_dir
    if install_dir is None:
        import tempfile
        install_dir = tempfile.mkdtemp(prefix="hive_models_")

    specs: List[tuple] = []
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not name or not path:
            print(f"--serve-models: bad model spec {spec!r} "
                  f"(want NAME=PACKAGE.vpkg)", file=sys.stderr)
            return 2
        if not os.path.isfile(path):
            print(f"--serve-models: no such package {path!r}",
                  file=sys.stderr)
            return 2
        specs.append((name, path))

    if args.mesh and args.mesh > 1:
        # the Prism arm: this replica OWNS an N-device mesh — one
        # MeshJaxDevice through the same make_device seam, so every
        # downstream consumer (residency, engines, batcher) sees a
        # device that happens to replicate rows and shard members
        from veles_tpu.parallel.data_parallel import MeshJaxDevice
        from veles_tpu.parallel.mesh import make_mesh
        try:
            device = MeshJaxDevice(make_mesh(int(args.mesh)))
        except ValueError as e:
            print(f"--serve-models --mesh {args.mesh}: {e}",
                  file=sys.stderr)
            return 2
    else:
        device = make_device(args.backend)
    platform = getattr(device, "platform", device.backend_name)
    if not getattr(device, "is_jax", False):
        print("--serve-models needs a jax device (TPU or XLA:CPU); "
              "-b numpy has no vmapped serving engine",
              file=sys.stderr)
        return 2
    residency = ResidencyManager(
        device, budget_bytes=args.hbm_budget or None,
        max_batch=max(1, args.max_batch),
        max_wait_s=max(0.0, args.max_wait_ms) / 1000.0)
    # this manager IS the process HBM arbiter: the online tier's
    # shadow training and any in-process cohort work charge the same
    # ledger the LRU spill reads
    from veles_tpu.serve.residency import install_process_arbiter
    install_process_arbiter(residency)

    pristine = copy.deepcopy(dict(root.__dict__))
    for name, path in specs:
        model = load_model_package(name, path, device, install_dir,
                                   pristine)
        residency.register(model)
        # admit eagerly in CLI order: the budget may spill the colder
        # ones right back — that IS the steady-state policy at work
        residency.ensure(name)

    emit_lock = witness.lock("hive.emit")

    def emit(obj: Dict[str, Any]) -> None:
        with emit_lock:
            print(json.dumps(obj), flush=True)

    learner = None
    if args.online:
        from veles_tpu.online import OnlineLearner
        learner = OnlineLearner(residency)
        armed = [name for name, _ in specs
                 if learner.arm_model(name)]
        if armed:
            learner.start()
        else:
            learner = None

    hello = {
        "ready": True, "pid": os.getpid(),
        "backend": device.backend_name, "platform": platform,
        "max_batch": residency.max_batch,
        "max_wait_ms": residency.max_wait_s * 1000.0,
        "online": learner is not None,
        # the replica advertises its REAL capacity (devices x
        # per-device budget) so a mixed fleet's placement policy can
        # stop assuming every replica is one chip
        "devices": residency.n_devices,
        "device_budget": residency.budget_bytes,
        "models": {
            m.name: {"members": len(m.member_params),
                     "param_bytes": m.param_bytes,
                     "resident": m.resident,
                     "sharded": bool(m.engine is not None
                                     and m.engine.member_sharded),
                     "version": m.meta.get("version")}
            for m in residency.models.values()},
    }
    telemetry.event(events.EV_SERVE_READY, pid=os.getpid(),
                    platform=platform,
                    models=sorted(residency.models),
                    max_batch=residency.max_batch)
    emit(hello)
    telemetry.flush()

    flap = faults.fire("fleet.replica_flap")
    if flap:
        # Faultline ``fleet.replica_flap``: this replica SIGKILLs
        # itself ``after`` seconds past hello — armed with ``times=*``
        # every respawn inherits the env var and flaps again, the
        # pathological member the respawn backoff and the scale
        # controller's cooldown must absorb without a spawn storm
        import time as _time
        flap_after = float(flap.get("after", 1.0))

        def _flap() -> None:
            _time.sleep(flap_after)
            try:
                trace.dump("flap")
            except Exception:  # noqa: BLE001 — the kill is the point
                pass
            os.kill(os.getpid(), signal.SIGKILL)

        threading.Thread(target=_flap, daemon=True,
                         name="fault-replica-flap").start()

    stop = {"signal": None}
    stop_event = threading.Event()

    def _on_term(signum, frame) -> None:
        # flag only — the main loop owns the drain; a second signal
        # exits immediately (the operator insists)
        if stop["signal"] is not None:
            os.write(2, b"hive: second signal - hard exit\n")
            os._exit(EXIT_PREEMPTED)
        stop["signal"] = signum
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except (ValueError, OSError):   # embedded / non-main thread
        pass

    hb_stop = threading.Event()

    def _hb_loop() -> None:
        n = 0
        while not hb_stop.wait(args.heartbeat_every):
            emit({"hb": n, "pid": os.getpid()})
            telemetry.maybe_flush()
            n += 1

    if args.heartbeat_every > 0:
        threading.Thread(target=_hb_loop, daemon=True,
                         name="hive-heartbeat").start()

    jobs: "queue.Queue[Optional[str]]" = queue.Queue()

    def _read_stdin() -> None:
        for line in sys.stdin:
            jobs.put(line)
        jobs.put(None)   # EOF

    threading.Thread(target=_read_stdin, daemon=True,
                     name="hive-stdin").start()

    def handle(line: str) -> bool:
        """One request line; returns False when the loop should end."""
        line = line.strip()
        if not line:
            return True
        try:
            job = json.loads(line)
        except ValueError:
            emit({"error": f"bad request line: {line[:120]!r}"})
            return True
        op = job.get("op")
        if op == "shutdown":
            return False
        if op == "stats":
            emit({"id": job.get("id"), "stats": telemetry.snapshot()})
            return True
        if op == "learn":
            emit({"id": job.get("id"),
                  "learn": learner.status() if learner else {}})
            return True
        if op in ("learner_suspend", "learner_resume"):
            # the degradation ladder's first rung, fleet-fanned by the
            # router: a no-op ack when no learner is armed
            if learner is not None:
                if op == "learner_suspend":
                    learner.suspend()
                else:
                    learner.resume()
            emit({"id": job.get("id"), "learner_ctl": {
                "online": learner is not None,
                "suspended": bool(learner is not None
                                  and learner.suspended)}})
            return True
        if "label_of" in job:
            # late ground truth joining an earlier tapped request by
            # wire id — fire-and-forget (an orphan only counts)
            if learner is not None:
                learner.tap.label_for(job["label_of"],
                                      job.get("label"))
            return True
        jid = job.get("id")
        telemetry.counter(events.CTR_SERVE_REQUESTS).inc()
        if faults.fire("hive.wedge", model=job.get("model")):
            # gray-failure rehearsal: the request vanishes into a
            # wedged batcher while heartbeats/stats keep flowing — the
            # caller's deadline (never this process) must catch it
            return True
        # the router's trace context off the wire; this process's own
        # admission span parents under the sending leg, and the
        # batcher attributes queue wait vs dispatch to it
        wctx = trace.from_wire(job)
        sctx = wctx.child() if wctx is not None else None
        try:
            with trace.use(sctx):
                model = job["model"]
                rows = np.asarray(job["rows"], np.float32)
                engine = residency.ensure(model)
                fut = engine.submit(rows,
                                    deadline_ms=job.get("deadline_ms"),
                                    ctx=sctx)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — a bad request
            # answers with an error; the process serves on
            telemetry.counter(events.CTR_SERVE_REQUEST_ERRORS).inc()
            emit({"id": jid, "error": f"{type(e).__name__}: {e}"})
            return True
        if sctx is not None and sctx.sampled:
            trace.record("hive.request", ctx=sctx, model=model,
                         rows=int(len(rows)))
        if learner is not None:
            # tapped AFTER admission: only rows the engine accepted
            # (shape-checked, submitted) may enter the replay buffer
            learner.tap.tap(model, jid, rows, job.get("label"),
                            ctx=sctx)

        def _deliver(f, jid=jid, model=model) -> None:
            try:
                probs = f.result()
            except DeadlineExpired as e:
                emit({"id": jid, "error": str(e), "expired": True})
                return
            except BaseException as e:  # noqa: BLE001 — dispatch-side
                emit({"id": jid, "error": f"{type(e).__name__}: {e}"})
                return
            probs32 = np.asarray(probs, np.float32)
            # integrity echo: crc over the CLEAN f32 payload (float32
            # round-trips JSON exactly) — computed BEFORE any injected
            # corruption so hive.garbage_response is detectable
            crc = zlib.crc32(probs32.tobytes())
            payload = probs32
            if faults.fire("hive.garbage_response", model=model):
                payload = faults.rng("hive.garbage_response") \
                    .standard_normal(probs32.shape).astype(np.float32)
            emit({"id": jid, "model": model,
                  "pred": np.argmax(probs32, axis=-1).tolist(),
                  "probs": payload.tolist(),
                  "rows_n": int(len(probs32)), "crc": int(crc)})

        fut.add_done_callback(_deliver)
        return True

    rc = 0
    while not stop_event.is_set():
        try:
            line = jobs.get(timeout=0.2)
        except queue.Empty:
            continue
        if line is None:      # stdin closed: the parent went away
            break
        if not handle(line):
            break

    # -- drain ---------------------------------------------------------
    # accept everything already on the wire (the stdin thread keeps
    # pulling bytes the clients flushed before the signal), then let
    # every model's batcher finish its queue
    if stop_event.is_set():
        stop_event.wait(0.0)
        import time as _time
        _time.sleep(0.3)
    n_late = 0
    while True:
        try:
            line = jobs.get_nowait()
        except queue.Empty:
            break
        if line is None:
            continue
        n_late += 1
        handle(line)
    if learner is not None:
        # the scavenger stops BEFORE the drain: a fine-tune step must
        # not race the batchers' final dispatches for the chip
        learner.stop()
    drained = residency.drain_all()
    telemetry.event(events.EV_SERVE_DRAIN, late_requests=n_late,
                    complete=bool(drained))
    reason = None
    if stop["signal"] is not None:
        try:
            reason = signal.Signals(stop["signal"]).name
        except ValueError:
            reason = f"sig{stop['signal']}"
        rc = EXIT_PREEMPTED
        # the flight recorder's SIGTERM hook: the ring + journal tail
        # land on disk even if the final flush below never completes
        trace.dump("sigterm")
    telemetry.event(events.EV_SERVE_SHUTDOWN, reason=reason, code=rc)
    hb_stop.set()
    telemetry.flush()
    if rc:
        # mirror the Phoenix preemption contract: flush everything and
        # exit 14 so --supervise resumes the serving process
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
