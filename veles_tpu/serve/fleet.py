"""Fleet replica lifecycle: spawn, supervise, respawn.

One **replica** is one ``--serve-models`` Hive subprocess owned
through :class:`~veles_tpu.serve.client.HiveClient` — the proven
topology (hello line, JSONL, heartbeats) unchanged; what is new is
that N of them run side by side and a monitor thread keeps the set
healthy:

- each replica gets a **per-replica metrics child dir**
  (``<metrics_dir>/replica-<i>``) so its Sightline snapshots merge
  into one fleet view (``veles_tpu/obs.py fleet_rows``);
- **death detection** is the pool discipline: reader-thread EOF or a
  heartbeat deadline (any stdout line is proof of life).  A dead
  replica's pending waiters fail immediately with ``ReplicaDied``
  (the router retries them on a peer); the monitor respawns the
  replica with exponential backoff, reusing its install dir so the
  package unpack — and on a real chip the persistent XLA compile
  cache — is warm (the same warm-resume property the exit-14 /
  ``--supervise`` contract gives a single supervised hive);
- **per-dispatch time** is polled from each replica's live stats into
  an EMA — the signal the router's SLO admission control multiplies
  by queue depth.

:class:`PlacementPolicy` decides which models a replica should serve
*preferentially*: every replica is spawned with the full model set on
its command line (so any replica can LRU-load any model as a
fallback), and the placement controls routing affinity — hot models
(the declaration-order prefix that fits every replica's residency
budget, or an explicit set) are replicated across all replicas, the
long tail is partitioned greedily onto the least-filled replica.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Set

from veles_tpu import events, knobs, telemetry
from veles_tpu.analysis import witness
from veles_tpu.logger import Logger
from veles_tpu.serve.client import HiveClient

#: a replica must SURVIVE this long past its hello for a death to
#: reset the crash streak — a flapping member (clean hello, dead
#: seconds later, forever) keeps escalating its respawn backoff
#: toward the 30s cap instead of spawn-storming at the base rate
STABLE_UPTIME_S = 10.0


class PlacementPolicy:
    """Model -> preferred replica set, under a per-replica budget.

    Declaration order is the hotness order (the operator lists the
    traffic-heavy models first, exactly like ``--serve-models`` admits
    eagerly in CLI order): models are replicated on ALL replicas while
    the running total fits every replica's residency budget; the first
    model that would overflow ends the replicated prefix and starts
    the partitioned long tail (greedy least-filled bin).  An explicit
    ``hot`` set overrides the prefix rule.

    Replicas are HETEROGENEOUS since the Prism arm: a ``--mesh N``
    replica advertises ``devices x device_budget`` in its hello, so
    ``assign`` takes an optional per-replica ``capacities`` list and
    places against REAL capacity — the replicated prefix must fit the
    SMALLEST replica, and the greedy tail lands on the replica with
    the most free bytes (not the least absolute fill, which would
    starve a big mesh replica next to an empty small one).
    """

    def __init__(self, budget_bytes: Optional[int] = None,
                 hot: Optional[Set[str]] = None) -> None:
        self.budget_bytes = int(budget_bytes) if budget_bytes \
            else int(knobs.get(knobs.SERVE_HBM_BUDGET))
        self.hot = set(hot) if hot is not None else None

    def assign(self, model_bytes: Dict[str, int],
               n_replicas: int,
               capacities: Optional[List[Optional[int]]] = None
               ) -> Dict[str, List[int]]:
        """{model: [replica indices]} — insertion order of
        ``model_bytes`` is the declaration order.  ``capacities`` is
        the per-replica byte capacity (hello ``devices x
        device_budget``); None entries (or no list) fall back to the
        policy's uniform ``budget_bytes``."""
        n = max(1, int(n_replicas))
        caps = [self.budget_bytes] * n
        if capacities:
            for i, c in enumerate(capacities[:n]):
                if c:
                    caps[i] = int(c)
        fill = [0] * n
        placement: Dict[str, List[int]] = {}
        replicating = True
        for name, nbytes in model_bytes.items():
            nbytes = int(nbytes)
            if self.hot is not None:
                is_hot = name in self.hot
            else:
                is_hot = replicating and all(
                    f + nbytes <= c for f, c in zip(fill, caps))
                if not is_hot:
                    replicating = False
            if is_hot:
                placement[name] = list(range(n))
                fill = [f + nbytes for f in fill]
            else:
                r = max(range(n),
                        key=lambda i: (caps[i] - fill[i], -i))
                placement[name] = [r]
                fill[r] += nbytes
        return placement


class Replica(Logger):
    """One Hive subprocess slot: spawn/respawn + load accounting."""

    def __init__(self, idx: int, models: Dict[str, str],
                 backend: str = "cpu",
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 hbm_budget: Optional[int] = None,
                 heartbeat_every: Optional[float] = None,
                 metrics_dir: Optional[str] = None,
                 cwd: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 mesh: int = 0,
                 start_timeout: float = 180.0,
                 install_dir: Optional[str] = None) -> None:
        self.idx = idx
        self.models = dict(models)
        self.backend = backend
        #: devices this replica's hive owns (--mesh N); 0/1 = one chip
        self.mesh = int(mesh)
        #: capacity advertised by the replica's OWN hello (devices x
        #: per-device budget) — the heterogeneous-placement input;
        #: None until the first spawn
        self.devices = 1
        self.capacity_bytes: Optional[int] = None
        self.max_batch = max_batch
        #: rows one dispatch can drain — the admission estimate's
        #: queue divisor (capacity, NOT the recent fill: dividing by
        #: the fill EMA is procyclical — shedding empties batches,
        #: which inflates the estimate, which sheds more)
        self.batch_capacity = int(max_batch) if max_batch \
            else int(knobs.get(knobs.SERVE_MAX_BATCH))
        self.max_wait_ms = max_wait_ms if max_wait_ms is not None \
            else float(knobs.get(knobs.SERVE_MAX_WAIT_MS))
        self.hbm_budget = hbm_budget
        self.heartbeat_every = heartbeat_every
        self.metrics_dir = os.path.join(metrics_dir,
                                        f"replica-{idx}") \
            if metrics_dir else None
        self.cwd = cwd
        self.env = env
        self.start_timeout = start_timeout
        #: reused across respawns: the package unpack stays warm.  An
        #: elastic fleet passes a pooled dir from a RETIRED replica so
        #: a scale-up inherits the unpack (and on a real chip the
        #: persistent compile cache) instead of paying a cold install
        self.install_dir = install_dir or tempfile.mkdtemp(
            prefix=f"fleet_replica{idx}_")
        self.client: Optional[HiveClient] = None
        self.healthy = False
        #: set by the router's scale-down path BEFORE the drain: a
        #: retiring replica takes no new work, and the monitor neither
        #: respawns it nor counts its orderly exit as a death
        self.retiring = False
        self.deaths = 0
        #: set by mark_dead on the healthy->dead transition; the
        #: monitor consumes it exactly once (death accounting +
        #: backoff scheduling), whoever noticed first
        self.death_kind: Optional[str] = None
        self._consecutive_deaths = 0
        #: monotonic stamp of the last successful hello — a death
        #: within STABLE_UPTIME_S of it continues the crash streak
        #: (the backoff escalates), a longer stint resets it
        self._ready_at: Optional[float] = None
        self.next_respawn_at = 0.0
        self._lock = witness.lock("fleet.replica")
        #: router-side in-flight requests (the bounded router queue)
        self.inflight = 0
        #: EMAs polled from the replica's live stats by the monitor
        self.ema_dispatch_s: Optional[float] = None
        self.ema_batch_rows: Optional[float] = None
        #: observed per-dispatch CYCLE: wall time between dispatches
        #: (batches-counter delta over the poll interval) — window +
        #: compute + CPU contention in one measured number, the
        #: admission estimate's multiplier
        self.ema_cycle_s: Optional[float] = None
        self._dispatch_base = (0, 0.0)   # (count, sum) last poll
        self._rows_base = (0, 0.0)
        self._batches_base: Optional[tuple] = None  # (count, t)

    # -- lifecycle -----------------------------------------------------

    def spawn(self) -> Dict[str, Any]:
        """Start (or restart) the subprocess; returns its hello."""
        self.client = HiveClient(
            self.models, backend=self.backend,
            max_batch=self.max_batch, max_wait_ms=self.max_wait_ms,
            hbm_budget=self.hbm_budget,
            heartbeat_every=self.heartbeat_every,
            metrics_dir=self.metrics_dir,
            install_dir=self.install_dir,
            env=self.env, cwd=self.cwd, mesh=self.mesh,
            start_timeout=self.start_timeout)
        hello = self.client.hello or {}
        with self._lock:
            # capacity comes from the replica's OWN hello — the probed
            # per-device budget on its real device, not a router-side
            # assumption (a mixed fleet's whole point)
            self.devices = int(hello.get("devices") or 1)
            budget = hello.get("device_budget")
            self.capacity_bytes = int(budget) * self.devices \
                if budget else None
            self.healthy = True
            self.death_kind = None
            # NOT a streak reset: a flapping replica (crash shortly
            # after a clean hello, respawn, crash again) must keep
            # escalating its backoff — only surviving STABLE_UPTIME_S
            # clears the streak (judged at death time in _on_death)
            self._ready_at = time.monotonic()
            self.inflight = 0
            self._dispatch_base = (0, 0.0)
            self._rows_base = (0, 0.0)
            self._batches_base = None
        return self.client.hello

    def mark_dead(self, kind: str = "eof") -> bool:
        """Flip healthy off; True only on the transition (the first
        noticer — router request path or fleet monitor — wins, and
        the monitor consumes ``death_kind`` for the accounting)."""
        with self._lock:
            if not self.healthy:
                return False
            self.healthy = False
            self.death_kind = kind
            return True

    @property
    def alive(self) -> bool:
        return self.client is not None and not self.client.dead

    @property
    def pid(self) -> Optional[int]:
        return self.client.pid if self.client is not None else None

    # -- load accounting -----------------------------------------------

    def acquire(self) -> None:
        with self._lock:
            self.inflight += 1

    def release(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)

    def update_from_stats(self, st: Dict[str, Any]) -> None:
        """Fold one live stats snapshot into the dispatch-time,
        batch-fill, and dispatch-cadence EMAs (delta vs the previous
        poll)."""
        hists = st.get("histograms") or {}
        now = time.monotonic()

        def delta(name, base):
            h = hists.get(name) or {}
            c, s = int(h.get("count", 0)), float(h.get("sum", 0.0))
            dc, ds = c - base[0], s - base[1]
            return (c, s), (dc, ds)

        with self._lock:
            self._dispatch_base, (dc, ds) = delta(
                "serve.dispatch_seconds", self._dispatch_base)
            if dc > 0:
                mean = ds / dc
                self.ema_dispatch_s = mean \
                    if self.ema_dispatch_s is None \
                    else 0.5 * self.ema_dispatch_s + 0.5 * mean
            self._rows_base, (rc, rs) = delta(
                "serve.batch_rows", self._rows_base)
            if rc > 0:
                mean = rs / rc
                self.ema_batch_rows = mean \
                    if self.ema_batch_rows is None \
                    else 0.5 * self.ema_batch_rows + 0.5 * mean
            batches = int((st.get("counters") or {})
                          .get("serve.batches", 0))
            if self._batches_base is not None:
                db = batches - self._batches_base[0]
                dt = now - self._batches_base[1]
                if db > 0 and dt > 0:
                    cycle = dt / db
                    self.ema_cycle_s = cycle \
                        if self.ema_cycle_s is None \
                        else 0.5 * self.ema_cycle_s + 0.5 * cycle
            self._batches_base = (batches, now)
        if self.ema_dispatch_s is not None:
            telemetry.gauge(events.GAUGE_FLEET_DISPATCH_EMA_MS).set(
                round(1000.0 * self.ema_dispatch_s, 3))

    def _cycle_s(self) -> float:
        """The observed per-dispatch cycle: measured cadence when the
        monitor has polled one, else the batching window + a small
        dispatch (the idle-replica floor)."""
        cycle = self.ema_cycle_s
        floor = self.max_wait_ms / 1000.0 + 0.002
        return max(cycle, floor) if cycle is not None else floor

    def estimated_wait_ms(self) -> float:
        """Queue depth x observed per-dispatch time: how long a new
        request would queue behind this replica's in-flight work (the
        admission-control estimate).  One dispatch drains up to
        ``batch_capacity`` rows, and the per-dispatch time is the
        MEASURED cadence — window + compute + contention — so CPU
        saturation raises the estimate (negative feedback) while a
        busier, fuller batch does not."""
        with self._lock:
            inflight = self.inflight
        pending_dispatches = inflight / max(1, self.batch_capacity)
        return 1000.0 * pending_dispatches * self._cycle_s()

    def estimated_total_ms(self) -> float:
        """The admission estimate a request's completion would see:
        queued wait + its own dispatch cycle."""
        return self.estimated_wait_ms() + 1000.0 * self._cycle_s()

    def close(self, kill: bool = False) -> None:
        self.mark_dead()
        if self.client is not None:
            self.client.close(kill=kill)


class ReplicaSet(Logger):
    """Spawn N replicas concurrently and keep the set healthy."""

    def __init__(self, replicas: List[Replica],
                 heartbeat_deadline: Optional[float] = None,
                 respawn_backoff: Optional[float] = None,
                 stats_every: float = 0.5) -> None:
        self.replicas = replicas
        self.heartbeat_deadline = float(heartbeat_deadline) \
            if heartbeat_deadline is not None \
            else float(knobs.get(knobs.FLEET_HEARTBEAT_DEADLINE))
        self.respawn_backoff = float(respawn_backoff) \
            if respawn_backoff is not None \
            else float(knobs.get(knobs.FLEET_RESPAWN_BACKOFF))
        self.stats_every = stats_every
        self._closing = False
        self._monitor_thread: Optional[threading.Thread] = None
        self._last_stats_poll = 0.0

    def start(self) -> List[Dict[str, Any]]:
        """Spawn every replica CONCURRENTLY (jax import + package
        install dominate startup; N x serial would multiply it);
        returns their hellos in replica order.  Any spawn failure
        tears the whole set down and raises."""
        from concurrent.futures import ThreadPoolExecutor
        try:
            with ThreadPoolExecutor(len(self.replicas)) as tp:
                hellos = list(tp.map(lambda r: r.spawn(),
                                     self.replicas))
        except BaseException:
            for r in self.replicas:
                try:
                    r.close(kill=True)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            raise
        for r, hello in zip(self.replicas, hellos):
            telemetry.event(events.EV_FLEET_REPLICA_SPAWNED,
                            replica=r.idx, pid=hello.get("pid"),
                            models=sorted(r.models))
        self._update_health_gauge()
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="fleet-monitor")
        self._monitor_thread.start()
        return hellos

    def healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.healthy]

    # -- elastic membership (the autoscaler's two verbs) ---------------

    def add(self, r: Replica) -> None:
        """Adopt an ALREADY-SPAWNED replica into supervision.  The
        caller spawns first (slow: jax import + install) so the
        monitor never sees a half-started member."""
        self.replicas.append(r)
        self._update_health_gauge()

    def remove(self, r: Replica) -> None:
        """Drop a replica from supervision (scale-down: the router
        drains and terminates it AFTER removal, so the monitor cannot
        mistake the orderly SIGTERM exit for a death to respawn)."""
        if r in self.replicas:
            self.replicas.remove(r)
        self._update_health_gauge()

    def _update_health_gauge(self) -> None:
        telemetry.gauge(events.GAUGE_FLEET_REPLICAS_HEALTHY).set(
            len(self.healthy()))

    # -- monitor -------------------------------------------------------

    def _monitor(self) -> None:
        while not self._closing:
            time.sleep(0.25)
            if self._closing:
                return
            now = time.monotonic()
            poll_stats = now - self._last_stats_poll \
                >= self.stats_every
            if poll_stats:
                self._last_stats_poll = now
            # snapshot: the autoscaler adds/removes members while the
            # monitor iterates
            for r in list(self.replicas):
                if self._closing:
                    return
                if r.retiring:
                    # the router's scale-down path owns this replica's
                    # remaining lifecycle (drain -> SIGTERM -> remove)
                    continue
                if r.healthy and not r.alive:
                    r.mark_dead("eof")
                    self._on_death(r)
                elif r.healthy and self.heartbeat_deadline > 0 \
                        and r.client is not None \
                        and now - r.client.last_line_ts \
                        > self.heartbeat_deadline:
                    # silent too long: declare it hung, kill it — the
                    # reader's EOF then fails its pending waiters
                    r.client.kill()
                    r.mark_dead("heartbeat_deadline")
                    self._on_death(r)
                elif not r.healthy and r.death_kind is not None:
                    # the router's request path noticed first (its
                    # waiter got ReplicaDied); account the death here
                    self._on_death(r)
                elif not r.healthy \
                        and now >= r.next_respawn_at:
                    self._respawn(r)
                elif r.healthy and poll_stats:
                    try:
                        r.update_from_stats(r.client.stats(timeout=5))
                    except Exception:  # noqa: BLE001 — a stats miss
                        pass           # must never kill supervision

    def _on_death(self, r: Replica) -> None:
        kind = r.death_kind or "eof"
        r.death_kind = None
        r.deaths += 1
        # the streak is judged by UPTIME, not by hello success: a
        # replica that flaps (clean hello, dead again within
        # STABLE_UPTIME_S) keeps escalating its backoff toward the
        # 30s cap; one that served a stable stint starts over
        uptime = (time.monotonic() - r._ready_at) \
            if r._ready_at is not None else None
        if uptime is not None and uptime >= STABLE_UPTIME_S:
            r._consecutive_deaths = 0
        r._consecutive_deaths += 1
        backoff = min(30.0, self.respawn_backoff
                      * (2 ** (r._consecutive_deaths - 1)))
        r.next_respawn_at = time.monotonic() + backoff
        telemetry.counter(events.CTR_FLEET_REPLICA_DEATHS).inc()
        telemetry.event(events.EV_FLEET_REPLICA_DIED,
                        replica=r.idx, pid=r.pid, kind=kind,
                        rc=getattr(r.client, "exit_rc", None),
                        backoff=round(backoff, 3))
        self._update_health_gauge()
        self.warning("replica %d (pid %s) died (%s); respawn in "
                     "%.2fs", r.idx, r.pid, kind, backoff)
        try:
            r.close(kill=True)   # reap the corpse
        except Exception:  # noqa: BLE001 — already dead
            pass

    def _respawn(self, r: Replica) -> None:
        consecutive = r._consecutive_deaths
        try:
            hello = r.spawn()
        except Exception as e:  # noqa: BLE001 — retry with backoff
            r._consecutive_deaths = max(consecutive, 1) + 1
            backoff = min(30.0, self.respawn_backoff
                          * (2 ** (r._consecutive_deaths - 1)))
            r.next_respawn_at = time.monotonic() + backoff
            self.warning("replica %d respawn failed (%s); next try "
                         "in %.2fs", r.idx, e, backoff)
            return
        telemetry.counter(events.CTR_FLEET_REPLICA_RESPAWNS).inc()
        telemetry.event(events.EV_FLEET_REPLICA_RESPAWNED,
                        replica=r.idx, pid=hello.get("pid"),
                        deaths=r.deaths)
        self._update_health_gauge()
        self.info("replica %d respawned (pid %s, warm install dir)",
                  r.idx, hello.get("pid"))

    # -- teardown ------------------------------------------------------

    def close(self, kill: bool = False) -> None:
        self._closing = True
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        from concurrent.futures import ThreadPoolExecutor
        replicas = list(self.replicas)
        with ThreadPoolExecutor(max(1, len(replicas))) as tp:
            list(tp.map(lambda r: r.close(kill=kill), replicas))
        self._update_health_gauge()
