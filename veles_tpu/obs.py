"""Sightline dir readers/renderers — the importable internals behind
``scripts/obs_report.py`` (the CLI) and ``web_status.py --metrics-dir``
(the live dashboard).

Reads every per-process snapshot (``metrics-*.json``) in a metrics
dir, merges them bucket-wise into ONE aggregate registry (skipping
``*.merged`` files — those were already folded into a parent's
snapshot by ``ChipEvaluatorPool``, and re-adding them would double
count), interleaves every process's journal (``journal-*.jsonl``) into
one timeline, and renders the counter/gauge tables, per-histogram
quantile tables, derived per-engine throughput, and the event
timeline.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

from veles_tpu.telemetry import Registry

#: (label, images counter, seconds counter, unit) rows of the derived
#: throughput table — only pairs present in the merged registry print
THROUGHPUT_ROWS = (
    ("fused train", "fused.train_images", "fused.train_seconds",
     "img/s"),
    ("fused eval", "fused.eval_images", "fused.eval_seconds", "img/s"),
    ("ensemble", "ensemble.member_images", "ensemble.seconds",
     "member-img/s"),
    ("ga", "ga.evaluations", "ga.eval_seconds", "genomes/s"),
    ("serve", "serve.rows", "serve.dispatch_seconds", "rows/s"),
)


def load_dir(metrics_dir: str):
    """(merged Registry, [snapshot paths], [journal paths], [journal
    events sorted by ts]) for a metrics dir."""
    reg = Registry()
    snaps = []
    for path in sorted(glob.glob(os.path.join(metrics_dir,
                                              "metrics-*.json"))):
        if path.endswith(".merged") or path.endswith(".tmp"):
            continue
        try:
            with open(path) as f:
                reg.merge_snapshot(json.load(f))
            snaps.append(path)
        except (OSError, ValueError) as e:
            print(f"obs_report: skipping unreadable {path}: {e}",
                  file=sys.stderr)
    events = []
    journals = sorted(glob.glob(os.path.join(metrics_dir,
                                             "journal-*.jsonl")))
    for path in journals:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue   # torn tail line of a killed process
                    ev["_pid"] = os.path.basename(path).split("-")[-1] \
                        .split(".")[0]
                    events.append(ev)
        except OSError:
            continue
    events.sort(key=lambda e: e.get("ts", 0.0))
    return reg, snaps, journals, events


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e6):
            return f"{v:.3e}"
        return f"{v:,.4f}".rstrip("0").rstrip(".")
    return f"{v:,}"


def render(metrics_dir: str, reg: Registry, snaps, journals, events,
           max_events: int = 40) -> str:
    out = [f"== Sightline report: {metrics_dir} ==",
           f"{len(snaps)} process snapshot(s), {len(journals)} "
           f"journal(s), {len(events)} event(s)", ""]

    counters = {n: c.value for n, c in sorted(reg.counters.items())
                if c.value}
    if counters:
        w = max(len(n) for n in counters)
        out.append("-- counters --")
        out += [f"  {n:<{w}}  {_fmt(v)}" for n, v in counters.items()]
        out.append("")

    gauges = {n: g.value for n, g in sorted(reg.gauges.items())
              if g.value is not None}
    if gauges:
        w = max(len(n) for n in gauges)
        out.append("-- gauges --")
        out += [f"  {n:<{w}}  {_fmt(v)}" for n, v in gauges.items()]
        out.append("")

    hists = {n: h for n, h in sorted(reg.histograms.items())
             if h.count}
    if hists:
        w = max(len(n) for n in hists)
        out.append("-- histograms (p50/p90/p99 from log buckets) --")
        out.append(f"  {'name':<{w}}  {'count':>8} {'mean':>11} "
                   f"{'p50':>11} {'p90':>11} {'p99':>11} {'max':>11}")
        for n, h in hists.items():
            out.append(
                f"  {n:<{w}}  {h.count:>8} {_fmt(h.mean):>11} "
                f"{_fmt(h.quantile(0.5)):>11} "
                f"{_fmt(h.quantile(0.9)):>11} "
                f"{_fmt(h.quantile(0.99)):>11} {_fmt(h.max):>11}")
        out.append("")

    rows = []
    for label, num, den, unit in THROUGHPUT_ROWS:
        n = counters.get(num)
        d = counters.get(den)
        if n and d:
            rows.append(f"  {label}: {_fmt(n)} over "
                        f"{_fmt(d)} engine-s -> "
                        f"{_fmt(n / d)} {unit}")
    if rows:
        out.append("-- derived throughput (per engine-second) --")
        out += rows
        out.append("")

    if events:
        shown = events[-max_events:]
        out.append(f"-- journal timeline (last {len(shown)} of "
                   f"{len(events)}) --")
        for ev in shown:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(ev.get("ts", 0)))
            fields = " ".join(
                f"{k}={_fmt(v) if isinstance(v, (int, float)) else v}"
                for k, v in ev.items()
                if k not in ("ts", "event", "_pid"))
            out.append(f"  {ts} [{ev.get('_pid', '?')}] "
                       f"{ev.get('event', '?')} {fields}".rstrip())
    return "\n".join(out)
