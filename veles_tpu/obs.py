"""Sightline dir readers/renderers — the importable internals behind
``scripts/obs_report.py`` (the CLI) and ``web_status.py --metrics-dir``
(the live dashboard).

Reads every per-process snapshot (``metrics-*.json``) in a metrics
dir, merges them bucket-wise into ONE aggregate registry (skipping
``*.merged`` files — those were already folded into a parent's
snapshot by ``ChipEvaluatorPool``, and re-adding them would double
count), interleaves every process's journal (``journal-*.jsonl``) into
one timeline, and renders the counter/gauge tables, per-histogram
quantile tables, derived per-engine throughput, and the event
timeline.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import sys
import time

from veles_tpu.telemetry import Registry

#: (label, images counter, seconds counter, unit) rows of the derived
#: throughput table — only pairs present in the merged registry print
THROUGHPUT_ROWS = (
    ("fused train", "fused.train_images", "fused.train_seconds",
     "img/s"),
    ("fused eval", "fused.eval_images", "fused.eval_seconds", "img/s"),
    ("ensemble", "ensemble.member_images", "ensemble.seconds",
     "member-img/s"),
    ("ga", "ga.evaluations", "ga.eval_seconds", "genomes/s"),
    ("serve", "serve.rows", "serve.dispatch_seconds", "rows/s"),
    ("online learner", "online.step_rows", "online.step_seconds",
     "rows/s"),
)


#: per-pid ``ts - mono`` offsets within this window of the shared
#: offset are treated as the SAME monotonic epoch (one machine, one
#: boot) — beyond it, the pid keeps its own offset (another machine:
#: its monotonic stamps are not comparable and wall clock is the best
#: cross-machine ordering available)
_SKEW_EPOCH_WINDOW_S = 120.0


def _skew_correct(events) -> None:
    """Stamp each event with ``_t`` — one shared timeline across
    processes.  Raw ``ts`` (wall clock) is cross-process comparable
    but step-prone (NTP slews, coarse rounding, a replica started
    mid-slew); ``mono`` (CLOCK_MONOTONIC) is smooth and, for every
    process on the same machine, counts from the SAME epoch.  So:
    take the median ``ts - mono`` over ALL events as the machine's
    wall<->monotonic offset and order everything by ``offset +
    mono`` — per-process wall-clock disagreement then cancels out
    entirely.  A pid whose own offset sits far from the shared one
    (a multihost peer on another machine, hence another monotonic
    epoch) keeps its own, falling back to wall-clock ordering for
    that hop.  Events without ``mono`` (pre-Flightline journals)
    fall back to raw ``ts``."""
    by_pid = {}
    all_deltas = []
    for ev in events:
        if isinstance(ev.get("mono"), (int, float)) \
                and isinstance(ev.get("ts"), (int, float)):
            d = ev["ts"] - ev["mono"]
            by_pid.setdefault(ev.get("_pid"), []).append(d)
            all_deltas.append(d)
    if not all_deltas:
        for ev in events:
            ev["_t"] = ev.get("ts", 0.0)
        return
    all_deltas.sort()
    shared = all_deltas[len(all_deltas) // 2]
    offsets = {}
    for pid, deltas in by_pid.items():
        deltas.sort()
        own = deltas[len(deltas) // 2]
        offsets[pid] = shared if abs(own - shared) \
            <= _SKEW_EPOCH_WINDOW_S else own
    for ev in events:
        off = offsets.get(ev.get("_pid"))
        if off is not None and isinstance(ev.get("mono"),
                                          (int, float)):
            ev["_t"] = off + ev["mono"]
        else:
            ev["_t"] = ev.get("ts", 0.0)


def load_dir(metrics_dir: str):
    """(merged Registry, [snapshot paths], [journal paths], [journal
    events sorted by skew-corrected time]) for a metrics dir."""
    reg = Registry()
    snaps = []
    for path in sorted(glob.glob(os.path.join(metrics_dir,
                                              "metrics-*.json"))):
        if path.endswith(".merged") or path.endswith(".tmp"):
            continue
        try:
            with open(path) as f:
                reg.merge_snapshot(json.load(f))
            snaps.append(path)
        except (OSError, ValueError) as e:
            print(f"obs_report: skipping unreadable {path}: {e}",
                  file=sys.stderr)
    events = []
    journals = sorted(glob.glob(os.path.join(metrics_dir,
                                             "journal-*.jsonl")))
    for path in journals:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue   # torn tail line of a killed process
                    ev["_pid"] = os.path.basename(path).split("-")[-1] \
                        .split(".")[0]
                    events.append(ev)
        except OSError:
            continue
    _skew_correct(events)
    events.sort(key=lambda e: e["_t"])
    return reg, snaps, journals, events


def fleet_replica_dirs(metrics_dir: str):
    """[(replica index, child dir)] — the per-replica metrics dirs a
    FleetRouter spawns its hives with (``replica-<i>/``)."""
    out = []
    try:
        names = sorted(os.listdir(metrics_dir))
    except OSError:
        return out
    for fn in names:
        if not fn.startswith("replica-"):
            continue
        path = os.path.join(metrics_dir, fn)
        if not os.path.isdir(path):
            continue
        try:
            idx = int(fn.split("-")[-1])
        except ValueError:
            continue
        out.append((idx, path))
    return sorted(out)


def load_tree(metrics_dir: str):
    """(root Registry, [all events]) with every ``replica-<i>/`` child
    journal merged in — the cross-process view trace assembly needs.
    Replica events carry ``_replica``; the whole list re-sorts on the
    skew-corrected ``_t`` stamp, so a hop that happened second never
    renders first just because its process's wall clock was behind."""
    reg, _snaps, _journals, events = load_dir(metrics_dir)
    merged = list(events)
    for idx, path in fleet_replica_dirs(metrics_dir):
        _reg, _s, _j, evs = load_dir(path)
        for ev in evs:
            ev["_replica"] = idx
        merged.extend(evs)
    merged.sort(key=lambda e: e.get("_t", e.get("ts", 0.0)))
    return reg, merged


# -- Flightline trace assembly -----------------------------------------

def assemble_traces(events):
    """{trace_id: [its events, time-ordered]} over a merged event
    list.  Membership is by the ``trace`` journal field (stamped
    explicitly by the trace.* events and implicitly by telemetry's
    provider seam); a ``trace.batch`` event joins EVERY trace it
    links — the coalesced dispatch belongs to each request it
    carried."""
    traces = {}
    for ev in events:
        tid = ev.get("trace")
        if tid:
            traces.setdefault(tid, []).append(ev)
        if ev.get("event") == "trace.batch":
            for link in ev.get("links") or ():
                lt = link.get("trace")
                if lt and lt != tid:
                    traces.setdefault(lt, []).append(ev)
    for evs in traces.values():
        evs.sort(key=lambda e: e.get("_t", e.get("ts", 0.0)))
    return traces


def critical_path(trace_events):
    """Decompose one assembled trace's winning leg into the four
    places its latency can hide: router-side pre-route (failed legs,
    hedge delay), wire + process hop overhead, batcher queue wait,
    and device dispatch.  Returns a dict of second-valued components
    (None when the hop's event is missing) plus leg bookkeeping —
    the "where did my p99 go" answer."""
    root = next((e for e in trace_events
                 if e.get("event") == "trace.request"), None)
    legs = [e for e in trace_events if e.get("event") == "trace.leg"]
    serves = {e.get("parent"): e for e in trace_events
              if e.get("event") == "trace.serve"}
    win = next((e for e in legs if e.get("winner")), None)
    out = {
        "trace": trace_events[0].get("trace")
        if trace_events else None,
        "model": root.get("model") if root else None,
        "outcome": root.get("outcome") if root else None,
        "total_s": root.get("seconds") if root else None,
        "legs": len(legs),
        "hedged": any(e.get("hedge") for e in legs),
        "retried": sum(1 for e in legs
                       if not e.get("hedge")) > 1,
        "replica": win.get("replica") if win else None,
        "pre_route_s": None, "wire_s": None,
        "batch_wait_s": None, "dispatch_s": None,
    }
    if root is not None and win is not None \
            and isinstance(root.get("seconds"), (int, float)) \
            and isinstance(win.get("seconds"), (int, float)):
        out["pre_route_s"] = round(
            max(0.0, root["seconds"] - win["seconds"]), 6)
    serve = serves.get(win.get("span")) if win else None
    if serve is not None:
        out["batch_wait_s"] = serve.get("wait_s")
        out["dispatch_s"] = serve.get("dispatch_s")
        if isinstance(win.get("seconds"), (int, float)) \
                and isinstance(serve.get("total_s"), (int, float)):
            out["wire_s"] = round(
                max(0.0, win["seconds"] - serve["total_s"]), 6)
    return out


def render_trace(trace_events) -> str:
    """One assembled trace as an indented hop timeline + its critical
    path."""
    if not trace_events:
        return "(empty trace)"
    tid = trace_events[0].get("trace")
    t0 = trace_events[0].get("_t", trace_events[0].get("ts", 0.0))
    depth = {}
    for ev in trace_events:
        par = ev.get("parent")
        depth[ev.get("span")] = depth.get(par, 0) + 1 \
            if par is not None else 0
    out = [f"trace {tid}"]
    for ev in trace_events:
        dt_ms = 1000.0 * (ev.get("_t", ev.get("ts", 0.0)) - t0)
        pad = "  " * (1 + depth.get(ev.get("span"), 0))
        who = ev.get("_pid", "?")
        rep = ev.get("_replica")
        if rep is not None:
            who = f"r{rep}/{who}"
        fields = " ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("ts", "mono", "event", "trace", "span",
                         "parent", "_pid", "_t", "_replica", "links")
            and v is not None)
        out.append(f"  +{dt_ms:8.1f}ms {pad}[{who}] "
                   f"{ev.get('event', '?')} {fields}".rstrip())
    cp = critical_path(trace_events)
    parts = [(k, cp[k]) for k in ("pre_route_s", "wire_s",
                                  "batch_wait_s", "dispatch_s")
             if isinstance(cp.get(k), (int, float))]
    if parts:
        hot = max(parts, key=lambda kv: kv[1])
        out.append("  critical path: " + "  ".join(
            f"{k[:-2]}={1000.0 * v:.1f}ms" for k, v in parts)
            + f"  <- {hot[0][:-2]} dominates")
    return "\n".join(out)


def tail_exemplars(reg: "Registry", name: str, q: float = 0.99):
    """Trace ids retained in ``name``'s histogram buckets at or above
    its q-quantile — the jump from "p99 is high" straight to the
    traces that MADE it high.  [(bucket lower edge seconds, trace_id)]
    slowest-last; empty when the histogram has no exemplars (tracing
    off)."""
    from veles_tpu.telemetry import LOG_LO, NBUCKETS, PER_DECADE
    h = reg.histograms.get(name)
    if h is None or not h.count or not h.exemplars:
        return []
    thr = h.quantile(q)
    out = []
    for i, tid in sorted(h.exemplars.items()):
        i = int(i)
        if i <= 0:
            edge, upper = 0.0, 10.0 ** LOG_LO
        elif i >= NBUCKETS + 1:
            edge = 10.0 ** (LOG_LO + NBUCKETS / PER_DECADE)
            upper = math.inf
        else:
            edge = 10.0 ** (LOG_LO + (i - 1) / PER_DECADE)
            upper = 10.0 ** (LOG_LO + i / PER_DECADE)
        # a bucket whose UPPER edge clears the quantile may contain
        # the quantile sample itself — include it, not just the
        # strictly-slower buckets
        if thr is None or upper >= thr:
            out.append((edge, tid))
    return out


def _sentinel_overlay(metrics_dir: str):
    """{replica index: (state, health score, hedge wins)} from the
    ROUTER process's own registry + journal: the sentinel's
    eject/probe/reinstate events carry a ``state`` field, the
    ``fleet.replica.<i>.*`` dynamic family carries score and hedge
    wins — the operator's answer to WHY a replica is out of
    rotation."""
    reg, _snaps, _journals, events = load_dir(metrics_dir)
    states = {}
    for ev in events:
        if ev.get("event") in ("fleet.eject.replica",
                               "fleet.eject.reinstated",
                               "fleet.probe.result") \
                and ev.get("replica") is not None \
                and ev.get("state"):
            states[int(ev["replica"])] = ev["state"]
    out = {}
    for idx in set(states) | {
            int(m.group(1)) for m in
            (re.match(r"fleet\.replica\.(\d+)\.health_score$", n)
             for n in reg.gauges) if m}:
        g = reg.gauges.get(f"fleet.replica.{idx}.health_score")
        c = reg.counters.get(f"fleet.replica.{idx}.hedge_wins")
        out[idx] = (states.get(idx, "healthy"),
                    g.value if g else None,
                    int(c.value) if c else 0)
    return out


def fleet_rows(metrics_dir: str):
    """Per-replica fleet view rows from the merged child snapshots:
    pid (of the NEWEST snapshot — respawns leave older pids behind),
    resident models, live queue depth, lifetime qps (requests over
    the ready->last-flush wall), request p99, and the sentinel health
    overlay (state healthy/ejected/probing, health score, hedge
    wins)."""
    rows = []
    overlay = _sentinel_overlay(metrics_dir)
    for idx, path in fleet_replica_dirs(metrics_dir):
        reg, snaps, _journals, events = load_dir(path)
        pid = None
        last_ts = None
        newest = max(snaps, key=os.path.getmtime, default=None)
        if newest is not None:
            stem = os.path.basename(newest)
            try:
                pid = int(stem.split("-")[-1].split(".")[0])
            except ValueError:
                pid = None
            try:
                with open(newest) as f:
                    last_ts = json.load(f).get("ts")
            except (OSError, ValueError):
                last_ts = None
        readies = [e for e in events
                   if e.get("event") == "serve.ready"]
        requests = reg.counters.get("serve.requests")
        requests = requests.value if requests else 0
        qps = None
        if readies and last_ts and last_ts > readies[0].get("ts", 0):
            qps = requests / (last_ts - readies[0]["ts"])
        g_res = reg.gauges.get("serve.models_resident")
        g_q = reg.gauges.get("serve.queue_depth")
        g_dev = reg.gauges.get("serve.mesh_devices")
        g_pd = reg.gauges.get("serve.resident_bytes_per_device")
        h = reg.histograms.get("serve.request_seconds")
        state, score, hedge_wins = overlay.get(
            idx, ("healthy", None, 0))
        rows.append({
            "replica": idx,
            "pid": pid,
            "spawns": len(readies),
            "models_resident": g_res.value if g_res else None,
            # the Prism topology read: devices this replica's mesh
            # owns (1 off-mesh) and its PER-DEVICE resident charge —
            # the number the per-device HBM budget is spent against
            "devices": int(g_dev.value) if g_dev else 1,
            "resident_mib_per_device": round(
                g_pd.value / (1 << 20), 2) if g_pd else None,
            "queue_depth": g_q.value if g_q else None,
            "requests": requests,
            "qps": round(qps, 1) if qps is not None else None,
            "p99_ms": round(1000 * h.quantile(0.99), 3)
            if h and h.count else None,
            "state": state,
            "health_score": score,
            "hedge_wins": hedge_wins,
            # the replica's HBM arbiter ledger (per-pool resident
            # bytes) — None for pre-Keel replicas
            "arbiter": arbiter_ledger(reg),
        })
    return rows


#: the journal events that mutate the fleet's shape — the rows of the
#: elastic timeline (Gauntlet), each with the field naming its cause
_SCALE_EVENTS = {
    "fleet.scale.up": "scale-up",
    "fleet.scale.down": "scale-down",
    "fleet.replica_retired": "retired",
    "fleet.degrade.engage": "degrade",
    "fleet.degrade.release": "recover",
}


def scale_timeline(metrics_dir: str):
    """The elastic-fleet timeline from the ROUTER journal: one row per
    scale/degradation event — ``{t_s, kind, replica, rung, cause,
    n_replicas}`` with ``t_s`` relative to the fleet's ready event —
    how an operator reads a production day's replica-count curve (and
    WHY each step happened) after the fact."""
    _reg, _snaps, _journals, events = load_dir(metrics_dir)
    t0 = None
    for ev in events:
        if ev.get("event") == "fleet.ready":
            t0 = ev.get("ts")
            break
    rows = []
    for ev in events:
        kind = _SCALE_EVENTS.get(ev.get("event"))
        if not kind:
            continue
        ts = ev.get("ts")
        rows.append({
            "t_s": round(ts - t0, 1)
            if ts is not None and t0 is not None else None,
            "kind": kind,
            "replica": ev.get("replica"),
            "rung": ev.get("rung"),
            "cause": ev.get("cause"),
            "n_replicas": ev.get("n_replicas"),
        })
    return rows


def fleet_model_rows(reg: Registry, events):
    """The per-model traffic split (the canary A/B read) from the
    ROUTER process's registry: one row per ``fleet.model.<name>.*``
    family, annotated with its canary registration from the last
    ``fleet.ready`` journal event."""
    models = {}
    for n, c in reg.counters.items():
        m = re.match(r"fleet\.model\.(.+)\.(requests|errors|shed|"
                     r"mirrored)$", n)
        if m:
            models.setdefault(m.group(1), {})[m.group(2)] = c.value
    canaries = {}
    for ev in events:
        if ev.get("event") == "fleet.ready" and ev.get("canaries"):
            canaries = ev["canaries"]
    total = sum(d.get("requests", 0) for d in models.values()) or 1
    rows = []
    for name in sorted(models):
        d = models[name]
        h = reg.histograms.get(f"fleet.model.{name}.request_seconds")
        c = canaries.get(name)
        rows.append({
            "model": name,
            "requests": d.get("requests", 0),
            "share": round(d.get("requests", 0) / total, 4),
            "errors": d.get("errors", 0),
            "shed": d.get("shed", 0),
            "mirrored": d.get("mirrored", 0),
            "p50_ms": round(1000 * h.quantile(0.5), 3)
            if h and h.count else None,
            "p99_ms": round(1000 * h.quantile(0.99), 3)
            if h and h.count else None,
            "canary_of": c.get("of") if c else None,
            "canary_fraction": c.get("fraction") if c else None,
        })
    return rows


def arbiter_ledger(reg: Registry):
    """The process HBM arbiter's ledger from its gauge family
    (``arbiter.budget_bytes`` / ``arbiter.resident_bytes`` /
    ``arbiter.pool.<pool>.resident_bytes``): budget, total resident,
    utilization, and the per-pool split (serve / train / cohort /
    scratch).  None when the process never charged the arbiter —
    a numpy-backend run, or a pre-Keel snapshot."""
    budget = reg.gauges.get("arbiter.budget_bytes")
    if budget is None or budget.value is None:
        return None
    pools = {}
    for n, g in reg.gauges.items():
        m = re.match(r"arbiter\.pool\.(.+)\.resident_bytes$", n)
        if m and g.value is not None:
            pools[m.group(1)] = int(g.value)
    total = reg.gauges.get("arbiter.resident_bytes")
    resident = int(total.value) if total and total.value is not None \
        else sum(pools.values())
    return {
        "budget_bytes": int(budget.value),
        "resident_bytes": resident,
        "utilization": round(resident / budget.value, 4)
        if budget.value else None,
        "pools": pools,
    }


def render_arbiter(reg: Registry, label: str = "") -> str:
    """The HBM arbiter panel (empty string when the process never
    charged it): one budget line + the per-pool resident split, in
    MiB — the "who is holding HBM" read across training, GA cohorts,
    and serving."""
    led = arbiter_ledger(reg)
    if led is None:
        return ""
    mib = 1 << 20

    def as_mib(v):
        return _fmt(round(v / mib, 2))

    head = "-- hbm arbiter" + (f" ({label})" if label else "") + " --"
    util = f" ({100.0 * led['utilization']:.1f}%)" \
        if led.get("utilization") is not None else ""
    out = [head,
           f"  resident {as_mib(led['resident_bytes'])} MiB of "
           f"{as_mib(led['budget_bytes'])} MiB budget{util}"]
    pools = led["pools"]
    if pools:
        out.append("  " + "  ".join(
            f"{pool}={as_mib(pools[pool])} MiB"
            for pool in sorted(pools, key=lambda p: -pools[p])))
    return "\n".join(out)


def learner_rows(reg: Registry, events):
    """The Evergreen learner panel rows: one per learning model, fed
    by the ``online.model.<name>.*`` gauge family (live buffer fill /
    steps / gate state) and the newest ``online.*`` journal events
    (the gate's last scored round, promotions, rollbacks)."""
    from veles_tpu.online.promote import GATE_STATES
    models = {}
    for n, g in reg.gauges.items():
        m = re.match(r"online\.model\.(.+)\.(buffer_rows|steps|"
                     r"gate_state)$", n)
        if m:
            models.setdefault(m.group(1), {})[m.group(2)] = g.value
    last_gate = {}
    counts = {}
    for ev in events:
        name = ev.get("model")
        if not name:
            continue
        kind = ev.get("event")
        if kind == "online.gate":
            last_gate[name] = ev
        elif kind in ("online.promoted", "online.rollback"):
            key = "promotions" if kind == "online.promoted" \
                else "rollbacks"
            counts.setdefault(name, {"promotions": 0,
                                     "rollbacks": 0})[key] += 1
            if kind == "online.promoted":
                counts[name]["last_promote_ts"] = ev.get("ts")
            else:
                counts[name]["last_rollback_ts"] = ev.get("ts")
    rows = []
    for name in sorted(set(models) | set(last_gate) | set(counts)):
        d = models.get(name, {})
        ev = last_gate.get(name, {})
        c = counts.get(name, {})
        code = d.get("gate_state")
        state = GATE_STATES[int(code)] \
            if code is not None and 0 <= int(code) < len(GATE_STATES) \
            else None
        rows.append({
            "model": name,
            "state": state,
            "buffer_rows": d.get("buffer_rows"),
            "steps": d.get("steps"),
            "shadow_error_pct": ev.get("shadow_error_pct"),
            "incumbent_error_pct": ev.get("incumbent_error_pct"),
            "promotions": c.get("promotions", 0),
            "rollbacks": c.get("rollbacks", 0),
            "last_promote_ts": c.get("last_promote_ts"),
            "last_rollback_ts": c.get("last_rollback_ts"),
        })
    return rows


def render_learner(reg: Registry, events) -> str:
    """The learner panel (empty string when nothing is learning)."""
    rows = learner_rows(reg, events)
    if not rows:
        return ""
    out = ["-- online learner (Evergreen) --",
           f"  {'model':<16} {'state':>11} {'buffer':>7} "
           f"{'steps':>7} {'shadow%':>8} {'incumb%':>8} "
           f"{'promo':>5} {'rollb':>5}"]
    for r in rows:
        out.append(
            f"  {r['model']:<16} {r['state'] or '-':>11} "
            f"{_fmt(r['buffer_rows']):>7} {_fmt(r['steps']):>7} "
            f"{_fmt(r['shadow_error_pct']):>8} "
            f"{_fmt(r['incumbent_error_pct']):>8} "
            f"{_fmt(r['promotions']):>5} {_fmt(r['rollbacks']):>5}")
    return "\n".join(out)


def render_fleet(metrics_dir: str) -> str:
    """The fleet view: per-replica rows + the per-model canary split.
    Empty string when ``metrics_dir`` holds no ``replica-*`` child
    dirs (not a fleet)."""
    rows = fleet_rows(metrics_dir)
    if not rows:
        return ""
    reg, _snaps, _journals, events = load_dir(metrics_dir)
    out = ["-- fleet replicas --",
           f"  {'replica':>7} {'pid':>8} {'spawns':>6} "
           f"{'resident':>8} {'devs':>4} {'MiB/dev':>8} "
           f"{'queue':>6} {'requests':>9} "
           f"{'qps':>9} {'p99 ms':>9} {'state':>8} {'health':>7} "
           f"{'hedge_w':>7}"]
    for r in rows:
        pid = "-" if r["pid"] is None else str(r["pid"])
        out.append(
            f"  {r['replica']:>7} {pid:>8} "
            f"{r['spawns']:>6} {_fmt(r['models_resident']):>8} "
            f"{_fmt(r.get('devices', 1)):>4} "
            f"{_fmt(r.get('resident_mib_per_device')):>8} "
            f"{_fmt(r['queue_depth']):>6} {_fmt(r['requests']):>9} "
            f"{_fmt(r['qps']):>9} {_fmt(r['p99_ms']):>9} "
            f"{r.get('state', 'healthy'):>8} "
            f"{_fmt(r.get('health_score')):>7} "
            f"{_fmt(r.get('hedge_wins', 0)):>7}")
    arb = [(r["replica"], r["arbiter"]) for r in rows
           if r.get("arbiter")]
    if arb:
        out.append("")
        out.append("-- hbm arbiter (per-replica resident MiB) --")
        out.append(f"  {'replica':>7} {'budget':>9} {'resident':>9} "
                   f"{'util%':>6} {'serve':>9} {'train':>9} "
                   f"{'cohort':>9} {'scratch':>9}")
        mib = 1 << 20
        for idx, led in arb:
            pools = led["pools"]
            util = f"{100.0 * led['utilization']:.1f}" \
                if led.get("utilization") is not None else "-"
            out.append(
                f"  {idx:>7} "
                f"{_fmt(round(led['budget_bytes'] / mib, 1)):>9} "
                f"{_fmt(round(led['resident_bytes'] / mib, 2)):>9} "
                f"{util:>6} " + " ".join(
                    f"{_fmt(round(pools.get(p, 0) / mib, 2)):>9}"
                    for p in ("serve", "train", "cohort", "scratch")))
    tl = scale_timeline(metrics_dir)
    if tl:
        out.append("")
        out.append("-- fleet scale timeline --")
        out.append(f"  {'t+s':>8} {'event':<10} {'replica':>7} "
                   f"{'n':>3} {'rung':<10} cause")
        for r in tl:
            out.append(
                f"  {_fmt(r['t_s']):>8} {r['kind']:<10} "
                f"{_fmt(r['replica']):>7} {_fmt(r['n_replicas']):>3} "
                f"{(r['rung'] or '-'):<10} "
                f"{r['cause'] or '-'}".rstrip())
    mrows = fleet_model_rows(reg, events)
    if mrows:
        out.append("")
        out.append("-- fleet per-model split --")
        out.append(f"  {'model':<16} {'requests':>9} {'share':>7} "
                   f"{'p50 ms':>9} {'p99 ms':>9} {'errors':>7} "
                   f"{'shed':>6} {'mirrored':>8}  note")
        for m in mrows:
            note = ""
            if m["canary_of"]:
                note = f"canary-of:{m['canary_of']} " \
                       f"@{m['canary_fraction']}"
            out.append(
                f"  {m['model']:<16} {_fmt(m['requests']):>9} "
                f"{_fmt(m['share']):>7} {_fmt(m['p50_ms']):>9} "
                f"{_fmt(m['p99_ms']):>9} {_fmt(m['errors']):>7} "
                f"{_fmt(m['shed']):>6} {_fmt(m['mirrored']):>8}  "
                f"{note}".rstrip())
    return "\n".join(out)


def render_traces(metrics_dir: str, reg: "Registry",
                  max_rows: int = 8) -> str:
    """The Flightline panel: per-trace critical-path rows for the
    slowest assembled traces, plus the p99 tail exemplars of the
    fleet request histogram.  Empty string when no trace events exist
    (tracing off, or a pre-Flightline dir)."""
    _root_reg, merged = load_tree(metrics_dir)
    traces = assemble_traces(merged)
    if not traces:
        return ""
    rows = []
    for tid, evs in traces.items():
        cp = critical_path(evs)
        if cp.get("total_s") is not None:
            rows.append(cp)
    rows.sort(key=lambda c: c["total_s"], reverse=True)
    out = [f"-- flightline traces ({len(traces)} assembled; "
           f"slowest first) --",
           f"  {'trace':<16} {'model':<12} {'outcome':>7} "
           f"{'total ms':>9} {'preroute':>9} {'wire':>8} "
           f"{'batchwait':>9} {'dispatch':>9} {'legs':>4} "
           f"{'hedged':>6}"]
    for cp in rows[:max_rows]:
        def ms(v):
            return _fmt(round(1000.0 * v, 2)) \
                if isinstance(v, (int, float)) else "-"
        out.append(
            f"  {cp['trace'] or '-':<16} {cp['model'] or '-':<12} "
            f"{cp['outcome'] or '-':>7} {ms(cp['total_s']):>9} "
            f"{ms(cp['pre_route_s']):>9} {ms(cp['wire_s']):>8} "
            f"{ms(cp['batch_wait_s']):>9} {ms(cp['dispatch_s']):>9} "
            f"{cp['legs']:>4} {'y' if cp['hedged'] else '-':>6}")
    ex = tail_exemplars(reg, "fleet.request_seconds")
    if ex:
        out.append("  p99 exemplars (fleet.request_seconds): "
                   + " ".join(t for _e, t in ex[-4:]))
    return "\n".join(out)


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e6):
            return f"{v:.3e}"
        return f"{v:,.4f}".rstrip("0").rstrip(".")
    return f"{v:,}"


def render(metrics_dir: str, reg: Registry, snaps, journals, events,
           max_events: int = 40) -> str:
    out = [f"== Sightline report: {metrics_dir} ==",
           f"{len(snaps)} process snapshot(s), {len(journals)} "
           f"journal(s), {len(events)} event(s)", ""]

    counters = {n: c.value for n, c in sorted(reg.counters.items())
                if c.value}
    if counters:
        w = max(len(n) for n in counters)
        out.append("-- counters --")
        out += [f"  {n:<{w}}  {_fmt(v)}" for n, v in counters.items()]
        out.append("")

    gauges = {n: g.value for n, g in sorted(reg.gauges.items())
              if g.value is not None}
    if gauges:
        w = max(len(n) for n in gauges)
        out.append("-- gauges --")
        out += [f"  {n:<{w}}  {_fmt(v)}" for n, v in gauges.items()]
        out.append("")

    hists = {n: h for n, h in sorted(reg.histograms.items())
             if h.count}
    if hists:
        w = max(len(n) for n in hists)
        out.append("-- histograms (p50/p90/p99 from log buckets) --")
        out.append(f"  {'name':<{w}}  {'count':>8} {'mean':>11} "
                   f"{'p50':>11} {'p90':>11} {'p99':>11} {'max':>11}")
        for n, h in hists.items():
            out.append(
                f"  {n:<{w}}  {h.count:>8} {_fmt(h.mean):>11} "
                f"{_fmt(h.quantile(0.5)):>11} "
                f"{_fmt(h.quantile(0.9)):>11} "
                f"{_fmt(h.quantile(0.99)):>11} {_fmt(h.max):>11}")
        out.append("")

    rows = []
    for label, num, den, unit in THROUGHPUT_ROWS:
        n = counters.get(num)
        d = counters.get(den)
        if n and d:
            rows.append(f"  {label}: {_fmt(n)} over "
                        f"{_fmt(d)} engine-s -> "
                        f"{_fmt(n / d)} {unit}")
    if rows:
        out.append("-- derived throughput (per engine-second) --")
        out += rows
        out.append("")

    arbiter = render_arbiter(reg)
    if arbiter:
        out.append(arbiter)
        out.append("")

    learner = render_learner(reg, events)
    if learner:
        out.append(learner)
        out.append("")

    trace_sec = render_traces(metrics_dir, reg)
    if trace_sec:
        out.append(trace_sec)
        out.append("")

    if events:
        shown = events[-max_events:]
        out.append(f"-- journal timeline (last {len(shown)} of "
                   f"{len(events)}) --")
        for ev in shown:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(ev.get("ts", 0)))
            fields = " ".join(
                f"{k}={_fmt(v) if isinstance(v, (int, float)) else v}"
                for k, v in ev.items()
                if k not in ("ts", "event", "_pid"))
            out.append(f"  {ts} [{ev.get('_pid', '?')}] "
                       f"{ev.get('event', '?')} {fields}".rstrip())
    return "\n".join(out)
