"""Workflow: a container of linked Units with a run loop.

Reference parity: veles/workflow.py — ``Workflow`` holds the unit DAG
between a ``StartPoint`` and an ``EndPoint``; ``initialize()`` recurses
over units in dependency order; ``run()`` drives firings until the end
point fires (Decision's ``complete`` gate steers the loop-back edge vs
the exit edge); a per-unit timing table is reported at the end.

The scheduler here is synchronous and deterministic: a FIFO of ready
units.  The reference used a thread pool, but its compute graph per
iteration is sequential through the chain anyway (SURVEY.md §3.4); on
TPU all heavy work is inside jitted functions dispatched from the
firing unit, and JAX's async dispatch already overlaps host scheduling
with device compute.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Optional

from veles_tpu.mutable import Bool
from veles_tpu.units import Container, Unit


class StartPoint(Unit):
    pass


class EndPoint(Unit):
    def run(self) -> None:
        if self.workflow is not None:
            self.workflow.stopped.set(True)


class Workflow(Container):
    """Container + scheduler for a unit graph."""

    def __init__(self, workflow: Optional[Unit] = None,
                 name: Optional[str] = None, **kwargs: Any) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        self.stopped = Bool(False)
        #: cooperative graceful-stop request (Phoenix): set from a
        #: signal handler / watchdog thread via ``request_stop()``; the
        #: run loop honors it at the next ITERATION BOUNDARY (a unit
        #: flagged ``iteration_boundary`` — the Repeater), where every
        #: unit has finished the iteration and a snapshot taken there
        #: resumes exactly like one written by the Snapshotter.  A
        #: plain bool: assignment is atomic under the GIL and safe from
        #: signal-handler context (no locks, no allocation).
        self.stop_requested = False
        self.device = None
        self._max_firings = kwargs.get("max_firings", 10_000_000)
        #: cumulative wall-clock seconds spent inside run() — unlike
        #: per-unit run_time this brackets the async device work too,
        #: because the loop's metric fetches (Decision) block on it
        self.wall_time = 0.0

    # -- lifecycle -----------------------------------------------------

    def initialize(self, device: Any = None, **kwargs: Any) -> None:
        """Initialize all units in control-dependency order.

        Like the reference, initialization is iterated: a unit whose
        ``initialize`` raises ``AttributeError`` (its linked inputs not
        yet allocated by a predecessor) is retried after the others; a
        full pass with no progress re-raises.
        """
        self.device = device
        order = self._dependency_order()
        pending = [u for u in order if u is not self]
        while pending:
            errors = {}
            still = []
            for u in pending:
                try:
                    u.initialize(device=device, **kwargs)
                    u._initialized = True
                except AttributeError as e:
                    errors[u] = e
                    still.append(u)
            if len(still) == len(pending):
                u, e = next(iter(errors.items()))
                raise RuntimeError(
                    f"initialization deadlock: {len(still)} units cannot "
                    f"initialize; first: {u} -> {e}") from e
            pending = still
        self._initialized = True

    def _dependency_order(self) -> list:
        """Topological-ish order over control edges, ignoring back edges
        (edges from units later discovered — the training loop edge)."""
        seen = []
        seen_set = set()
        queue = collections.deque([self.start_point])
        while queue:
            u = queue.popleft()
            if u in seen_set:
                continue
            seen.append(u)
            seen_set.add(u)
            for succ in sorted(u.links_to, key=lambda x: x.name):
                if succ not in seen_set:
                    queue.append(succ)
        # Units never linked from the start point chain still need init.
        for u in self.units:
            if u not in seen_set:
                seen.append(u)
                seen_set.add(u)
        return seen

    # -- run loop ------------------------------------------------------

    def run(self) -> None:
        """Fire the start point and drive the graph until stopped."""
        if not self._initialized:
            raise RuntimeError("workflow.run() before initialize()")
        t_start = time.perf_counter()
        self.stopped.set(False)
        # a stop requested before (or during a previous) run must not
        # leak into this one — notably a workflow snapshotted by a
        # graceful stop carries stop_requested=True on disk, and the
        # RESUMED run would otherwise stop before its first firing
        self.stop_requested = False
        queue: collections.deque = collections.deque([self.start_point])
        firings = 0
        while queue and not bool(self.stopped):
            unit = queue.popleft()
            if self.stop_requested and \
                    getattr(unit, "iteration_boundary", False):
                # graceful stop lands HERE: the boundary unit (Repeater)
                # is about to open the next iteration, so every unit has
                # completed the current one — identical to the state a
                # fresh run() reaches right before the same firing,
                # which is what makes the final snapshot resume exactly
                break
            if bool(unit.gate_block):
                continue
            unit._reset_trigger_state()
            unit.fire()
            firings += 1
            if firings > self._max_firings:
                raise RuntimeError("workflow exceeded max firings "
                                   "(runaway loop?)")
            if bool(self.stopped):
                break
            for succ in sorted(unit.links_to, key=lambda x: x.name):
                succ.links_from[unit] = True
                if succ.ready and not bool(succ.gate_block):
                    queue.append(succ)
        self.wall_time += time.perf_counter() - t_start
        self.on_workflow_finished()

    def stop(self) -> None:
        self.stopped.set(True)
        for u in self.units:
            u.stop()

    def request_stop(self) -> None:
        """Ask the run loop to stop at the next iteration boundary
        (see ``stop_requested``).  Unlike ``stop()`` this never fires
        unit cleanup hooks and leaves the graph in a resumable state —
        the preemption path (Launcher graceful stop) snapshots right
        after ``run()`` returns."""
        self.stop_requested = True

    def on_workflow_finished(self) -> None:
        self.report_timings()
        # end-of-run telemetry summary (images/sec + achieved MFU
        # gauges): the CLI standalone path finishes run() without ever
        # calling stop(), so the fused runner's summary fires here too
        # (idempotent — the runner gates on its first-firing timestamp)
        fused = getattr(self, "fused", None)
        if fused is not None and \
                hasattr(fused, "_record_telemetry_summary"):
            fused._record_telemetry_summary()

    def report_timings(self) -> None:
        """Per-unit wall-time table (reference: end-of-run unit timing)."""
        rows = [(u.name, u.run_count, u.run_time)
                for u in self.units if u.run_count]
        if not rows:
            return
        total = sum(r[2] for r in rows) or 1e-12
        self.info("unit timing report:")
        for name, count, t in sorted(rows, key=lambda r: -r[2]):
            self.info("  %-28s %8d runs  %9.3fs  %5.1f%%",
                      name, count, t, 100.0 * t / total)

    # -- snapshot support ---------------------------------------------

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        self.__dict__.setdefault("wall_time", 0.0)
        # pre-Phoenix snapshots lack the flag; and NEVER carry a stale
        # request into a resumed run
        self.stop_requested = False

    def generate_data_for_master(self) -> Any:
        return None

    def generate_data_for_slave(self, slave: Any = None) -> Any:
        return None

    def apply_data_from_master(self, data: Any) -> None:
        pass

    def apply_data_from_slave(self, data: Any, slave: Any = None) -> None:
        pass


class Repeater(Unit):
    """Joins the loop-back edge with the initial edge so the loader can
    be triggered either by the start point or by the end of an iteration
    (reference: veles/workflow.py Repeater).

    A Repeater fires when ANY predecessor fires (OR semantics), unlike
    normal units (AND semantics).
    """

    #: the Repeater opens each training iteration, so the instant it is
    #: about to fire is the graceful-stop boundary: loader pointer
    #: advanced, params updated, decision/snapshotter done — a snapshot
    #: here resumes exactly (Workflow.run honors stop_requested on it)
    iteration_boundary = True

    @property
    def ready(self) -> bool:
        if not self.links_from:
            return True
        return any(self.links_from.values())
