"""Mesh construction and sharding helpers.

One flat ``data`` axis covers the reference's capability surface (pure
data parallelism, SURVEY.md §3.4 — no tensor/pipeline parallelism to
reproduce).  Helpers return ``NamedSharding``s so call sites never
touch PartitionSpec spelling.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def make_mesh(n: Optional[int] = None, axis_name: str = "data",
              devices: Optional[Sequence] = None):
    """A 1-D mesh over the first ``n`` visible devices (all by default).

    On a multi-host run ``jax.devices()`` already enumerates every chip
    in the slice, so the same call builds the global mesh.
    """
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if n is None:
        n = len(devs)
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices for the mesh, only {len(devs)} visible "
            f"(tests simulate with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return jax.sharding.Mesh(np.array(devs[:n]), (axis_name,))


def replicated_sharding(mesh):
    """Every device holds the full array (params, dataset, scalars)."""
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def batch_sharding(mesh):
    """Leading axis split across the data axis (minibatch rows)."""
    import jax

    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
