"""Mesh construction and sharding helpers — the ONE sharding seam.

One flat ``data`` axis covers the reference's capability surface (pure
data parallelism, SURVEY.md §3.4 — no tensor/pipeline parallelism to
reproduce).  Helpers return ``NamedSharding``s so call sites never
touch PartitionSpec spelling.

Three placements cover every engine (Lattice, ROADMAP items 2+4):

- **replicated** (:func:`replicated_sharding`): every device holds the
  full array — params, scalars, and datasets small enough to copy;
- **row-sharded** (:func:`row_sharding`, :func:`put_row_sharded`):
  each device holds 1/N of the leading-axis rows — the sharded
  RESIDENCY placement, so dataset HBM capacity scales with the mesh
  instead of multiplying by it;
- **member-sharded** (:func:`member_sharding`): the stacked member
  axis of a vmapped population/ensemble split 1/N per device — P/N
  members per device, so cohort capacity scales with the mesh.

Row- and member-sharding are the same PartitionSpec (leading axis over
``data``); the two names exist because call sites mean different
things by the leading axis and the seam should read as placement
intent, not spelling.  Placement goes through
``jax.make_array_from_callback`` so the same helpers work on a
single-process virtual mesh (tests, ``dryrun_multichip``) and a
multi-process/multi-host mesh (each process materializes only its
addressable shards).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(n: Optional[int] = None, axis_name: str = "data",
              devices: Optional[Sequence] = None):
    """A 1-D mesh over the first ``n`` visible devices (all by default).

    On a multi-host run ``jax.devices()`` already enumerates every chip
    in the slice, so the same call builds the global mesh.
    """
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if n is None:
        n = len(devs)
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices for the mesh, only {len(devs)} visible "
            f"(tests simulate with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return jax.sharding.Mesh(np.array(devs[:n]), (axis_name,))


def replicated_sharding(mesh):
    """Every device holds the full array (params, dataset, scalars)."""
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def batch_sharding(mesh):
    """Leading axis split across the data axis (minibatch rows)."""
    import jax

    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))


def row_sharding(mesh):
    """Dataset rows split 1/N per device — the sharded-residency
    placement (each device's HBM holds ``padded_rows(R, N) / N``
    rows).  Same spec as :func:`batch_sharding`; named for intent."""
    return batch_sharding(mesh)


def member_sharding(mesh):
    """A stacked member axis split P/N per device — the population /
    ensemble capacity placement (members are embarrassingly parallel,
    so the partitioner moves no data between devices inside the
    vmapped body)."""
    return batch_sharding(mesh)


def shard_mode(value: str) -> str:
    """Normalize a ``VELES_MESH_SHARD_*`` knob/ctor value to one of
    ``"never"`` / ``"auto"`` / ``"always"``."""
    v = str(value).strip().lower()
    if v in ("0", "never", "false", "no", "off"):
        return "never"
    if v in ("1", "always", "true", "yes", "on"):
        return "always"
    return "auto"


def padded_rows(n_rows: int, n_devices: int) -> int:
    """Leading-axis length after padding to a whole per-device tile
    (the mask-tail convention: padded rows exist only as placement
    filler — loader indices never reference them)."""
    return -(-int(n_rows) // int(n_devices)) * int(n_devices)


def put_along(mesh, array: np.ndarray, spec):
    """Place a host array on the mesh under ``spec``, single- or
    multi-process: ``make_array_from_callback`` asks each process for
    its addressable shards only, so the same call works on a virtual
    CPU mesh and a real multi-host slice (where a plain
    ``device_put`` to non-addressable devices cannot)."""
    import jax

    array = np.ascontiguousarray(array)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        array.shape, sharding, lambda idx: array[idx])


def put_member_sharded(mesh, array: np.ndarray):
    """Upload a stacked member-axis array split P/N per device — the
    ensemble/population capacity placement.  The caller has already
    padded the member axis to a multiple of N (``batching.pad_members``
    or a repeated member), so every device holds a whole tile."""
    import jax

    if len(array) % int(mesh.devices.size):
        raise ValueError(
            f"member axis {len(array)} not a multiple of the "
            f"{int(mesh.devices.size)}-device mesh — pad members first")
    return put_along(
        mesh, array, jax.sharding.PartitionSpec(mesh.axis_names[0]))


def put_row_sharded(mesh, array: np.ndarray) -> Tuple[object, int]:
    """Upload ``array`` with its leading axis row-sharded 1/N per
    device, zero-padding the tail to a whole per-device tile.
    Returns ``(jax array of padded_rows(R, N) rows, R)`` — callers
    keep the real row count; consumers must never index past it."""
    import jax

    n = int(mesh.devices.size)
    n_real = len(array)
    pad = padded_rows(n_real, n) - n_real
    if pad:
        array = np.concatenate(
            [array, np.zeros((pad,) + array.shape[1:], array.dtype)])
    return put_along(
        mesh, array,
        jax.sharding.PartitionSpec(mesh.axis_names[0])), n_real
