"""DataParallel: install SPMD data parallelism on a StandardWorkflow.

Replaces the reference's master--slave gradient aggregation
(veles/server.py: apply_data_from_slave summing weight diffs into
canonical weights) with a single-controller sharded jit: the fused
step's minibatch ``indices``/``mask`` are sharded over the mesh's
``data`` axis, parameters stay replicated, and XLA inserts the gradient
allreduce over ICI.  Semantics are synchronous SGD on the GLOBAL
minibatch — numerically the same training trajectory as the
single-device fused step (the tests assert this on a virtual CPU mesh).
"""

from __future__ import annotations

from typing import Any, Optional

from veles_tpu.backends import JaxDevice
from veles_tpu.logger import Logger
from veles_tpu.parallel.mesh import make_mesh, replicated_sharding


class MeshJaxDevice(JaxDevice):
    """A JaxDevice whose buffers live on a mesh.

    ``put`` uploads host arrays with a fully-replicated NamedSharding so
    Vectors initialized through the normal ``Vector.initialize(device)``
    path are immediately consumable by the sharded step without a
    resharding transfer.  ``put_sharded`` is the capacity placement:
    the leading axis split 1/N per device (row-sharded residency,
    member-sharded cohorts).

    Transfer/residency accounting is PER-DEVICE-HONEST: a replicated
    put physically lands one copy on EVERY device, so it charges
    ``nbytes * n_devices`` against ``h2d_bytes``; a sharded put lands
    ``total/N`` per device and charges the padded total once.  (The
    original accounting charged replicated uploads at 1x — an 8-device
    mesh looked as cheap as one chip while burning 8x HBM.)
    """

    backend_name = "mesh"

    def __init__(self, mesh, compute_dtype: Any = None) -> None:
        import jax

        self.mesh = mesh
        self._repl = replicated_sharding(mesh)
        self._zeros_fn = None
        self._zeros_sharded_fn = None
        platform = mesh.devices.flat[0].platform
        super().__init__(platform=platform, compute_dtype=compute_dtype)
        self._jax = jax

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    def put(self, array) -> Any:
        import numpy as np
        # dtype-preserving like JaxDevice.put: a quantized loader's
        # uint8 dataset replicates at 1 byte/element per device, and
        # the sharded streaming path ships uint8 superstep batches
        # (each device receives only its slice of every minibatch)
        arr = np.array(array, copy=True)
        # replicated = one physical copy PER device
        self.h2d_bytes += arr.nbytes * self.n_devices
        from veles_tpu.engine import core as engine_core
        return engine_core.put(arr, self._repl)

    def put_sharded(self, array) -> Any:
        """Upload with the leading axis split 1/N per device (rows
        zero-padded to a whole per-device tile).  Total HBM across the
        mesh is the padded array ONCE — per-device cost total/N — and
        that is what ``h2d_bytes`` charges."""
        import numpy as np

        from veles_tpu.parallel import mesh as mesh_helpers
        arr = np.asarray(array)
        buf, _ = mesh_helpers.put_row_sharded(self.mesh, arr)
        self.h2d_bytes += int(buf.nbytes)
        return buf

    def zeros(self, shape, dtype=None, sharded: bool = False) -> Any:
        import numpy as np
        if self._zeros_fn is None:
            import jax.numpy as jnp
            from veles_tpu.parallel.mesh import row_sharding
            # one jitted fn per placement with static (shape, dtype):
            # momentum allocation calls this once per parameter and a
            # fresh lambda per call would defeat jit's cache
            self._zeros_fn = self._jax.jit(
                lambda shape, dtype: jnp.zeros(shape, dtype),
                static_argnums=(0, 1), out_shardings=self._repl)
            self._zeros_sharded_fn = self._jax.jit(
                lambda shape, dtype: jnp.zeros(shape, dtype),
                static_argnums=(0, 1),
                out_shardings=row_sharding(self.mesh))
        dtype = np.dtype(dtype if dtype is not None else np.float32)
        if isinstance(shape, (int, np.integer)):
            shape = (shape,)
        fn = self._zeros_sharded_fn if sharded else self._zeros_fn
        return fn(tuple(int(s) for s in shape), dtype)

    def __repr__(self) -> str:
        n = self.mesh.devices.size
        return f"<MeshJaxDevice {n}x{self.platform} axes={self.mesh.axis_names}>"


class DataParallel(Logger):
    """Wires a mesh into a StandardWorkflow's fused step.

    Usage (what Launcher does for ``--dp=N``)::

        dp = DataParallel(workflow, n)
        device = dp.install()          # BEFORE workflow.initialize
        workflow.initialize(device=device)

    After ``install()`` the workflow's ``FusedStepRunner`` jits its
    train/eval steps with mesh shardings; everything else (Decision,
    Snapshotter, plotters) is unchanged — they observe replicated
    Vectors exactly as in the single-device run.
    """

    def __init__(self, workflow, dp: Optional[int] = None,
                 axis_name: str = "data", mesh=None, devices=None,
                 compute_dtype: Any = None) -> None:
        self.workflow = workflow
        self.mesh = mesh if mesh is not None \
            else make_mesh(dp, axis_name, devices=devices)
        self.device = MeshJaxDevice(self.mesh, compute_dtype=compute_dtype)

    @property
    def num_devices(self) -> int:
        return int(self.mesh.devices.size)

    def install(self) -> MeshJaxDevice:
        fused = getattr(self.workflow, "fused", None)
        if fused is None:
            raise ValueError(
                "DataParallel needs a StandardWorkflow with a fused step "
                "(the numpy/eager path has no sharded execution)")
        n = self.num_devices
        loader = self.workflow.loader
        mb = loader.minibatch_size
        if mb % n:
            raise ValueError(
                f"minibatch_size {mb} not divisible by mesh size {n}")
        fused.mesh = self.mesh
        self.info("data parallel over %d devices (%s), global minibatch "
                  "%d -> %d per device", n, self.mesh.axis_names[0], mb,
                  mb // n)
        return self.device
