"""Distributed execution: SPMD data parallelism over a device mesh.

Reference parity: the reference's ONLY parallelism strategy is
master--slave data parallelism with centralized gradient aggregation
over ZeroMQ (veles/server.py, veles/client.py, SURVEY.md §3.4).  The
TPU-native replacement (the BASELINE.json north star, verbatim) is an
**ICI allreduce**: the fused training step is jitted over a
``jax.sharding.Mesh`` with the minibatch sharded along a ``data`` axis
and parameters replicated; XLA's SPMD partitioner inserts the gradient
``psum`` automatically because the batch reduction crosses the sharded
axis.  Multi-host runs extend the same mesh over DCN via
``jax.distributed.initialize`` (Launcher ``--multihost``).

The zmq master--slave protocol survives as a DCN-only compat path for
heterogeneous clusters (veles_tpu/server.py, veles_tpu/client.py).
"""

from veles_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                     member_sharding, padded_rows,
                                     put_along, put_row_sharded,
                                     replicated_sharding, row_sharding,
                                     shard_mode)
from veles_tpu.parallel.data_parallel import DataParallel, MeshJaxDevice

__all__ = ["make_mesh", "batch_sharding", "replicated_sharding",
           "row_sharding", "member_sharding", "padded_rows",
           "put_along", "put_row_sharded", "shard_mode",
           "DataParallel", "MeshJaxDevice"]
