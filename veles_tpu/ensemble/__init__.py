"""Ensemble training and prediction.

Reference parity: veles/ensemble/ — train N instances of a workflow
(different seeds), then aggregate member predictions (SURVEY.md §3.1
Ensemble).  Members train sequentially in-process (one chip); their
trained parameters are kept as host pytrees so prediction runs without
keeping N live workflows.
"""

from veles_tpu.ensemble.core import EnsemblePredictor, EnsembleTrainer
from veles_tpu.ensemble.packaging import (load_members,
                                          load_packed_ensemble,
                                          normalize_npz_path,
                                          pack_ensemble, save_members)
from veles_tpu.ops.fused import EnsembleEvalEngine

__all__ = ["EnsembleTrainer", "EnsemblePredictor",
           "EnsembleEvalEngine", "save_members", "load_members",
           "pack_ensemble", "load_packed_ensemble",
           "normalize_npz_path"]
