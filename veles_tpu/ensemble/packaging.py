"""Ensemble <-> Forge coupling: persist trained members and ship them
as a Forge package.

Reference parity: upstream couples its ensembles to the model
marketplace — a trained ensemble is a publishable artifact, not a
process-lifetime object (SURVEY.md §3.1 Ensemble / Forge rows; the
reference mount is empty, so the coupling shape is reconstructed from
the survey).  Here the trained member parameters are serialized to one
compressed ``.npz`` (arrays + a JSON metadata record) that rides a
standard Forge package as its ``snapshot`` member, so the whole
existing pipeline — pack, publish, store listing, fetch, checksum
verify, install — works on ensembles unchanged.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu.forge import ForgePackage

_SEP = "|"
_META = "__meta__"


def normalize_npz_path(path: str) -> str:
    """The real on-disk path of an npz artifact: numpy's ``savez``
    appends ``.npz`` when missing — every save AND load site must
    apply the same normalization or a suffix-less path trains fine
    and then fails to load under the identical flag value."""
    return path if path.endswith(".npz") else path + ".npz"


def save_members(path: str, members: List[Dict[str, Any]]) -> str:
    """Serialize ``EnsembleTrainer.members`` to one compressed npz:
    ``m<i>|<forward>|<param>`` arrays plus a JSON metadata record
    (seed, valid_error, forward_names, GA values).

    Both name components are validated for the ``|`` separator HERE,
    at save time — a bad name must fail before the artifact is
    published, not when a consumer later calls ``load_members``."""
    if not members:
        raise ValueError("empty ensemble")
    arrays: Dict[str, np.ndarray] = {}
    meta = []
    for i, m in enumerate(members):
        meta.append({"seed": m["seed"],
                     "valid_error": m["valid_error"],
                     "forward_names": m["forward_names"],
                     "values": m.get("values")})
        for fname, p in m["params"].items():
            if _SEP in fname:
                raise ValueError(f"forward name {fname!r} contains "
                                 f"{_SEP!r}")
            for pname, arr in p.items():
                if _SEP in pname:
                    raise ValueError(f"param name {pname!r} (forward "
                                     f"{fname!r}) contains {_SEP!r}")
                arrays[f"m{i}{_SEP}{fname}{_SEP}{pname}"] = \
                    np.asarray(arr)
    arrays[_META] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8).copy()
    np.savez_compressed(path, **arrays)
    return normalize_npz_path(path)


def load_members(path: str) -> List[Dict[str, Any]]:
    """Inverse of :func:`save_members` — returns member dicts directly
    consumable by ``EnsemblePredictor``."""
    with np.load(path) as z:
        meta = json.loads(bytes(z[_META]))
        members: List[Dict[str, Any]] = []
        for i, md in enumerate(meta):
            # weightless forwards (pooling/LRN/dropout) serialize no
            # arrays but the predictor indexes params[f.name] for
            # EVERY forward — seed each name with an empty dict
            params: Dict[str, Dict[str, np.ndarray]] = {
                fn: {} for fn in md.get("forward_names", [])}
            prefix = f"m{i}{_SEP}"
            for key in z.files:
                if key.startswith(prefix):
                    # maxsplit guards legacy artifacts written before
                    # save-time validation covered param names
                    _, fname, pname = key.split(_SEP, 2)
                    params.setdefault(fname, {})[pname] = z[key]
            members.append(dict(md, params=params))
    return members


def pack_ensemble(out_path: str, name: str,
                  members: List[Dict[str, Any]],
                  workflow_file: str,
                  config_files: Optional[List[str]] = None,
                  version: str = "1.0.0", author: str = "",
                  description: str = "") -> str:
    """Package a trained ensemble for Forge: the members npz becomes
    the package snapshot, ``workflow_file`` the runnable entry."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        npz = os.path.join(tmp, f"{name}_members.npz")
        save_members(npz, members)
        return ForgePackage.pack(
            out_path, name, workflow_file,
            config_files=config_files, snapshot=npz,
            version=version, author=author,
            description=description or
            f"ensemble of {len(members)} members")


def load_packed_ensemble(pkg_path: str, dest_dir: str,
                         verify: bool = True) -> List[Dict[str, Any]]:
    """Install a fetched ensemble package (checksum-verified) and load
    its members."""
    manifest = ForgePackage.install(pkg_path, dest_dir, verify=verify)
    snap = manifest.get("snapshot")
    if not snap:
        raise ValueError(f"{pkg_path}: package has no ensemble "
                         f"snapshot member")
    return load_members(os.path.join(manifest["root"], snap))
