"""Ensemble core: sequential member training + averaged prediction."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu import prng
from veles_tpu.logger import Logger


class EnsembleTrainer(Logger):
    """Trains N members of ``workflow_factory() -> StandardWorkflow``
    with per-member seeds; records each member's trained params and
    validation error.

    With ``member_values`` (a list of hyperparameter dicts — e.g. the
    GA's top-K genomes via :meth:`from_ga`), the factory is called as
    ``workflow_factory(values)`` per member, so members differ by
    HYPERPARAMETERS on top of seeds.  Values must not change the
    architecture (layer shapes): the averaged predictor swaps member
    params through one shared forward chain."""

    def __init__(self, workflow_factory: Callable[..., Any],
                 device_factory: Callable[[], Any],
                 n_members: int = 4,
                 base_seed: int = 1234,
                 member_values: Optional[List[Dict[str, Any]]] = None
                 ) -> None:
        self.workflow_factory = workflow_factory
        self.device_factory = device_factory
        self.member_values = member_values
        self.n_members = n_members if member_values is None \
            else len(member_values)
        self.base_seed = base_seed
        #: [{"params": pytree, "valid_error": float, "seed": int,
        #:   "values": hyperparams-or-None}]
        self.members: List[Dict[str, Any]] = []

    @classmethod
    def from_ga(cls, optimizer, workflow_factory: Callable[..., Any],
                device_factory: Callable[[], Any], k: int = 4,
                base_seed: int = 1234) -> "EnsembleTrainer":
        """Seed the ensemble from a finished GA's final-generation
        top-K genomes (reference coupling: upstream builds ensembles
        from the tuner's best individuals — SURVEY.md §3.1 Ensemble).
        ``optimizer`` is a ``genetics.GeneticOptimizer`` whose
        ``run()`` has completed; ``workflow_factory(values)`` builds a
        member with that genome's hyperparameters."""
        if not getattr(optimizer, "history", None):
            raise ValueError(
                "run the GA first — optimizer.history is empty")
        top = optimizer.history[-1][:max(1, k)]   # best-first
        return cls(workflow_factory, device_factory,
                   base_seed=base_seed,
                   member_values=[dict(v) for _, v in top])

    def train(self) -> List[Dict[str, Any]]:
        for i in range(self.n_members):
            seed = self.base_seed + 7919 * i
            prng.seed_all(seed)
            values = self.member_values[i] \
                if self.member_values is not None else None
            w = self.workflow_factory(values) if values is not None \
                else self.workflow_factory()
            w.initialize(device=self.device_factory())
            w.run()
            params = self._trained_params(w)
            err = w.decision.epoch_error_pct[1]
            self.members.append({"params": params, "valid_error": err,
                                 "seed": seed, "values": values,
                                 "forward_names": [f.name
                                                   for f in w.forwards]})
            self.info("member %d/%d (seed %d%s): valid error %.2f%%",
                      i + 1, self.n_members, seed,
                      f", {values}" if values else "", err)
        return self.members

    @staticmethod
    def _trained_params(w) -> Dict[str, Dict[str, np.ndarray]]:
        out = {}
        if getattr(w, "fused", None) is not None and \
                w.fused._params is not None:
            w.fused.sync_params_to_vectors()
        for f in w.forwards:
            p = {}
            if f.weights:
                p["weights"] = np.asarray(f.weights.map_read()).copy()
            if f.bias and f.include_bias:
                p["bias"] = np.asarray(f.bias.map_read()).copy()
            out[f.name] = p
        return out


class EnsemblePredictor(Logger):
    """Averages member class-probability outputs (the reference's
    aggregation mode for classifiers).

    ``device="auto"`` (the default) serves prediction from the chip
    whenever the template workflow landed on a jax device: member
    params are stacked ONCE at construction along a leading member
    axis and every ``predict_proba``/``error_pct`` call is a single
    jitted vmapped dispatch with on-device probability averaging
    (ops/fused.py ``EnsembleEvalEngine``) — N members x L layers of
    host ``apply_fwd`` calls collapse to one XLA computation.
    ``device="host"`` forces the numpy member loop, which stays as the
    engine's parity oracle (and the only path on the numpy backend).
    """

    def __init__(self, workflow_factory: Callable[[], Any],
                 device_factory: Callable[[], Any],
                 members: List[Dict[str, Any]],
                 device: str = "auto") -> None:
        if not members:
            raise ValueError("empty ensemble")
        if device not in ("auto", "host"):
            raise ValueError(f"device={device!r}: use 'auto' (chip "
                             f"engine when the backend is jax) or "
                             f"'host' (numpy member loop)")
        self.members = members
        # ONE template workflow provides the pure forward chain; member
        # params are swapped through it
        prng.seed_all(members[0]["seed"])
        self.workflow = workflow_factory()
        self.workflow.initialize(device=device_factory())
        self._forwards = list(self.workflow.forwards)
        self.engine = None
        dev = getattr(self.workflow, "device", None)
        if device == "auto" and dev is not None and \
                getattr(dev, "is_jax", False):
            from veles_tpu.ops.fused import EnsembleEvalEngine
            self.engine = EnsembleEvalEngine(
                self._forwards, [m["params"] for m in members], dev)
            self.info("ensemble predictor: %d members stacked on %r "
                      "(one vmapped dispatch per batch)",
                      len(members), dev)

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """Mean of member probability outputs for a batch (NHWC/ND)."""
        if self.engine is not None:
            return self.engine.predict_proba(x)
        return self.predict_proba_host(x)

    def predict_proba_host(self, x: np.ndarray) -> np.ndarray:
        """The numpy member-loop oracle: members x layers of eager
        ``apply_fwd`` host calls.  Kept verbatim as the independent
        reference the device engine is tested against."""
        acc: Optional[np.ndarray] = None
        for m in self.members:
            out = x
            for f in self._forwards:
                p = {k: np.asarray(v)
                     for k, v in m["params"][f.name].items()}
                out, _ = f.apply_fwd(p, out, rng=None, train=False)
            out = np.asarray(out)
            acc = out if acc is None else acc + out
        return acc / len(self.members)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.argmax(self.predict_proba(x), axis=-1)

    def error_pct(self, x: np.ndarray, labels: np.ndarray,
                  chunk: int = 256) -> float:
        """Ensemble classification error %, evaluated in fixed-size
        chunks (one giant batch would materialize every member's
        full-split activations at once)."""
        labels = np.asarray(labels)
        if self.engine is not None:
            return self.engine.error_pct(x, labels, chunk=chunk)
        wrong = 0
        for i in range(0, len(x), chunk):
            wrong += int((np.argmax(self.predict_proba_host(
                x[i:i + chunk]), axis=-1) != labels[i:i + chunk]).sum())
        return 100.0 * wrong / max(len(x), 1)
