"""Distribution hooks.

Reference parity: veles/distributable.py — ``IDistributable`` defines
the per-unit hooks the master--slave protocol calls:
``generate_data_for_slave/master``, ``apply_data_from_slave/master``,
``drop_slave``.

TPU-first role: in the primary SPMD mode (shard_map + psum over ICI,
see veles_tpu/parallel/) these hooks are not on the hot path — gradient
aggregation happens inside the jitted step.  They remain the contract
for (a) the optional zmq DCN mode for heterogeneous clusters and (b)
multi-host job coordination (which host loads which shard).
"""

from __future__ import annotations

from typing import Any, Optional


class Distributable:
    """Mixin with default no-op distribution hooks."""

    negotiates_on_connect: bool = False

    def generate_data_for_master(self) -> Any:
        return None

    def generate_data_for_slave(self, slave: Optional[Any] = None) -> Any:
        return None

    def apply_data_from_master(self, data: Any) -> None:
        pass

    def apply_data_from_slave(self, data: Any,
                              slave: Optional[Any] = None) -> None:
        pass

    def drop_slave(self, slave: Optional[Any] = None) -> None:
        pass
