"""CLI entry point.

Reference parity: veles/__main__.py —
``python -m veles_tpu [flags] workflow.py [config.py ...] [root.k=v ...]``

The workflow file must expose ``run(launcher)`` (builds, initializes
and runs its workflow) or ``create_workflow(launcher) -> Workflow``
(the launcher then drives initialize/run).  Config files are python
executed against the global ``root``; trailing ``root.path=value``
arguments override both.
"""

from __future__ import annotations

import argparse
import os
import sys

from veles_tpu.config import parse_overrides
from veles_tpu.launcher import (Launcher, apply_config_file,
                                drive_workflow)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native dataflow ML framework "
                    "(VELES-capability rebuild)")
    p.add_argument("files", nargs="+",
                   help="workflow.py [config.py ...]")
    p.add_argument("-b", "--backend", default="auto",
                   choices=["auto", "tpu", "jax", "cpu", "numpy",
                            "tpu-evaluator"],
                   help="execution backend (default: auto); "
                        "'tpu-evaluator' is --optimize-only: one "
                        "chip-owning evaluator process + host prep "
                        "workers")
    p.add_argument("-s", "--seed", type=int, default=1234)
    p.add_argument("--snapshot", default=None,
                   help="resume from a snapshot file")
    p.add_argument("--supervise", action="store_true",
                   help="Phoenix run supervisor: spawn the run as a "
                        "child and auto-resume it from the newest "
                        "intact snapshot / GA state on crash (exit "
                        "codes 13/14 always resume; crash-loops give "
                        "up after $VELES_SUPERVISE_MAX_CRASHES "
                        "failures inside $VELES_SUPERVISE_CRASH_"
                        "WINDOW seconds).  See docs/guide.md "
                        "'Operating long runs'")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel ways over the device mesh")
    p.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() "
                        "(multi-host SPMD over DCN+ICI)")
    p.add_argument("--master-address", default=None,
                   help="run as zmq slave of this master (DCN compat)")
    p.add_argument("--listen-address", default=None,
                   help="run as zmq master listening here (DCN compat)")
    p.add_argument("-p", "--plotters", action="store_true",
                   help="render per-epoch plots (error/loss curves, "
                        "confusion, weight tiles) to $VELES_PLOTS_DIR")
    p.add_argument("--plots-endpoint", default=None,
                   help="also publish plot events on this zmq PUB "
                        "endpoint for live graphics_client viewers")
    p.add_argument("--optimize", default=None, metavar="POP:GEN",
                   help="GA-tune config values wrapped in Tune(...): "
                        "population size : generations (e.g. 8:5)")
    p.add_argument("--ga-workers", type=int, default=0,
                   help="parallel GA workers (0 = auto: up to 4). "
                        "With -b cpu/numpy these are genome-evaluation "
                        "subprocesses; with -b auto/tpu-evaluator they "
                        "are host-side prep threads feeding ONE "
                        "chip-owning evaluator process — the chip is "
                        "exclusive and is never probed from the GA "
                        "parent")
    p.add_argument("--ga-eval-timeout", "--eval-timeout",
                   type=float, default=3600, dest="ga_eval_timeout",
                   help="hard cap in seconds before a genome's "
                        "training run is killed and scored inf "
                        "(default 3600).  The chip-owning evaluator "
                        "additionally enforces an ADAPTIVE per-genome "
                        "deadline (4x the EMA of measured genome "
                        "durations, floored at 60s), so a hung "
                        "evaluator is replaced long before this cap")
    p.add_argument("--heartbeat-deadline", type=float, default=60,
                   help="tpu-evaluator mode: seconds of evaluator "
                        "stdout silence (no heartbeat, no result) "
                        "before it is declared hung and replaced "
                        "(default 60; 0 disables heartbeat "
                        "supervision)")
    p.add_argument("--ga-cohort", type=int, default=0,
                   help="tpu-evaluator mode: genomes sharing a shape "
                        "signature (identical integer tunes) train as "
                        "ONE population-batched vmapped dispatch of up "
                        "to this many members (0 = auto, capped by the "
                        "HBM budget; 1 = disable cohort batching and "
                        "evaluate per genome)")
    p.add_argument("--ga-state", default=None, metavar="FILE",
                   help="per-generation GA checkpoint; an existing "
                        "file resumes the run")
    p.add_argument("--ensemble-train", type=int, default=None,
                   metavar="N",
                   help="train N ensemble members of the workflow "
                        "(per-member seeds; the workflow file must "
                        "expose create_workflow) and save them to "
                        "--ensemble-file")
    p.add_argument("--ensemble-test", action="store_true",
                   help="load --ensemble-file and report the "
                        "aggregated (mean-probability) validation "
                        "error")
    p.add_argument("--ensemble-file", default="ensemble.npz",
                   metavar="FILE",
                   help="member store for --ensemble-train/test "
                        "(default: ensemble.npz)")
    p.add_argument("--ensemble-device", default="auto",
                   choices=["auto", "host"],
                   help="--ensemble-test prediction engine: 'auto' = "
                        "one vmapped member-stacked dispatch on the "
                        "chip when the backend is jax; 'host' = the "
                        "numpy member-loop oracle")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run plus "
                        "a per-layer FLOPs table into DIR")
    p.add_argument("--status-server", default=None,
                   help="POST per-epoch status to this web_status "
                        "dashboard (http://host:port)")
    p.add_argument("--log-events", default=None, metavar="FILE",
                   help="append every log record to FILE as JSON "
                        "lines (the reference's run-event DB sink, "
                        "file-shaped)")
    p.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help="Sightline telemetry: write per-process "
                        "metrics snapshots (metrics-<pid>.json) and "
                        "the run journal (journal-<pid>.jsonl) into "
                        "DIR; exported as $VELES_METRICS_DIR so GA "
                        "evaluators and multihost peers inherit it.  "
                        "Render with scripts/obs_report.py DIR")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--dump-config", action="store_true",
                   help="print the effective config tree and exit")
    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--supervise" in argv:
        # intercepted BEFORE argparse/config side effects: the
        # supervisor process must stay light (no device, no jax) —
        # the child re-parses the identical argv minus the flag
        from veles_tpu import supervisor
        return supervisor.run([a for a in argv if a != "--supervise"])
    if "--serve-models" in argv:
        # the Hive serving process (docs/guide.md "Online serving"):
        # its model specs are NAME=PKG pairs, not workflow files, so it
        # owns its own parser — intercepted like --supervise (and
        # composable with it: a supervised hive exits 14 on SIGTERM
        # and is resumed with warm caches)
        from veles_tpu.serve import hive
        return hive.main([a for a in argv if a != "--serve-models"])
    if "--serve-fleet" in argv:
        # Swarm (docs/guide.md "Fleet serving"): N hive replicas
        # behind one SLO-aware router, speaking the same JSONL
        # protocol — intercepted like --serve-models; the replica
        # count rides as the first positional
        from veles_tpu.serve import router
        return router.main([a for a in argv if a != "--serve-fleet"])
    # root.* overrides can appear anywhere; apply AFTER config files,
    # so collect them first but apply later.
    overrides = [a for a in argv if a.startswith("root.") and "=" in a]
    rest = [a for a in argv if a not in overrides]
    args = build_parser().parse_args(rest)

    workflow_file, *config_files = args.files
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)

    if args.plots_endpoint:
        from veles_tpu import graphics_server
        server = graphics_server.get_server()
        server.endpoint = args.plots_endpoint
        server.bind()
        args.plotters = True  # an endpoint without plotters is silence

    if args.dump_config:
        from veles_tpu.config import root
        root.print_()
        return 0

    if args.log_events:
        import atexit

        from veles_tpu.logger import add_jsonl_sink
        atexit.register(add_jsonl_sink(args.log_events))

    if args.metrics_dir:
        from veles_tpu import telemetry
        telemetry.configure(args.metrics_dir)

    if args.backend == "tpu-evaluator" and not args.optimize:
        print("-b tpu-evaluator is a GA execution mode — it needs "
              "--optimize POP:GEN", file=sys.stderr)
        return 2

    if args.optimize:
        # NO Launcher here: constructing one acquires the device, and
        # an exclusive TPU grabbed by the GA parent would lock every
        # worker subprocess out of the chip
        return run_optimizer(args, workflow_file, config_files,
                             overrides)

    if args.ensemble_train is not None or args.ensemble_test:
        if args.ensemble_train is not None and args.ensemble_train < 1:
            print(f"--ensemble-train needs N >= 1 "
                  f"(got {args.ensemble_train})", file=sys.stderr)
            return 2
        return run_ensemble(args, workflow_file)

    launcher = Launcher(
        backend=args.backend, seed=args.seed, snapshot=args.snapshot,
        dp=args.dp, master_address=args.master_address,
        listen_address=args.listen_address, multihost=args.multihost,
        plotters=args.plotters, status_server=args.status_server,
        profile=args.profile, verbose=args.verbose)
    try:
        drive_workflow(launcher, workflow_file)
    except RuntimeError as e:
        if "defines neither" in str(e):
            print(str(e), file=sys.stderr)
            return 2
        raise
    return 0


def _ga_worker_count(args) -> int:
    if args.ga_workers:
        return max(1, args.ga_workers)
    # cpu/numpy workers are evaluation subprocesses; auto/tpu-evaluator
    # workers are prep threads for the single chip-owning evaluator —
    # both parallelize across host cores.  Explicit tpu/jax serializes
    # (the chip admits one client and the user asked for direct mode).
    if args.backend in ("numpy", "cpu", "auto", "tpu-evaluator"):
        import os
        return min(4, max(1, (os.cpu_count() or 2) // 2))
    return 1


def _resolve_ga_execution(backend: str, workers: int):
    """(workers, worker_backend) such that parallel genome workers can
    never race to initialize an exclusive TPU chip:

    - ``auto`` -> ``tpu-evaluator`` mode: ONE evaluator subprocess owns
      the device (TPU when present) and executes every genome on it;
      the N workers become host-side prep threads that never construct
      a device, so there is no race by construction.  Runs routed here
      are also eligible for POPULATION-BATCHED evaluation: genomes
      sharing a shape signature train as one vmapped cohort dispatch
      (``--ga-cohort``; run_optimizer wires evaluate_cohort).  When
      the evaluator's hello reports no accelerator, run_optimizer
      falls back to the classic ``cpu`` subprocess fan-out;
    - explicit ``tpu-evaluator`` -> the same, honored even without an
      accelerator (the evaluator then runs genomes on XLA:CPU,
      still one process, compile caches warm across genomes);
    - explicit ``tpu``/``jax`` + parallel workers -> serialized to 1
      direct worker (honors the per-genome-subprocess choice; the
      chip admits one client);
    - ``cpu``/``numpy`` parallelize freely.
    """
    if backend in ("auto", "tpu-evaluator"):
        return max(1, workers), "tpu-evaluator"
    if workers <= 1 or backend in ("numpy", "cpu"):
        return workers, backend
    return 1, backend


def run_ensemble(args, workflow_file: str) -> int:
    """Ensemble mode (reference parity: the upstream CLI's ensemble
    train/test surface — SURVEY.md §3.1 Ensemble row): ``--ensemble-
    train N`` trains N members with per-member seeds and persists them
    (veles_tpu/ensemble/packaging.py npz — the same container Forge
    ensemble packages carry); ``--ensemble-test`` aggregates member
    probabilities over the validation split."""
    import json

    from veles_tpu.backends import make_device
    from veles_tpu.ensemble import (EnsemblePredictor, EnsembleTrainer,
                                    load_members, normalize_npz_path,
                                    save_members)
    from veles_tpu.launcher import load_workflow_module
    from veles_tpu.loader.base import VALID
    from veles_tpu.logger import setup_logging

    setup_logging(10 if args.verbose else 20)
    mod = load_workflow_module(workflow_file)
    create = getattr(mod, "create_workflow", None)
    if create is None:
        print(f"--ensemble-train/test need {workflow_file} to expose "
              f"create_workflow(launcher)", file=sys.stderr)
        return 2

    class _FL:
        workflow = None

    def factory():
        return create(_FL())

    def device_factory():
        return make_device(args.backend)

    members = None
    if args.ensemble_train is not None:
        trainer = EnsembleTrainer(factory, device_factory,
                                  n_members=args.ensemble_train,
                                  base_seed=args.seed)
        members = trainer.train()
        # save_members returns the REAL path (npz suffix appended by
        # numpy when missing) — report and reuse that, not the arg
        path = save_members(args.ensemble_file, members)
        print(json.dumps({
            "members": len(members),
            "member_valid_errors_pct": [round(m["valid_error"], 4)
                                        for m in members],
            "file": path}))
        if not args.ensemble_test:
            return 0

    import numpy as np
    if members is None:   # test-only invocation: load from disk
        # numpy appends .npz on save — normalize_npz_path applies the
        # SAME rule save_members used, so a suffix-less
        # --ensemble-file that trained fine also loads
        fname = normalize_npz_path(args.ensemble_file)
        try:
            members = load_members(fname)
        except FileNotFoundError:
            print(f"--ensemble-test: {fname!r} does not "
                  f"exist (train one first with --ensemble-train N)",
                  file=sys.stderr)
            return 2
    pred = EnsemblePredictor(factory, device_factory, members,
                             device=args.ensemble_device)
    ld = pred.workflow.loader
    n = ld.class_lengths[VALID]
    if not n:
        print("--ensemble-test: the workflow's loader has no "
              "validation split", file=sys.stderr)
        return 2
    off = ld.class_offset(VALID)
    try:
        # normalized_host_rows, not raw original_data: a quantized
        # loader keeps uint8 bytes there and the members were trained
        # on the dequantized float view
        if hasattr(ld, "normalized_host_rows"):
            x = np.asarray(
                ld.normalized_host_rows(slice(off, off + n)))
        else:
            x = np.asarray(ld.original_data.map_read()[off:off + n])
        y = np.asarray(ld.original_labels.map_read()[off:off + n])
    except RuntimeError:
        print("--ensemble-test needs a loader with host-resident "
              "original_data/labels (full-batch); streaming loaders "
              "are not supported here", file=sys.stderr)
        return 2
    # minibatch-sized chunks in both engines: one giant batch would
    # materialize every member's full-split activations at once (the
    # device engine additionally keeps ONE compiled shape this way)
    err = pred.error_pct(x, y, chunk=max(1, ld.max_minibatch_size))
    print(json.dumps({
        "members": len(members),
        "ensemble_valid_error_pct": round(err, 4),
        "ensemble_eval_engine": "device" if pred.engine is not None
        else "host",
        "member_valid_errors_pct": [round(m["valid_error"], 4)
                                    for m in members]}))
    return 0


def run_optimizer(args, workflow_file: str, config_files, overrides) \
        -> int:
    """GA mode (reference: veles --optimize): genes are Tune(...)
    markers in the config tree; fitness is the best validation error
    of a full (short) training run.  Two execution modes, resolved by
    _resolve_ga_execution:

    - subprocess fan-out (cpu/numpy, or explicit tpu/jax serialized):
      each genome runs in its OWN worker subprocess
      (veles_tpu/genetics/worker.py), isolating the global ``root``
      mutation and any crash, fanned out over --ga-workers;
    - ``tpu-evaluator`` (the ``auto`` default): ONE persistent
      evaluator subprocess owns the accelerator and executes every
      genome on it (genetics/pool.py), the workers become host prep
      threads — the framework's own hyperparameter search finally
      trains on the chip with N>1 workers and no device race.

    --ga-state checkpoints every generation and resumes in both."""
    import json
    import subprocess
    from concurrent.futures import ThreadPoolExecutor

    from veles_tpu.config import root
    from veles_tpu.genetics import GeneticOptimizer, find_tunes
    from veles_tpu.logger import setup_logging

    # no Launcher in this process (the device must stay unclaimed for
    # the evaluator/workers), so logging is configured directly
    setup_logging(10 if args.verbose else 20)

    tunes = find_tunes(root)
    if not tunes:
        print("--optimize: no Tune(...) markers in the config tree",
              file=sys.stderr)
        return 2
    pop_s, _, gen_s = args.optimize.partition(":")
    pop, gen = int(pop_s), int(gen_s or 3)
    workers, worker_backend = _resolve_ga_execution(
        args.backend, _ga_worker_count(args))

    # Phoenix graceful stop for the GA parent: SIGTERM/SIGINT stops at
    # the next generation boundary (the --ga-state checkpoint is the
    # resume point) and exits 14 so a supervisor resumes it.
    # Installed after the cheap usage validation above so every path
    # from here runs finish_preempt() (handler restoration) below.
    from veles_tpu import faults
    from veles_tpu.supervisor import install_ga_stop
    stop_check, finish_preempt = install_ga_stop()
    faults.maybe_inject_sigterm(
        attempt=os.environ.get("VELES_SUPERVISE_ATTEMPT", "0"),
        mode="ga")

    pool = None
    if worker_backend == "tpu-evaluator":
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        serve_cmd = [sys.executable, "-m",
                     "veles_tpu.genetics.worker", "--serve",
                     workflow_file, *config_files, *overrides,
                     "-b", "auto", "-s", str(args.seed),
                     "--cohort", str(max(0, args.ga_cohort))]
        if args.verbose:
            serve_cmd.append("-v")
        pool = ChipEvaluatorPool(
            serve_cmd, workers=workers,
            timeout=args.ga_eval_timeout,
            heartbeat_deadline=args.heartbeat_deadline,
            seed=args.seed)
        try:
            hello = pool.start()
        except Exception as e:  # noqa: BLE001 — fall back, not die
            print(f"--optimize: chip evaluator failed to start ({e})",
                  file=sys.stderr)
            pool.close()
            pool = None
            hello = None
        if pool is not None and not pool.is_accelerator \
                and args.backend == "auto":
            # no chip behind `auto`: the classic CPU fan-out
            # parallelizes better than one XLA:CPU evaluator process
            print(f"--optimize: no accelerator visible (evaluator "
                  f"landed on {pool.platform}) — falling back to "
                  f"{workers} cpu evaluation subprocesses",
                  file=sys.stderr)
            pool.close()
            pool = None
        if pool is None:
            worker_backend = "cpu"
        else:
            print(f"--optimize: tpu-evaluator mode — evaluator pid "
                  f"{hello['pid']} owns {pool.platform}; {workers} "
                  f"prep workers feed its queue", file=sys.stderr)
    if pool is None and workers == 1 and args.ga_workers > 1:
        print(f"--optimize: -b {args.backend} admits one client — "
              f"--ga-workers {args.ga_workers} serialized to 1",
              file=sys.stderr)

    base_cmd = [sys.executable, "-m", "veles_tpu.genetics.worker",
                workflow_file, *config_files, *overrides,
                "-b", worker_backend, "-s", str(args.seed)]

    def evaluate_one_subprocess(values) -> float:
        cmd = base_cmd + ["--values", json.dumps(values)]
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=args.ga_eval_timeout)
            if res.returncode != 0:
                raise RuntimeError(
                    f"worker rc={res.returncode}: "
                    f"{res.stderr.strip().splitlines()[-1:]!r}")
            return float(json.loads(
                res.stdout.strip().splitlines()[-1])["fitness"])
        except Exception as e:  # noqa: BLE001 — bad genes score inf
            print(f"--optimize: genome {values} failed: {e}",
                  file=sys.stderr)
            return float("inf")

    def evaluate_many_subprocess(values_list):
        with ThreadPoolExecutor(workers) as tp:
            return list(tp.map(evaluate_one_subprocess, values_list))

    if pool is not None:
        evaluate_one, evaluate_many = pool.evaluate_one, \
            pool.evaluate_many
    else:
        evaluate_one, evaluate_many = evaluate_one_subprocess, \
            evaluate_many_subprocess

    # population-batched cohorts ride the chip-owning evaluator: the
    # optimizer buckets each generation by shape signature and the
    # evaluator trains every bucket as one vmapped dispatch chain
    # (--ga-cohort 1 opts out; any failure falls back to the
    # per-genome oracle inside _fitness_many)
    evaluate_cohort = pool.evaluate_cohort \
        if pool is not None and args.ga_cohort != 1 else None

    try:
        opt = GeneticOptimizer(evaluate_one, tunes, population=pop,
                               generations=gen,
                               evaluate_many=evaluate_many,
                               evaluate_cohort=evaluate_cohort,
                               state_path=args.ga_state,
                               stop_check=stop_check)
        best, fitness = opt.run()
    finally:
        if pool is not None:
            pool.close()
        # restore the signal handlers on EVERY path (exceptions
        # included); the returned code matters only below
        preempt_code = finish_preempt()
    if preempt_code is not None:
        # graceful stop: best-so-far reported, checkpoint on disk is
        # the resume point — exit 14 so a supervisor resumes, never
        # "done"
        print(json.dumps({"best": best, "fitness": fitness,
                          "preempted": True}))
        return preempt_code
    import math
    if not math.isfinite(fitness):
        print("--optimize: every evaluation failed (fitness inf); "
              "check the workflow runs standalone first",
              file=sys.stderr)
        return 1
    print(json.dumps({"best": best, "fitness": fitness}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
