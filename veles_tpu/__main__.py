"""CLI entry point.

Reference parity: veles/__main__.py —
``python -m veles_tpu [flags] workflow.py [config.py ...] [root.k=v ...]``

The workflow file must expose ``run(launcher)`` (builds, initializes
and runs its workflow) or ``create_workflow(launcher) -> Workflow``
(the launcher then drives initialize/run).  Config files are python
executed against the global ``root``; trailing ``root.path=value``
arguments override both.
"""

from __future__ import annotations

import argparse
import sys

from veles_tpu.config import parse_overrides
from veles_tpu.launcher import (Launcher, apply_config_file,
                                load_workflow_module)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native dataflow ML framework "
                    "(VELES-capability rebuild)")
    p.add_argument("files", nargs="+",
                   help="workflow.py [config.py ...]")
    p.add_argument("-b", "--backend", default="auto",
                   choices=["auto", "tpu", "jax", "cpu", "numpy"],
                   help="execution backend (default: auto)")
    p.add_argument("-s", "--seed", type=int, default=1234)
    p.add_argument("--snapshot", default=None,
                   help="resume from a snapshot file")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel ways over the device mesh")
    p.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() "
                        "(multi-host SPMD over DCN+ICI)")
    p.add_argument("--master-address", default=None,
                   help="run as zmq slave of this master (DCN compat)")
    p.add_argument("--listen-address", default=None,
                   help="run as zmq master listening here (DCN compat)")
    p.add_argument("-p", "--plotters", action="store_true",
                   help="render per-epoch plots (error/loss curves, "
                        "confusion, weight tiles) to $VELES_PLOTS_DIR")
    p.add_argument("--plots-endpoint", default=None,
                   help="also publish plot events on this zmq PUB "
                        "endpoint for live graphics_client viewers")
    p.add_argument("--optimize", default=None, metavar="POP:GEN",
                   help="GA-tune config values wrapped in Tune(...): "
                        "population size : generations (e.g. 8:5)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run plus "
                        "a per-layer FLOPs table into DIR")
    p.add_argument("--status-server", default=None,
                   help="POST per-epoch status to this web_status "
                        "dashboard (http://host:port)")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--dump-config", action="store_true",
                   help="print the effective config tree and exit")
    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # root.* overrides can appear anywhere; apply AFTER config files,
    # so collect them first but apply later.
    overrides = [a for a in argv if a.startswith("root.") and "=" in a]
    rest = [a for a in argv if a not in overrides]
    args = build_parser().parse_args(rest)

    workflow_file, *config_files = args.files
    for cf in config_files:
        apply_config_file(cf)
    parse_overrides(overrides)

    if args.plots_endpoint:
        from veles_tpu import graphics_server
        server = graphics_server.get_server()
        server.endpoint = args.plots_endpoint
        server.bind()
        args.plotters = True  # an endpoint without plotters is silence

    launcher = Launcher(
        backend=args.backend, seed=args.seed, snapshot=args.snapshot,
        dp=args.dp, master_address=args.master_address,
        listen_address=args.listen_address, multihost=args.multihost,
        plotters=args.plotters, status_server=args.status_server,
        profile=args.profile, verbose=args.verbose)

    if args.dump_config:
        from veles_tpu.config import root
        root.print_()
        return 0

    if args.optimize:
        return run_optimizer(args, workflow_file)

    mod = load_workflow_module(workflow_file)
    if hasattr(mod, "run"):
        mod.run(launcher)
    elif hasattr(mod, "create_workflow"):
        launcher.create_workflow(getattr(mod, "create_workflow"))
        launcher.initialize()
        launcher.run()
    else:
        print(f"{workflow_file}: defines neither run(launcher) nor "
              "create_workflow(launcher)", file=sys.stderr)
        return 2
    return 0


def run_optimizer(args, workflow_file: str) -> int:
    """GA mode (reference: veles --optimize): genes are Tune(...)
    markers in the config tree; fitness is the best validation error
    count of a full (short) training run."""
    from veles_tpu.config import root
    from veles_tpu.genetics import (GeneticOptimizer, find_tunes,
                                    substitute_tunes)

    tunes = find_tunes(root)
    if not tunes:
        print("--optimize: no Tune(...) markers in the config tree",
              file=sys.stderr)
        return 2
    pop_s, _, gen_s = args.optimize.partition(":")
    pop, gen = int(pop_s), int(gen_s or 3)

    def evaluate(values):
        substitute_tunes(root, values)
        launcher = Launcher(backend=args.backend, seed=args.seed,
                            verbose=args.verbose)
        mod = load_workflow_module(workflow_file)
        if hasattr(mod, "run"):
            mod.run(launcher)
        elif hasattr(mod, "create_workflow"):
            launcher.create_workflow(getattr(mod, "create_workflow"))
            launcher.initialize()
            launcher.run()
        else:
            raise RuntimeError(
                f"{workflow_file}: defines neither run(launcher) nor "
                "create_workflow(launcher)")
        d = launcher.workflow.decision
        err = d.min_valid_error
        if err == float("inf"):
            err = d.min_train_error
        return err

    opt = GeneticOptimizer(evaluate, tunes, population=pop,
                           generations=gen)
    best, fitness = opt.run()
    import json
    import math
    if not math.isfinite(fitness):
        print("--optimize: every evaluation failed (fitness inf); "
              "check the workflow runs standalone first",
              file=sys.stderr)
        return 1
    print(json.dumps({"best": best, "fitness": fitness}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
