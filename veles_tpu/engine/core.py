"""Keel — the ONE execution core under every engine loop.

The repo used to run four engine loops — ``FusedStepRunner``,
``EnsembleEvalEngine``, ``PopulationTrainEngine`` (ops/fused.py) and
the online scavenger's ``ShadowTrainer`` (online/trainer.py) — each
owning its own residency, donation, and dispatch code: four copies of
the forward/backward trace body, four spellings of ``jax.device_put``,
four places a donation bug could hide.  This module collapses the
overlap into one core where the orthogonal execution flags live
TOGETHER instead of being re-derived per loop:

- **member axis**: absent (one model) or a leading stacked axis vmapped
  over P members (:meth:`ExecutionCore.vmap_members`);
- **data residency**: host-streaming (per-batch :func:`put` uploads),
  HBM-resident (in-trace gather), or row-sharded resident (the
  shard_map gather seam in ops/batching.py) — the adapters pick the
  gather, the core owns every upload;
- **mesh placement**: replicated / batch-sharded / member-sharded
  shardings from parallel/mesh.py, resolved once per core;
- **wire format**: the quantized-ingest prologue
  (:func:`build_ingest`) is part of the shared trace, so uint8 wire
  bytes dequantize identically in every loop;
- **donation**: :func:`donating_jit` is THE place a ``donate_argnums``
  is ever spelled (the ``engine-residency-seam`` lint rule forbids it
  anywhere outside this file, serve/residency.py, parallel/mesh.py).

The shared trace builders (:func:`build_forward`,
:func:`build_backward`, :func:`build_member_forward`,
:func:`build_mean_probs`) are the EXACT bodies the four loops traced
before the refactor — adapters compose them into the same jaxprs, so
f32-bitwise parity with the pre-refactor engines holds by construction
(pinned by tests/test_engine_core.py).

The core also charges its HBM footprint to the process-wide arbiter
(serve/residency.py ``process_arbiter()``): training, GA cohorts, and
serving draw on ONE ledger with per-pool gauges instead of
per-subsystem budget fictions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

# -- the two seam primitives -------------------------------------------
# Every host->device placement and every buffer donation in the repo
# goes through these two calls (or through parallel/mesh.py /
# serve/residency.py, the other two allowlisted seam modules).


def put(array: Any, where: Any = None):
    """THE ``jax.device_put`` seam: place ``array`` on ``where`` (a
    jax device or a Sharding; the backend's default device when
    None).  Call sites outside the seam modules are lint findings —
    residency decisions must not scatter back across the repo."""
    import jax

    if where is None:
        return jax.device_put(array)
    return jax.device_put(array, where)


def donating_jit(fn, donate: Tuple[int, ...] = (),
                 in_shardings: Any = None, out_shardings: Any = None,
                 static_argnums: Any = None):
    """THE donation seam: ``jax.jit`` with ``donate_argnums`` spelled
    exactly once in the repo.  ``donate=()`` compiles without donation
    (the eval/predict dispatchers); sharding kwargs pass through only
    when given, so the non-mesh call is byte-identical to a bare
    ``jax.jit(fn, donate_argnums=...)``."""
    import jax

    kw: Dict[str, Any] = {}
    if donate:
        kw["donate_argnums"] = tuple(donate)
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    if static_argnums is not None:
        kw["static_argnums"] = static_argnums
    return jax.jit(fn, **kw)


# -- shared trace builders ---------------------------------------------
# The bodies below are the (formerly four-times-copied) fused trace
# pieces.  They are pure closures over static unit lists: composing
# them yields the same jaxpr the pre-Keel loops traced, which is what
# keeps the refactor f32-bitwise.


def build_ingest(dequant: Any):
    """The wire-format prologue: identity for f32/bf16 batches, the
    affine uint8 dequantize (f32 arithmetic, host normalization order)
    for quantized loaders."""
    import jax.numpy as jnp

    if dequant is None:
        return lambda x: x
    q_scale = jnp.asarray(dequant.scale, jnp.float32)
    q_bias = jnp.asarray(dequant.bias, jnp.float32)

    def ingest(x):
        return x.astype(jnp.float32) * q_scale + q_bias

    return ingest


def build_forward(forwards, seed: int, compute_dtype):
    """One model's forward chain WITH residuals — the train-mode body
    every loop traces.  The rng key chain (``fold_in(fold_in(key(seed),
    rc), i)`` per stochastic layer) is the repo-wide dropout contract:
    cohort members, the online shadow, and the oracle replay all hash
    the same stream."""
    import jax

    mixed = _not_f32(compute_dtype)

    def forward_pass(params, x, rng_counter, train: bool):
        residuals = []
        if mixed:
            x = x.astype(compute_dtype)
        for i, f in enumerate(forwards):
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(seed),
                                   rng_counter), i) \
                if f.stochastic else None
            x, res = f.apply_fwd(params[f.name], x, rng=rng,
                                 train=train)
            residuals.append(res)
        return x, residuals

    return forward_pass


def _not_f32(compute_dtype) -> bool:
    import jax.numpy as jnp

    return compute_dtype != jnp.float32


def build_backward(forwards, gds, compute_dtype):
    """The backward + SGD chain: walk the gradient units in reverse,
    skip the chain-head err_input when nothing consumes it, and apply
    ``update_params`` with the per-call (lr, bias-lr) row — plus the
    per-member (wd, bias-wd) row when the caller supplies one (the
    population engine's decays contract; ``decays=None`` omits the
    kwarg entirely, matching the single-model loops exactly)."""
    n_fwd = len(forwards)
    first_gd = next((i for i, g in enumerate(gds) if g is not None),
                    -1)
    mixed = _not_f32(compute_dtype)

    def backward_update(cparams, params, opt, residuals, err, lr,
                        wd=None):
        if mixed:
            err = err.astype(compute_dtype)
        new_params = dict(params)
        new_opt = dict(opt)
        for i in range(n_fwd - 1, -1, -1):
            f, gd = forwards[i], gds[i]
            if gd is None:
                continue
            if i == first_gd and gd.can_skip_err_input:
                # nothing consumes the chain-head err_input; for conv1
                # this skips the input-dilated transposed conv (the
                # worst MXU op here)
                _, grads = gd.backward_from_saved(
                    cparams[f.name], residuals[i], err,
                    need_err_input=False)
                err_in = None
            else:
                err_in, grads = gd.backward_from_saved(
                    cparams[f.name], residuals[i], err)
            if grads:
                if wd is None:
                    p, v = gd.update_params(params[f.name], grads,
                                            opt.get(gd.name, {}),
                                            rates=(lr[i, 0],
                                                   lr[i, 1]))
                else:
                    p, v = gd.update_params(params[f.name], grads,
                                            opt.get(gd.name, {}),
                                            rates=(lr[i, 0],
                                                   lr[i, 1]),
                                            decays=(wd[i, 0],
                                                    wd[i, 1]))
                new_params[f.name] = p
                if gd.name in opt:
                    new_opt[gd.name] = v
            err = err_in
        return new_params, new_opt

    return backward_update


def build_member_forward(forwards, compute_dtype):
    """One member's pure inference chain (no rng, f32 output) — the
    body vmapped over a stacked member axis by the ensemble
    dispatchers and the shadow scorer."""
    import jax.numpy as jnp

    mixed = _not_f32(compute_dtype)

    def member_forward(params, x):
        if mixed:
            x = x.astype(compute_dtype)
        for f in forwards:
            x, _ = f.apply_fwd(params[f.name], x, rng=None,
                               train=False)
        return x.astype(jnp.float32)

    return member_forward


def build_mean_probs(forwards, n_members: int, compute_dtype,
                     replicated: Any = None):
    """The ensemble's mean member probabilities: vmap the member
    forward over the stacked axis and average with a FIXED
    left-to-right add chain over the REAL members (never mesh-padding
    copies) — XLA may re-associate a ``jnp.mean`` differently between
    sharded and unsharded programs, and serving parity across
    placements is pinned f32-exact.  On a mesh the ``replicated``
    constraint gathers the member axis first (all_gather moves bits,
    bitwise), so both programs run the identical chain."""
    import jax

    from veles_tpu.ops import batching

    cast = batching.make_caster(compute_dtype)
    member_forward = build_member_forward(forwards, compute_dtype)

    def mean_probs(params, x):
        probs = jax.vmap(member_forward, in_axes=(0, None))(
            cast(params), x)
        if replicated is not None:
            probs = jax.lax.with_sharding_constraint(probs, replicated)
        acc = probs[0]
        for i in range(1, n_members):
            acc = acc + probs[i]
        return acc / n_members

    return mean_probs


def build_som_step(coords):
    """One masked Kohonen minibatch update — the body every SOM loop
    (fused epoch scan, eager per-minibatch dispatch, cohort vmap)
    shares, so fused-vs-eager parity is the same-jaxpr argument the
    supervised builders make.  ``coords`` is the (N, 2) host grid;
    the returned closure takes ``(weights, x, alpha, sigma, mask)``
    with ``x`` still carrying the loader's sample shape."""
    import jax.numpy as jnp

    from veles_tpu.ops.kohonen import som_step_masked

    coords = jnp.asarray(np.asarray(coords), jnp.float32)

    def som_update(weights, x, alpha, sigma, mask):
        x = x.reshape(x.shape[0], -1)
        return som_step_masked(weights, x, coords, alpha, sigma, mask)

    return som_update


def build_som_epoch(coords, resident: bool = True, gather=None):
    """A whole SOM superstep group (one epoch at full superstep) as
    ONE donated ``lax.scan``: the prototype matrix is the scan carry
    (donated by the caller's jit), the (alpha, sigma) schedule rides
    the scan xs so the decay is applied PER STEP inside the trace, and
    the per-step quantization-error / sample-count stats accumulate in
    f32 in the carry (sequential adds — the same order the eager loop's
    per-minibatch accumulator produces).

    ``resident=True`` returns ``epoch(weights, alphas, sigmas,
    dataset, indices, mask)`` gathering rows in-trace (``gather`` is
    the row-sharded shard_map gather on a mesh, ``jnp.take``
    otherwise); ``resident=False`` returns ``epoch(weights, alphas,
    sigmas, xb, mask)`` consuming host-assembled (k, mb, ...) batches.
    Argument order keeps weights at 0 (the donation slot) and the
    member-varying arrays (weights, alphas, sigmas) leading, so the
    cohort engine vmaps with in_axes=(0, 0, 0, None, ...)."""
    import jax.numpy as jnp
    from jax import lax

    som_update = build_som_step(coords)

    def _take(dataset, idx):
        if gather is not None:
            return gather(idx, dataset)
        return jnp.take(dataset, idx, axis=0)

    if resident:
        def epoch(weights, alphas, sigmas, dataset, indices, mask):
            def body(carry, xs):
                w, qe, cnt = carry
                idx, msk, a, s = xs
                w, _, qe_b, n_b = som_update(w, _take(dataset, idx),
                                             a, s, msk)
                return (w, qe + qe_b, cnt + n_b), None

            (weights, qe, cnt), _ = lax.scan(
                body, (weights, jnp.float32(0.0), jnp.float32(0.0)),
                (indices, mask, alphas, sigmas))
            return weights, jnp.stack([qe, cnt])

        return epoch

    def epoch(weights, alphas, sigmas, xb, mask):
        def body(carry, xs):
            w, qe, cnt = carry
            x, msk, a, s = xs
            w, _, qe_b, n_b = som_update(w, x, a, s, msk)
            return (w, qe + qe_b, cnt + n_b), None

        (weights, qe, cnt), _ = lax.scan(
            body, (weights, jnp.float32(0.0), jnp.float32(0.0)),
            (xb, mask, alphas, sigmas))
        return weights, jnp.stack([qe, cnt])

    return epoch


def build_som_eval(coords, resident: bool = True, gather=None):
    """The evaluation-class twin of :func:`build_som_epoch`: same scan
    skeleton, weights untouched (no donation), quantization error and
    sample count accumulated.  ``resident=True`` returns
    ``evaluate(weights, dataset, indices, mask)``; streaming returns
    ``evaluate(weights, xb, mask)`` (``coords`` is accepted for
    signature symmetry; evaluation needs distances only)."""
    import jax.numpy as jnp
    from jax import lax

    from veles_tpu.ops.kohonen import som_qe_masked

    del coords

    def _take(dataset, idx):
        if gather is not None:
            return gather(idx, dataset)
        return jnp.take(dataset, idx, axis=0)

    def _step(w, x, msk):
        return som_qe_masked(w, x.reshape(x.shape[0], -1), msk)

    if resident:
        def evaluate(weights, dataset, indices, mask):
            def body(carry, xs):
                qe, cnt = carry
                idx, msk = xs
                qe_b, n_b = _step(weights, _take(dataset, idx), msk)
                return (qe + qe_b, cnt + n_b), None

            (qe, cnt), _ = lax.scan(
                body, (jnp.float32(0.0), jnp.float32(0.0)),
                (indices, mask))
            return jnp.stack([qe, cnt])

        return evaluate

    def evaluate(weights, xb, mask):
        def body(carry, xs):
            qe, cnt = carry
            x, msk = xs
            qe_b, n_b = _step(weights, x, msk)
            return (qe + qe_b, cnt + n_b), None

        (qe, cnt), _ = lax.scan(
            body, (jnp.float32(0.0), jnp.float32(0.0)), (xb, mask))
        return jnp.stack([qe, cnt])

    return evaluate


# -- the core ----------------------------------------------------------


class ExecutionCore:
    """One engine loop's placement + compile + budget surface.

    Flags are orthogonal and resolved ONCE at construction:

    ``device``
        the framework device (backends.JaxDevice / MeshJaxDevice);
    ``mesh``
        a ``jax.sharding.Mesh`` (or None off-mesh) — placement
        properties (:attr:`replicated`, :attr:`batch_sharding`,
        :attr:`row_sharding`, :attr:`member_axis_sharding`) resolve
        against it;
    ``donate``
        whether :meth:`jit` actually donates (False pins buffers for
        debugging without touching adapter code);
    ``pool``
        which arbiter ledger pool this core's HBM footprint charges
        (``train`` / ``cohort`` / ``serve`` / ``scratch``).
    """

    def __init__(self, device: Any = None, mesh: Any = None, *,
                 donate: bool = True, pool: str = "train",
                 name: Optional[str] = None) -> None:
        self.device = device
        self.mesh = mesh if (mesh is not None
                             and int(mesh.devices.size) > 1) else None
        self.donate = bool(donate)
        self.pool = str(pool)
        self.name = name
        self._shardings: Dict[str, Any] = {}
        self._zeros_cache: Dict[Tuple[int, ...], Any] = {}
        self._replicate_fn = None
        self._charge_key: Optional[str] = None

    # -- placement -----------------------------------------------------

    @property
    def on_mesh(self) -> bool:
        return self.mesh is not None

    @property
    def jax_device(self):
        return getattr(self.device, "jax_device", None)

    def _sharding(self, kind: str):
        s = self._shardings.get(kind)
        if s is None and self.on_mesh:
            from veles_tpu.parallel import mesh as mesh_helpers
            if kind == "replicated":
                s = mesh_helpers.replicated_sharding(self.mesh)
            elif kind == "batch":
                # superstep batches are (k, mb, ...): shard the
                # MINIBATCH axis
                import jax.sharding as shd
                s = shd.NamedSharding(
                    self.mesh,
                    shd.PartitionSpec(None, self.mesh.axis_names[0]))
            elif kind == "rows":
                s = mesh_helpers.row_sharding(self.mesh)
            elif kind == "members":
                s = mesh_helpers.member_sharding(self.mesh)
            self._shardings[kind] = s
        return s

    @property
    def replicated(self):
        """Params/scalars placement on the mesh (None off-mesh)."""
        return self._sharding("replicated")

    @property
    def batch_sharding(self):
        """(k, mb, ...) superstep batches: minibatch axis over the
        data axis (None off-mesh — the single-device jit consumes
        host numpy directly)."""
        return self._sharding("batch")

    @property
    def row_sharding(self):
        """Resident dataset rows 1/N per device (None off-mesh)."""
        return self._sharding("rows")

    @property
    def member_axis_sharding(self):
        """Stacked member axis P/N per device (None off-mesh)."""
        return self._sharding("members")

    def put(self, array: Any, where: Any = None):
        """Place ``array``: on ``where`` when given, else on the
        core's default device."""
        if where is None:
            where = self.jax_device
        return put(array, where)

    def put_members(self, array: np.ndarray):
        """Upload a member-axis-leading array: sharded P/N per device
        on a mesh (multihost-safe ``make_array_from_callback``
        placement, H2D bytes charged to the device accounting), a
        plain device put otherwise."""
        if not self.on_mesh:
            return self.device.put(array)
        from veles_tpu.parallel import mesh as mesh_helpers
        buf = mesh_helpers.put_member_sharded(self.mesh,
                                              np.asarray(array))
        self.device.h2d_bytes += int(buf.nbytes)
        return buf

    def put_replicated(self, array: np.ndarray):
        """Replicate a host array over the mesh (dataset, targets,
        superstep indices/masks), or hand it through untouched
        off-mesh — the single-device jit consumes host numpy
        directly, as before."""
        if not self.on_mesh:
            return array
        import jax.sharding as shd

        from veles_tpu.parallel import mesh as mesh_helpers
        return mesh_helpers.put_along(self.mesh, np.asarray(array),
                                      shd.PartitionSpec())

    def zeros_members(self, shape) -> Any:
        """A member-axis-leading zeros buffer under the member
        sharding — cached per shape (a fresh jit per accumulator
        reset would retrace every class end)."""
        if not self.on_mesh:
            return self.device.zeros(shape, np.float32)
        key = tuple(int(s) for s in shape)
        fn = self._zeros_cache.get(key)
        if fn is None:
            import jax.numpy as jnp
            fn = donating_jit(
                lambda: jnp.zeros(key, jnp.float32),
                out_shardings=self.member_axis_sharding)
            self._zeros_cache[key] = fn
        return fn()

    def replicate_for_fetch(self, array: Any) -> Any:
        """Re-lay a (member-)sharded array out replicated so every
        process can host-fetch it (the multihost-safe
        materialization); identity off-mesh."""
        if not self.on_mesh:
            return array
        if self._replicate_fn is None:
            self._replicate_fn = donating_jit(
                lambda a: a, out_shardings=self.replicated)
        return self._replicate_fn(array)

    # -- compile -------------------------------------------------------

    def jit(self, fn, donate: Tuple[int, ...] = (),
            in_shardings: Any = None, out_shardings: Any = None):
        """Compile through the donation seam; ``donate`` is dropped
        when the core was built with ``donate=False``."""
        return donating_jit(
            fn, donate=donate if self.donate else (),
            in_shardings=in_shardings, out_shardings=out_shardings)

    @staticmethod
    def vmap_members(fn, in_axes):
        """Lift ``fn`` over the leading stacked member axis (axis 0
        where ``in_axes`` says so, broadcast where None)."""
        import jax

        return jax.vmap(fn, in_axes=in_axes)

    # -- the process HBM arbiter ---------------------------------------

    def charge(self, nbytes: int, label: Optional[str] = None) -> None:
        """Charge this core's HBM footprint to the process-wide
        arbiter's ledger under :attr:`pool` (re-charging under the
        same key replaces, so a growing footprint stays one entry)."""
        from veles_tpu.serve import residency

        if self._charge_key is None:
            self._charge_key = (f"{self.pool}:"
                                f"{label or self.name or hex(id(self))}")
        residency.process_arbiter(self.device).reserve(
            self._charge_key, int(nbytes), pool=self.pool)

    def discharge(self) -> None:
        """Release this core's ledger entry (engine release path)."""
        if self._charge_key is None:
            return
        from veles_tpu.serve import residency

        residency.process_arbiter(self.device).release(
            self._charge_key)
        self._charge_key = None

    def release(self) -> None:
        """Drop cached dispatchers and the ledger charge."""
        self._zeros_cache.clear()
        self._replicate_fn = None
        self.discharge()


def tree_nbytes(tree: Any) -> int:
    """Total leaf bytes of a (possibly nested dict) param/opt pytree —
    the arbiter-charge accounting (works on host numpy and device
    arrays alike)."""
    import jax

    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(tree))
