"""The execution-core package (Keel).

``engine.core`` is the ONE place device placement, donation, and the
shared fused trace bodies live; every engine loop in the repo is an
adapter over it (ops/fused.py, online/trainer.py).
"""

from veles_tpu.engine.core import (ExecutionCore, donating_jit,  # noqa: F401
                                   put)
