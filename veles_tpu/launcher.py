"""Launcher: builds the runtime around a workflow and runs it.

Reference parity: veles/launcher.py — selects mode (standalone /
master / slave), creates the device, initializes the workflow, runs,
handles graceful stop and snapshots (SURVEY.md §4.1/§4.2).

TPU adaptation: the primary distributed mode is NOT master--slave —
it is single-controller SPMD: one process per host, all chips driven
through a ``jax.sharding.Mesh`` with gradient psum over ICI
(veles_tpu/parallel/).  ``--master-address``/``--listen-address`` zmq
modes survive as a DCN compat path for heterogeneous clusters
(veles_tpu/server.py, client.py).
"""

from __future__ import annotations

import importlib.util
import os
import re
import sys
import threading
import time
from typing import Any, Optional

from veles_tpu import events, faults, prng, telemetry
from veles_tpu.backends import Device, make_device
from veles_tpu.config import root
from veles_tpu.logger import Logger, setup_logging


class Launcher(Logger):
    def __init__(self, backend: str = "auto",
                 seed: int = 1234,
                 snapshot: Optional[str] = None,
                 dp: Optional[int] = None,
                 master_address: Optional[str] = None,
                 listen_address: Optional[str] = None,
                 multihost: bool = False,
                 plotters: bool = False,
                 status_server: Optional[str] = None,
                 profile: Optional[str] = None,
                 verbose: bool = False,
                 **kwargs: Any) -> None:
        setup_logging(10 if verbose else 20)
        self.backend = backend
        self.snapshot = snapshot
        self.dp = dp
        self.master_address = master_address
        self.listen_address = listen_address
        self.workflow = None
        self.plotters = plotters
        self.status_server = status_server
        self.profile_dir = profile
        self.multihost = multihost
        #: Phoenix graceful-stop state: the signal that requested
        #: preemption (None = not preempted), an event the main thread
        #: sets once the final snapshot landed (stops the grace
        #: watchdog), and the multihost watchdog stopper
        self._preempt_signum: Optional[int] = None
        self._preempt_done = threading.Event()
        self._mh_watchdog_stop = None
        prng.seed_all(seed)
        if multihost:
            init_multihost()
        self.device: Device = make_device(backend)
        self.info("launcher: backend=%s device=%r mode=%s",
                  backend, self.device, self.mode)

    @property
    def mode(self) -> str:
        if self.master_address:
            return "slave"
        if self.listen_address:
            return "master"
        return "standalone"

    # -- workflow lifecycle -------------------------------------------

    def create_workflow(self, factory, **kwargs: Any):
        """factory(launcher, **kwargs) -> Workflow, or resume from
        --snapshot."""
        if self.snapshot:
            from veles_tpu.snapshotter import load_workflow
            self.info("resuming from %s", self.snapshot)
            # fallback=True: a torn/corrupt snapshot resumes from the
            # newest intact sibling instead of killing the run (and
            # raises when none is intact — never a silent fresh start)
            self.workflow = load_workflow(self.snapshot, fallback=True)
        else:
            self.workflow = factory(self, **kwargs)
        if self.plotters and hasattr(self.workflow, "link_plotters"):
            self.workflow.link_plotters()
        if self.status_server and hasattr(self.workflow,
                                          "link_status_reporter"):
            self.workflow.link_status_reporter(self.status_server,
                                               mode=self.mode)
        return self.workflow

    def initialize(self, **kwargs: Any) -> None:
        if self.dp and self.dp > 1:
            from veles_tpu.parallel import DataParallel
            if not self.device.is_jax:
                raise ValueError("--dp requires a jax backend "
                                 "(tpu/jax/cpu), not numpy")
            # mesh over the devices of the SELECTED backend platform —
            # jax.devices() alone would pick the default platform even
            # when the user asked for -b cpu
            import jax
            devices = jax.devices(self.device.platform)
            self.workflow_dp = DataParallel(self.workflow, self.dp,
                                            devices=devices)
            # the mesh device replaces the single-chip device: Vectors
            # upload replicated, the fused step jits sharded
            self.device = self.workflow_dp.install()
        if self.mode == "master" and hasattr(self.workflow,
                                             "wire_fused"):
            # The master never computes minibatches: fused wiring would
            # point Decision at a never-run FusedStepRunner (all-zero
            # metrics) and superstep>1 would advance the loader k
            # minibatches per issued job while shipping only the last
            # one.  Master-side semantics must be eager.
            kwargs.setdefault("fused", False)
        self.workflow.initialize(device=self.device, **kwargs)

    def run(self) -> None:
        from veles_tpu import profiling
        watchdog_stop = self._start_multihost_watchdog() \
            if self.multihost else None
        self._mh_watchdog_stop = watchdog_stop
        uninstall = self._install_preempt_handlers()
        faults.maybe_inject_sigterm(
            attempt=os.environ.get("VELES_SUPERVISE_ATTEMPT", "0"),
            mode=self.mode)
        try:
            with profiling.trace(self.profile_dir):
                if self.mode == "standalone":
                    self.workflow.run()
                elif self.mode == "master":
                    from veles_tpu.server import MasterServer
                    MasterServer(self.workflow,
                                 self.listen_address).serve()
                else:
                    if not self.device.is_jax:
                        raise ValueError(
                            "slave mode computes jobs with the fused "
                            "jitted step — use a jax backend (-b "
                            "tpu/jax/cpu), not numpy")
                    from veles_tpu.client import SlaveClient
                    SlaveClient(self.workflow,
                                self.master_address).serve()
        except (KeyboardInterrupt, SystemExit):
            # with the preempt handlers installed a Ctrl-C never gets
            # here (SIGINT routes through the graceful-stop path and
            # leaves a final snapshot); this survives for embedding
            # contexts where the handlers could not be installed
            raise
        except BaseException as e:
            if self.multihost:
                # a dying peer surfaces here as a failed collective
                # (gloo/XLA distributed error) — abort CLEANLY with a
                # final snapshot instead of hanging or losing the run
                self._abort_multihost(e)
            raise
        finally:
            uninstall()
            if watchdog_stop is not None:
                watchdog_stop()
        if self._preempt_signum is not None:
            self._finish_preempt()   # never returns: os._exit(14)
        if self.profile_dir:
            self._dump_flops_table()

    #: exit code of a clean multihost peer-failure abort (documented
    #: in docs/guide.md "Operating long runs")
    MULTIHOST_ABORT_EXIT = 13
    #: exit code of a preemption-triggered graceful stop (SIGTERM /
    #: SIGINT / a peer's ``veles_preempt`` broadcast): the run stopped
    #: at a dispatch boundary and wrote a final resumable snapshot —
    #: the supervisor always resumes 13/14 without charging the crash
    #: budget
    PREEMPT_EXIT = 14
    #: seconds the graceful stop may take before the watchdog thread
    #: hard-snapshots and exits (the main thread may be wedged inside
    #: a long dispatch or a dead collective)
    PREEMPT_GRACE_ENV = "VELES_PREEMPT_GRACE"
    PREEMPT_GRACE_DEFAULT = 25.0

    # -- graceful stop (Phoenix) --------------------------------------

    def _install_preempt_handlers(self):
        """SIGTERM/SIGINT -> cooperative stop at the next dispatch
        boundary + final snapshot + exit 14.  Installable only from
        the main thread (tests and embedders calling ``run()`` from a
        worker thread keep the old raise-through behavior); returns an
        uninstall callable either way.  ``$VELES_PREEMPT_DISABLE=1``
        opts a process out entirely — the serve-mode GA evaluator sets
        it so a group-wide Ctrl-C can't make every genome child dump a
        'final snapshot' of its scratch workflow into the lineage
        (preemption semantics belong to the GA parent; a dying
        evaluator is the pool's retry-once path, as before)."""
        import signal
        if os.environ.get("VELES_PREEMPT_DISABLE") == "1":
            return lambda: None
        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, self._on_preempt_signal)
            except (ValueError, OSError):  # non-main interp / platform
                pass

        def uninstall() -> None:
            for sig, handler in prev.items():
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass
        return uninstall

    def _on_preempt_signal(self, signum, frame) -> None:
        # handler body is minimal and allocation-light: the interrupted
        # main thread may hold arbitrary locks (telemetry journal,
        # logging), so flag + request + hand off to a watchdog THREAD
        # which does the talking
        if self._preempt_signum is not None:
            # second signal: the operator (or the platform) insists —
            # exit right now; the watchdog/final snapshot may be
            # mid-write, the newest intact candidate still resumes
            os.write(2, b"veles: second preempt signal - hard exit\n")
            os._exit(self.PREEMPT_EXIT)
        self._preempt_signum = signum
        self._begin_graceful_stop(publish=True)

    def _begin_graceful_stop(self, publish: bool) -> None:
        """Request a cooperative stop and arm the grace watchdog.
        Called from the signal handler or (multihost) from the
        ``veles_preempt`` watcher thread."""
        wf = self.workflow
        if wf is not None and hasattr(wf, "request_stop"):
            wf.request_stop()
        threading.Thread(target=self._preempt_watchdog,
                         args=(publish,), daemon=True,
                         name="preempt-watchdog").start()

    def preempt_grace(self) -> float:
        return float(os.environ.get(self.PREEMPT_GRACE_ENV,
                                    str(self.PREEMPT_GRACE_DEFAULT)))

    def _preempt_signal_name(self) -> str:
        import signal
        try:
            return signal.Signals(self._preempt_signum).name
        except (ValueError, TypeError):
            return f"sig{self._preempt_signum}"

    def _preempt_watchdog(self, publish: bool) -> None:
        grace = self.preempt_grace()
        name = self._preempt_signal_name()
        telemetry.event(events.EV_PREEMPT_REQUESTED, signal=name,
                        grace=grace, multihost=self.multihost)
        self.warning(
            "preemption requested (%s): stopping at the next dispatch "
            "boundary; final snapshot due within %.0fs "
            "($%s)", name, grace, self.PREEMPT_GRACE_ENV)
        if publish and self.multihost:
            # coordinated preemption: ALL peers must snapshot and exit
            # 14 together (a lone exit would read as peer death and
            # trigger the abort path on the survivors)
            client = self._kv_client()
            if client is not None:
                try:
                    client.key_value_set("veles_preempt", name)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        if self._preempt_done.wait(grace):
            return   # the main thread finished the graceful stop
        # wedged (long dispatch, dead collective, serve loop): write
        # the final snapshot from THIS thread, bounded, then exit —
        # preemption must never outlive the platform's kill deadline
        self.error("graceful stop missed the %.0fs grace deadline — "
                   "hard final snapshot from the watchdog", grace)
        telemetry.event(events.EV_PREEMPT_DEADLINE_EXCEEDED, grace=grace)
        result: dict = {}

        def snap() -> None:
            result["path"] = self.final_snapshot(f"preempt-{name}")

        t = threading.Thread(target=snap, daemon=True,
                             name="preempt-final-snapshot")
        t.start()
        t.join(timeout=max(10.0, grace))
        telemetry.flush()
        import logging
        logging.shutdown()
        sys.stderr.flush()
        os._exit(self.PREEMPT_EXIT)

    def _finish_preempt(self) -> None:
        """Main-thread completion of a graceful stop: the run loop
        stopped at a dispatch boundary, so write the final snapshot,
        journal, flush, and exit 14 (``os._exit`` — under multihost a
        normal interpreter exit would hang in jax's distributed
        shutdown barrier against peers that already left)."""
        name = self._preempt_signal_name()
        t0 = time.perf_counter()
        path = self.final_snapshot(f"preempt-{name}")
        dt = time.perf_counter() - t0
        telemetry.gauge(events.GAUGE_PREEMPT_SNAPSHOT_SECONDS).set(
            round(dt, 3))
        self._preempt_done.set()
        self.warning(
            "preempted (%s): final snapshot %s (%.2fs); exiting %d",
            name, path or "FAILED", dt, self.PREEMPT_EXIT)
        telemetry.flush()
        import logging
        logging.shutdown()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(self.PREEMPT_EXIT)

    def _kv_client(self):
        """The jax distributed KV client, or None outside a real
        multi-process run."""
        try:
            from jax._src.distributed import global_state
            return global_state.client
        except Exception:  # noqa: BLE001 — no distributed context
            return None

    def final_snapshot(self, reason: str) -> Optional[str]:
        """Best-effort final snapshot for a stop/abort path; None when
        it could not be written (the exit must land regardless).

        Generalizes PR 6's ``_emergency_snapshot``: the file is named
        INTO the Snapshotter lineage
        (``<prefix>_final_<reason>_pid<pid>.pickle.gz`` in the
        snapshotter's directory), so ``snapshot_candidates()`` resume
        discovery finds it, and the resume manifest is pointed at it —
        ``--supervise`` restarts need no flags."""
        try:
            if self.workflow is None:
                return None
            from veles_tpu.snapshotter import (save_workflow,
                                               write_resume_manifest)
            snap = getattr(self.workflow, "snapshotter", None)
            directory = snap.directory if snap is not None else \
                os.path.join(os.path.expanduser("~"),
                             ".veles_tpu", "snapshots")
            prefix = getattr(snap, "prefix", None) or "snapshot"
            os.makedirs(directory, exist_ok=True)
            safe = re.sub(r"[^A-Za-z0-9._-]+", "-", reason)
            path = os.path.join(
                directory,
                f"{prefix}_final_{safe}_pid{os.getpid()}.pickle.gz")
            t0 = time.perf_counter()
            out = save_workflow(self.workflow, path)
            dt = round(time.perf_counter() - t0, 3)
            if reason.startswith("multihost"):
                telemetry.counter(
                    events.CTR_MULTIHOST_EMERGENCY_SNAPSHOTS).inc()
                telemetry.event(events.EV_MULTIHOST_EMERGENCY_SNAPSHOT,
                                path=out, seconds=dt)
            else:
                telemetry.counter(events.CTR_PREEMPT_FINAL_SNAPSHOTS).inc()
                telemetry.event(events.EV_PREEMPT_FINAL_SNAPSHOT,
                                path=out, reason=reason, seconds=dt)
            write_resume_manifest(snapshot=out, reason=reason)
            telemetry.flush()   # os._exit follows — atexit never runs
            return out
        except Exception as e:  # noqa: BLE001 — the exit must land
            self.warning("final snapshot (%s) failed: %s", reason, e)
            return None

    def _emergency_snapshot(self) -> Optional[str]:
        """PR-6 name kept for callers/tests; now writes into the
        snapshot lineage via ``final_snapshot``."""
        return self.final_snapshot("multihost-abort")

    def _abort_multihost(self, exc: BaseException) -> None:
        """A collective failed under --multihost (peer death, network
        partition): write a final emergency snapshot of the local
        workflow state and exit with a distinctive code — the
        supervisor's restart-from-snapshot path, not a hang and not a
        lost run."""
        telemetry.event(events.EV_MULTIHOST_COLLECTIVE_FAILED,
                        error=f"{type(exc).__name__}: {exc}")
        path = self._emergency_snapshot()
        # flush UNCONDITIONALLY: when the snapshot failed, the flush
        # inside final_snapshot never ran and the journal events above
        # (collective_failed, peer_death) would die with os._exit
        telemetry.flush()
        self.error(
            "multihost collective failed (%s: %s) — peer death or "
            "partition; aborting cleanly%s",
            type(exc).__name__, exc,
            f"; final snapshot: {path}" if path else
            " (no snapshot written)")
        # os._exit, not SystemExit: a normal interpreter exit runs
        # jax's atexit distributed.shutdown(), whose Shutdown barrier
        # waits on the DEAD peer until the coordination service
        # SIGABRTs this process (~100 s) — the clean abort must skip
        # that barrier entirely
        import logging
        import sys as _sys
        logging.shutdown()
        _sys.stderr.flush()
        os._exit(self.MULTIHOST_ABORT_EXIT)

    def _start_multihost_watchdog(self):
        """Cross-process liveness over the distributed KV store.

        A dying peer does NOT reliably surface as a catchable error:
        the XLA coordination service only declares a silent task
        unhealthy after ~100 s and then hard-ABORTS every remaining
        process from a C++ thread (SIGABRT — no Python except path,
        no snapshot), while a collective against the dead peer can
        block the main thread indefinitely.  So each process
        publishes a heartbeat key every ``$VELES_MULTIHOST_HEARTBEAT``
        (default 2 s) seconds, and a watchdog thread per peer blocks
        on the peer's next key with a ``$VELES_MULTIHOST_DEADLINE``
        (default 15 s) timeout.  A missed deadline (and no clean
        ``done`` marker) means the peer is gone: write the emergency
        snapshot (bounded wait — the main thread may be wedged inside
        the dead collective) and ``os._exit(13)``, well before the
        coordination service's own fatal abort.

        Returns a stop() callable (publishes this process's clean
        ``done`` marker), or None when not in a real multi-process
        run."""
        import threading
        try:
            import jax
            from jax._src.distributed import global_state
            client = global_state.client
            if client is None or jax.process_count() <= 1:
                return None
            me = jax.process_index()
            peers = [p for p in range(jax.process_count()) if p != me]
        except Exception:  # noqa: BLE001 — no distributed context
            return None
        interval = float(os.environ.get("VELES_MULTIHOST_HEARTBEAT",
                                        "2.0"))
        deadline = float(os.environ.get("VELES_MULTIHOST_DEADLINE",
                                        "15.0"))
        stop = threading.Event()

        def beat() -> None:
            seq = 0
            while not stop.wait(interval):
                try:
                    client.key_value_set(f"veles_hb/{me}/{seq}", "1")
                except Exception:  # noqa: BLE001 — coordination gone;
                    return         # its own abort path is in flight
                seq += 1

        def watch(peer: int) -> None:
            import time as _time
            seq = 0
            last = _time.monotonic()
            while not stop.is_set():
                try:
                    client.blocking_key_value_get(
                        f"veles_hb/{peer}/{seq}",
                        int(deadline * 1000))
                    now = _time.monotonic()
                    # the freshest peer-liveness age the run observed
                    # — obs_report's first read on a wedged slice
                    telemetry.gauge(
                        events.GAUGE_MULTIHOST_PEER_HEARTBEAT_AGE
                    ).set(round(now - last, 3))
                    last = now
                    seq += 1
                    continue
                except Exception:  # noqa: BLE001 — timeout or error
                    pass
                if stop.is_set():
                    return
                try:   # did the peer just finish cleanly?
                    client.blocking_key_value_get(
                        f"veles_done/{peer}", 2000)
                    return
                except Exception:  # noqa: BLE001
                    pass
                if stop.is_set():
                    return
                if self._preempt_signum is not None:
                    # coordinated preemption in flight: a silent peer
                    # is exiting 14 like us, not dying — never convert
                    # a preemption into a peer-death abort
                    return
                self._peer_death_abort(peer, deadline)

        def watch_preempt() -> None:
            # a peer that catches SIGTERM broadcasts ``veles_preempt``
            # so the WHOLE slice snapshots and exits 14 together —
            # coordinated resume, not a peer-death abort
            import signal as _signal
            while not stop.is_set():
                try:
                    client.blocking_key_value_get("veles_preempt",
                                                  5000)
                except Exception:  # noqa: BLE001 — timeout: re-poll
                    continue
                if stop.is_set():
                    return
                if self._preempt_signum is None:
                    self._preempt_signum = int(_signal.SIGTERM)
                    telemetry.event(events.EV_PREEMPT_PEER_BROADCAST)
                    self.warning("peer broadcast veles_preempt — "
                                 "joining the coordinated graceful "
                                 "stop")
                    self._begin_graceful_stop(publish=False)
                return

        threading.Thread(target=beat, daemon=True,
                         name="mh-heartbeat").start()
        threading.Thread(target=watch_preempt, daemon=True,
                         name="mh-watch-preempt").start()
        for p in peers:
            threading.Thread(target=watch, args=(p,), daemon=True,
                             name=f"mh-watch-{p}").start()
        self.info("multihost watchdog up: %d peer(s), heartbeat "
                  "%.1fs, deadline %.1fs", len(peers), interval,
                  deadline)

        def stopper() -> None:
            stop.set()
            try:
                client.key_value_set(f"veles_done/{me}", "1")
            except Exception:  # noqa: BLE001 — shutdown race
                pass

        return stopper

    def _peer_death_abort(self, peer: int, deadline: float) -> None:
        """Watchdog-thread abort: the main thread may be blocked in a
        collective against the dead peer, so the snapshot is written
        from here with a bounded grace period, then the process exits
        with the clean abort code (never hangs, never waits for the
        coordination service's SIGABRT)."""
        telemetry.event(events.EV_MULTIHOST_PEER_DEATH, peer=peer,
                        deadline=deadline)
        self.error(
            "multihost peer %d missed its liveness deadline (%.1fs) — "
            "peer death/partition; writing a final snapshot and "
            "aborting cleanly", peer, deadline)
        result: dict = {}

        def snap() -> None:
            result["path"] = self._emergency_snapshot()

        t = threading.Thread(target=snap, daemon=True,
                             name="mh-final-snapshot")
        t.start()
        t.join(timeout=30.0)
        # flush UNCONDITIONALLY: a failed/hung snapshot skipped the
        # flush inside final_snapshot, and the peer_death event above
        # must survive os._exit
        telemetry.flush()
        path = result.get("path")
        self.error("multihost peer failure: aborting cleanly%s",
                   f"; final snapshot: {path}" if path
                   else " (snapshot did not complete)")
        # stderr flush then hard exit: the main thread cannot be
        # unblocked from a dead collective
        import logging
        logging.shutdown()
        os._exit(self.MULTIHOST_ABORT_EXIT)

    def stop(self) -> None:
        if self.workflow is not None:
            self.workflow.stop()

    def _dump_flops_table(self) -> None:
        """Write the analytic per-layer FLOPs/params table next to the
        jax.profiler trace so the two can be read together."""
        forwards = getattr(self.workflow, "forwards", None)
        if not forwards:
            return
        from veles_tpu import profiling
        from veles_tpu.snapshotter import write_json_atomic
        path = os.path.join(self.profile_dir, "flops_table.json")
        write_json_atomic(path, {
            "layers": profiling.layer_flops_table(forwards),
            "total": profiling.model_flops_per_sample(forwards)})
        self.info("profile: trace + flops_table.json in %s",
                  self.profile_dir)


_multihost_initialized = False


def init_multihost() -> None:
    """``jax.distributed.initialize()`` exactly once per process.

    Multi-host SPMD launch recipe (SURVEY.md §5.8): start the SAME
    ``python -m veles_tpu --multihost ...`` command on every host of
    the slice; on TPU pods coordinator address/process id/count are
    discovered from the TPU metadata automatically, elsewhere set
    JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID / JAX_NUM_PROCESSES.
    After initialization ``jax.devices()`` spans the whole slice, so a
    ``--dp N_total`` mesh shards over every chip; DCN carries control,
    ICI the collectives."""
    global _multihost_initialized
    if _multihost_initialized:
        return
    import jax
    # Detect prior initialization WITHOUT touching the backend:
    # jax.process_count() would itself initialize XLA, after which
    # distributed.initialize() unconditionally raises.  The distributed
    # client handle is the only side-effect-free signal.
    try:
        from jax._src.distributed import global_state
        already = global_state.client is not None
    except Exception:
        already = False
    if not already:
        # The env-var contract this docstring promises is honored HERE:
        # this jax's bare initialize() only auto-detects known cluster
        # environments (SLURM, TPU pods) and raises "Number of
        # processes must be defined" on a plain JAX_COORDINATOR_ADDRESS
        # / JAX_PROCESS_ID / JAX_NUM_PROCESSES launch — so read them
        # explicitly and pass them through (None = keep auto-detect).
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or None
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        if "cpu" in (os.environ.get("JAX_PLATFORMS") or "").lower():
            # cross-process collectives on the CPU backend need the
            # gloo transport selected BEFORE the backend initializes;
            # without it every psum dies in the partitioner.  Config
            # knob present on this jax; guarded for future removal.
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # noqa: BLE001 — newer jax: gloo default
                pass
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nproc) if nproc else None,
                process_id=int(pid) if pid else None)
        except RuntimeError as e:
            # Backend already up (e.g. the embedding process made a JAX
            # call first).  A --multihost launch that silently runs
            # single-process would train on 1/N of the data and
            # checkpoint a state no peer can join — fail LOUDLY unless
            # the operator explicitly accepts solo semantics.
            telemetry.event(events.EV_MULTIHOST_INIT_REFUSED, error=str(e))
            if os.environ.get("VELES_MULTIHOST_ALLOW_SOLO") == "1":
                import logging
                logging.getLogger("veles_tpu.launcher").warning(
                    "jax.distributed.initialize() refused (%s); "
                    "continuing single-process "
                    "($VELES_MULTIHOST_ALLOW_SOLO=1)", e)
            else:
                raise RuntimeError(
                    "--multihost launch refused by "
                    f"jax.distributed.initialize() ({e}); refusing to "
                    "continue single-process — set "
                    "VELES_MULTIHOST_ALLOW_SOLO=1 to accept solo "
                    "semantics") from e
    _multihost_initialized = True
    _maybe_inject_peer_exit()


def _maybe_inject_peer_exit() -> None:
    """Faultline ``multihost.peer_exit``: hard-exit THIS process (now,
    or ``after`` seconds on a timer thread) so the drill can rehearse
    a dying peer — the surviving processes must abort cleanly with a
    final snapshot (Launcher._abort_multihost), never hang."""
    from veles_tpu import faults
    if not faults.active():
        return
    proc = os.environ.get("JAX_PROCESS_ID")
    if proc is None:
        try:
            import jax
            proc = str(jax.process_index())
        except Exception:  # noqa: BLE001 — no distributed context
            proc = "0"
    f = faults.fire("multihost.peer_exit", process=proc)
    if not f:
        return
    delay = float(f.get("after", 0.0))
    if delay <= 0:
        os._exit(17)
    import threading
    import time as _time

    def _die():
        _time.sleep(delay)
        os._exit(17)

    threading.Thread(target=_die, daemon=True,
                     name="fault-peer-exit").start()


def load_workflow_module(path: str):
    """Import a workflow file the reference way (a plain python file,
    not necessarily on sys.path)."""
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def apply_config_file(path: str) -> None:
    """Execute a config file for its side effect of mutating ``root``
    (reference: config files are python)."""
    glb = {"root": root, "__file__": path, "__name__": "__veles_config__"}
    with open(path) as f:
        code = compile(f.read(), path, "exec")
    exec(code, glb)


def drive_workflow(launcher, workflow_file: str) -> None:
    """Load a workflow module and drive it through the launcher — the
    one place the run(launcher) / create_workflow(launcher) module
    contract is interpreted (CLI main and GA workers both call this)."""
    mod = load_workflow_module(workflow_file)
    if hasattr(mod, "run"):
        mod.run(launcher)
    elif hasattr(mod, "create_workflow"):
        launcher.create_workflow(getattr(mod, "create_workflow"))
        launcher.initialize()
        launcher.run()
    else:
        raise RuntimeError(
            f"{workflow_file}: defines neither run(launcher) nor "
            "create_workflow(launcher)")


def workflow_fitness(workflow) -> float:
    """The GA fitness of a finished workflow: best validation error,
    falling back to best train error for valid-less configs."""
    d = workflow.decision
    err = d.min_valid_error
    if err == float("inf"):
        err = d.min_train_error
    return err
