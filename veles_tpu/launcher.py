"""Launcher: builds the runtime around a workflow and runs it.

Reference parity: veles/launcher.py — selects mode (standalone /
master / slave), creates the device, initializes the workflow, runs,
handles graceful stop and snapshots (SURVEY.md §4.1/§4.2).

TPU adaptation: the primary distributed mode is NOT master--slave —
it is single-controller SPMD: one process per host, all chips driven
through a ``jax.sharding.Mesh`` with gradient psum over ICI
(veles_tpu/parallel/).  ``--master-address``/``--listen-address`` zmq
modes survive as a DCN compat path for heterogeneous clusters
(veles_tpu/server.py, client.py).
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import Any, Optional

from veles_tpu import prng
from veles_tpu.backends import Device, make_device
from veles_tpu.config import root
from veles_tpu.logger import Logger, setup_logging


class Launcher(Logger):
    def __init__(self, backend: str = "auto",
                 seed: int = 1234,
                 snapshot: Optional[str] = None,
                 dp: Optional[int] = None,
                 master_address: Optional[str] = None,
                 listen_address: Optional[str] = None,
                 multihost: bool = False,
                 plotters: bool = False,
                 status_server: Optional[str] = None,
                 profile: Optional[str] = None,
                 verbose: bool = False,
                 **kwargs: Any) -> None:
        setup_logging(10 if verbose else 20)
        self.backend = backend
        self.snapshot = snapshot
        self.dp = dp
        self.master_address = master_address
        self.listen_address = listen_address
        self.workflow = None
        self.plotters = plotters
        self.status_server = status_server
        self.profile_dir = profile
        prng.seed_all(seed)
        if multihost:
            init_multihost()
        self.device: Device = make_device(backend)
        self.info("launcher: backend=%s device=%r mode=%s",
                  backend, self.device, self.mode)

    @property
    def mode(self) -> str:
        if self.master_address:
            return "slave"
        if self.listen_address:
            return "master"
        return "standalone"

    # -- workflow lifecycle -------------------------------------------

    def create_workflow(self, factory, **kwargs: Any):
        """factory(launcher, **kwargs) -> Workflow, or resume from
        --snapshot."""
        if self.snapshot:
            from veles_tpu.snapshotter import load_workflow
            self.info("resuming from %s", self.snapshot)
            self.workflow = load_workflow(self.snapshot)
        else:
            self.workflow = factory(self, **kwargs)
        if self.plotters and hasattr(self.workflow, "link_plotters"):
            self.workflow.link_plotters()
        if self.status_server and hasattr(self.workflow,
                                          "link_status_reporter"):
            self.workflow.link_status_reporter(self.status_server,
                                               mode=self.mode)
        return self.workflow

    def initialize(self, **kwargs: Any) -> None:
        if self.dp and self.dp > 1:
            from veles_tpu.parallel import DataParallel
            if not self.device.is_jax:
                raise ValueError("--dp requires a jax backend "
                                 "(tpu/jax/cpu), not numpy")
            # mesh over the devices of the SELECTED backend platform —
            # jax.devices() alone would pick the default platform even
            # when the user asked for -b cpu
            import jax
            devices = jax.devices(self.device.platform)
            self.workflow_dp = DataParallel(self.workflow, self.dp,
                                            devices=devices)
            # the mesh device replaces the single-chip device: Vectors
            # upload replicated, the fused step jits sharded
            self.device = self.workflow_dp.install()
        if self.mode == "master" and hasattr(self.workflow,
                                             "wire_fused"):
            # The master never computes minibatches: fused wiring would
            # point Decision at a never-run FusedStepRunner (all-zero
            # metrics) and superstep>1 would advance the loader k
            # minibatches per issued job while shipping only the last
            # one.  Master-side semantics must be eager.
            kwargs.setdefault("fused", False)
        self.workflow.initialize(device=self.device, **kwargs)

    def run(self) -> None:
        from veles_tpu import profiling
        with profiling.trace(self.profile_dir):
            if self.mode == "standalone":
                self.workflow.run()
            elif self.mode == "master":
                from veles_tpu.server import MasterServer
                MasterServer(self.workflow, self.listen_address).serve()
            else:
                if not self.device.is_jax:
                    raise ValueError(
                        "slave mode computes jobs with the fused jitted "
                        "step — use a jax backend (-b tpu/jax/cpu), "
                        "not numpy")
                from veles_tpu.client import SlaveClient
                SlaveClient(self.workflow, self.master_address).serve()
        if self.profile_dir:
            self._dump_flops_table()

    def stop(self) -> None:
        if self.workflow is not None:
            self.workflow.stop()

    def _dump_flops_table(self) -> None:
        """Write the analytic per-layer FLOPs/params table next to the
        jax.profiler trace so the two can be read together."""
        forwards = getattr(self.workflow, "forwards", None)
        if not forwards:
            return
        import json
        from veles_tpu import profiling
        path = os.path.join(self.profile_dir, "flops_table.json")
        with open(path, "w") as f:
            json.dump({"layers": profiling.layer_flops_table(forwards),
                       "total": profiling.model_flops_per_sample(
                           forwards)}, f, indent=2)
        self.info("profile: trace + flops_table.json in %s",
                  self.profile_dir)


_multihost_initialized = False


def init_multihost() -> None:
    """``jax.distributed.initialize()`` exactly once per process.

    Multi-host SPMD launch recipe (SURVEY.md §5.8): start the SAME
    ``python -m veles_tpu --multihost ...`` command on every host of
    the slice; on TPU pods coordinator address/process id/count are
    discovered from the TPU metadata automatically, elsewhere set
    JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID / JAX_NUM_PROCESSES.
    After initialization ``jax.devices()`` spans the whole slice, so a
    ``--dp N_total`` mesh shards over every chip; DCN carries control,
    ICI the collectives."""
    global _multihost_initialized
    if _multihost_initialized:
        return
    import jax
    # Detect prior initialization WITHOUT touching the backend:
    # jax.process_count() would itself initialize XLA, after which
    # distributed.initialize() unconditionally raises.  The distributed
    # client handle is the only side-effect-free signal.
    try:
        from jax._src.distributed import global_state
        already = global_state.client is not None
    except Exception:
        already = False
    if not already:
        # The env-var contract this docstring promises is honored HERE:
        # this jax's bare initialize() only auto-detects known cluster
        # environments (SLURM, TPU pods) and raises "Number of
        # processes must be defined" on a plain JAX_COORDINATOR_ADDRESS
        # / JAX_PROCESS_ID / JAX_NUM_PROCESSES launch — so read them
        # explicitly and pass them through (None = keep auto-detect).
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or None
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        if "cpu" in (os.environ.get("JAX_PLATFORMS") or "").lower():
            # cross-process collectives on the CPU backend need the
            # gloo transport selected BEFORE the backend initializes;
            # without it every psum dies in the partitioner.  Config
            # knob present on this jax; guarded for future removal.
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # noqa: BLE001 — newer jax: gloo default
                pass
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nproc) if nproc else None,
                process_id=int(pid) if pid else None)
        except RuntimeError as e:
            # Backend already up (e.g. the embedding process made a JAX
            # call first) — single-process semantics are the only safe
            # fallback; surface it loudly rather than crash.
            import logging
            logging.getLogger("veles_tpu.launcher").warning(
                "jax.distributed.initialize() refused (%s); continuing "
                "single-process", e)
    _multihost_initialized = True


def load_workflow_module(path: str):
    """Import a workflow file the reference way (a plain python file,
    not necessarily on sys.path)."""
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def apply_config_file(path: str) -> None:
    """Execute a config file for its side effect of mutating ``root``
    (reference: config files are python)."""
    glb = {"root": root, "__file__": path, "__name__": "__veles_config__"}
    with open(path) as f:
        code = compile(f.read(), path, "exec")
    exec(code, glb)


def drive_workflow(launcher, workflow_file: str) -> None:
    """Load a workflow module and drive it through the launcher — the
    one place the run(launcher) / create_workflow(launcher) module
    contract is interpreted (CLI main and GA workers both call this)."""
    mod = load_workflow_module(workflow_file)
    if hasattr(mod, "run"):
        mod.run(launcher)
    elif hasattr(mod, "create_workflow"):
        launcher.create_workflow(getattr(mod, "create_workflow"))
        launcher.initialize()
        launcher.run()
    else:
        raise RuntimeError(
            f"{workflow_file}: defines neither run(launcher) nor "
            "create_workflow(launcher)")


def workflow_fitness(workflow) -> float:
    """The GA fitness of a finished workflow: best validation error,
    falling back to best train error for valid-less configs."""
    d = workflow.decision
    err = d.min_valid_error
    if err == float("inf"):
        err = d.min_train_error
    return err
