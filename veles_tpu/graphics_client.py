"""Graphics client: subscribes to a GraphicsServer PUB endpoint and
renders incoming plot events to files (or a live GUI when a display
exists).

Reference parity: veles/graphics_client.py — the separate matplotlib
process attached to the plot event bus.  Run it on a workstation while
training runs headless:

    python -m veles_tpu.graphics_client tcp://trainhost:5005 [out_dir]
"""

from __future__ import annotations

import sys

from veles_tpu.graphics_server import FileRenderer, decode_event
from veles_tpu.logger import Logger, setup_logging


class GraphicsClient(Logger):
    def __init__(self, endpoint: str, out_dir: str = "plots") -> None:
        self.endpoint = endpoint
        self.renderer = FileRenderer(out_dir)

    def serve(self, max_events: int = 0) -> int:
        import zmq

        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.SUB)
        sock.setsockopt(zmq.SUBSCRIBE, b"")
        sock.connect(self.endpoint)
        self.info("subscribed to %s", self.endpoint)
        n = 0
        try:
            while True:
                event = decode_event(sock.recv())
                path = self.renderer.render(event)
                if path:
                    self.info("rendered %s", path)
                n += 1
                if max_events and n >= max_events:
                    break
        except KeyboardInterrupt:
            pass
        finally:
            sock.close(0)
        return n


def main() -> int:
    setup_logging()
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    out = sys.argv[2] if len(sys.argv) > 2 else "plots"
    GraphicsClient(sys.argv[1], out).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
