"""Phoenix: the run supervisor — auto-resume long runs from the
newest intact state.

Faultline (PR 6) made every failure die CLEANLY (exit 13 + emergency
snapshot, CRC-verified checkpoints, fallback-to-intact loaders) and
Sightline (PR 7) made it observable — but the loop still ended at a
human restarting the run.  Phoenix closes it: preemption becomes a
*graceful stop* (SIGTERM/SIGINT -> cooperative stop at the next
dispatch boundary -> final snapshot inside ``$VELES_PREEMPT_GRACE``
-> exit 14), and this module's supervisor turns any child death into
an automatic, flag-less resume.

The exit-code contract (pinned in tests/test_supervisor.py)::

    0    done               -> the supervisor exits 0
    13   multihost abort    -> ALWAYS resume; never charged to the
                               crash budget (a peer died cleanly)
    14   preempted          -> ALWAYS resume; never charged (the
                               platform reclaimed the machine)
    2    usage error        -> give up immediately (deterministic)
    else crash              -> resume from the newest intact state,
                               charged to the crash budget

Resume needs no operator flags: every snapshot/checkpoint writer in
the child updates a *resume manifest* (snapshotter.py
``write_resume_manifest`` — snapshot path, GA state path, metrics
dir), the supervisor exports ``$VELES_RESUME_MANIFEST`` so the child
knows where, and on each restart the ``--snapshot`` argument is
rewritten to the newest INTACT candidate (CRC-probed without
unpickling; siblings walked newest-first when the manifest's pointer
is torn).  GA runs resume through their own ``--ga-state`` file —
the checkpoint is re-read by the child, bit-identically.

Crash-loop protection mirrors the evaluator pool's restart shape
(genetics/pool.py): exponential backoff with deterministic +-25%
jitter between consecutive crashes, and ``max_crashes`` failures
inside ``crash_window`` seconds give up LOUDLY (``supervisor.giveup``
journal event, child's exit code propagated).  Every transition is
journaled (``supervisor.restart`` / ``supervisor.resumed`` /
``supervisor.giveup``, ``supervisor.restarts`` counter,
``supervisor.downtime_seconds`` histogram).

Entry points::

    python -m veles_tpu --supervise [any normal CLI args...]
    supervisor.run(argv)                       # same, programmatic
    Supervisor(argv, command=[...]).run()      # custom child command
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from veles_tpu import events, telemetry
from veles_tpu.logger import Logger

#: the exit-code contract (kept equal to Launcher's constants; the
#: cross-check is pinned in tests/test_supervisor.py without importing
#: launcher here — the supervisor must stay importable without jax)
EXIT_DONE = 0
EXIT_MULTIHOST_ABORT = 13
EXIT_PREEMPTED = 14
EXIT_USAGE = 2
#: codes that always resume and never charge the crash budget
RESUME_CODES = frozenset((EXIT_MULTIHOST_ABORT, EXIT_PREEMPTED))

MAX_CRASHES_ENV = "VELES_SUPERVISE_MAX_CRASHES"
CRASH_WINDOW_ENV = "VELES_SUPERVISE_CRASH_WINDOW"
#: exported to each child: 0 for the first attempt, incrementing per
#: restart — Faultline qualifiers (``@attempt=0``) target one attempt
ATTEMPT_ENV = "VELES_SUPERVISE_ATTEMPT"


def _normalize_rc(rc: int) -> int:
    """subprocess returncode -> shell-convention exit code (signal
    deaths surface as negative returncodes; 128+sig keeps them
    nonzero and distinguishable when we propagate them)."""
    return 128 - rc if rc < 0 else rc


class Supervisor(Logger):
    """Spawn-and-resume loop around one run command.

    ``argv`` is the child's CLI tail; ``command`` defaults to
    ``[sys.executable, "-m", "veles_tpu"]``.  Knobs (env defaults in
    parentheses): ``max_crashes`` ($VELES_SUPERVISE_MAX_CRASHES, 5)
    genuine crashes inside ``crash_window``
    ($VELES_SUPERVISE_CRASH_WINDOW, 300 s) give up;
    ``restart_backoff``/``restart_backoff_cap`` (0.5 s / 30 s) shape
    the between-crash delay exactly like the evaluator pool's.
    """

    name = "supervisor"

    def __init__(self, argv: List[str],
                 command: Optional[List[str]] = None,
                 max_crashes: Optional[int] = None,
                 crash_window: Optional[float] = None,
                 restart_backoff: float = 0.5,
                 restart_backoff_cap: float = 30.0,
                 seed: int = 1234,
                 manifest_path: Optional[str] = None) -> None:
        self.argv = list(argv)
        self.command = list(command) if command else \
            [sys.executable, "-m", "veles_tpu"]
        self.max_crashes = int(
            os.environ.get(MAX_CRASHES_ENV, "5")
            if max_crashes is None else max_crashes)
        self.crash_window = float(
            os.environ.get(CRASH_WINDOW_ENV, "300")
            if crash_window is None else crash_window)
        self.restart_backoff = restart_backoff
        self.restart_backoff_cap = restart_backoff_cap
        self._backoff_rng = np.random.default_rng(seed ^ 0x5EED)
        # the supervisor's own journal (restart/resumed/giveup) must
        # land in the run's metrics dir even when it was given as a
        # flag rather than the env var (configure() exports the var,
        # so the child inherits it exactly as if it had the flag)
        mdir = self._argv_value("--metrics-dir")
        if mdir and not telemetry.metrics_dir():
            telemetry.configure(mdir)
        self.manifest_path = manifest_path or self._default_manifest()
        self._child: Optional[subprocess.Popen] = None
        self._shutdown_sig: Optional[int] = None
        #: restarts performed so far (mirrors supervisor.restarts)
        self.restarts = 0

    def _default_manifest(self) -> str:
        """$VELES_RESUME_MANIFEST when the caller exported one, else a
        file inside the run's metrics dir (one artifact dir per run),
        else a supervisor-owned temp dir."""
        from veles_tpu.snapshotter import MANIFEST_ENV, MANIFEST_NAME
        env = os.environ.get(MANIFEST_ENV)
        if env:
            return env
        mdir = self._argv_value("--metrics-dir") or \
            telemetry.metrics_dir()
        if mdir:
            return os.path.join(mdir, MANIFEST_NAME)
        import tempfile
        return os.path.join(
            tempfile.mkdtemp(prefix="veles_supervise_"), MANIFEST_NAME)

    def _argv_value(self, flag: str) -> Optional[str]:
        for i, a in enumerate(self.argv):
            if a == flag and i + 1 < len(self.argv):
                return self.argv[i + 1]
            if a.startswith(flag + "="):
                return a.split("=", 1)[1]
        return None

    # -- resume-state discovery ---------------------------------------

    def newest_intact_snapshot(self) -> Optional[str]:
        """The newest CRC-intact snapshot the run left behind: the
        manifest's pointer first, then its lineage siblings
        newest-first.  None when the run never snapshotted (or the
        manifest is gone) — the child then starts from its own flags."""
        from veles_tpu.snapshotter import (read_resume_manifest,
                                           snapshot_candidates,
                                           verify_snapshot)
        manifest = read_resume_manifest(self.manifest_path) or {}
        pointer = manifest.get("snapshot") or \
            self._argv_value("--snapshot")
        if not pointer:
            return None
        for cand in [pointer] + snapshot_candidates(pointer):
            if os.path.isfile(cand) and verify_snapshot(cand):
                return cand
            self.warning("resume candidate %s is torn/missing; "
                         "walking the lineage", cand)
        return None

    def _argv_for_attempt(self, attempt: int,
                          downtime: Optional[float]) -> List[str]:
        """The child argv, with ``--snapshot`` rewritten to the newest
        intact candidate on restarts.  GA runs (--optimize) resume
        through their own --ga-state file and are left untouched."""
        argv = list(self.argv)
        if attempt == 0:
            return argv
        from veles_tpu.snapshotter import read_resume_manifest
        manifest = read_resume_manifest(self.manifest_path) or {}
        source, state = "fresh", None
        if "--optimize" not in argv:
            snap = self.newest_intact_snapshot()
            if snap:
                source, state = "snapshot", snap
                done = False
                for i, a in enumerate(argv):
                    if a == "--snapshot" and i + 1 < len(argv):
                        argv[i + 1] = snap
                        done = True
                    elif a.startswith("--snapshot="):
                        argv[i] = f"--snapshot={snap}"
                        done = True
                if not done:
                    # flags must precede the positional workflow file?
                    # argparse interleaves fine — append is safe
                    argv += ["--snapshot", snap]
        elif manifest.get("ga_state"):
            source, state = "ga_state", manifest["ga_state"]
        telemetry.event(events.EV_SUPERVISOR_RESUMED, attempt=attempt,
                        source=source, state=state,
                        downtime=None if downtime is None
                        else round(downtime, 3))
        self.info("attempt %d resumes from %s%s", attempt, source,
                  f" ({state})" if state else "")
        return argv

    # -- the loop ------------------------------------------------------

    def _install_forwarding(self):
        """Supervisor-side SIGTERM/SIGINT: forward to the child and do
        NOT resume its resulting exit — the platform is reclaiming us
        too.  Main-thread only; returns an uninstall callable."""
        import signal
        if threading.current_thread() is not threading.main_thread():
            return lambda: None

        def handler(signum, frame):
            self._shutdown_sig = signum
            child = self._child
            if child is not None and child.poll() is None:
                try:
                    child.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

        def uninstall():
            for sig, h in prev.items():
                try:
                    signal.signal(sig, h)
                except (ValueError, OSError):
                    pass
        return uninstall

    def run(self) -> int:
        from veles_tpu.snapshotter import MANIFEST_ENV
        uninstall = self._install_forwarding()
        attempt = 0
        crash_times: deque = deque()
        consecutive_crashes = 0
        last_death: Optional[float] = None
        self.info("supervising: %s (manifest: %s, crash budget %d/"
                  "%.0fs)", " ".join(self.command + self.argv),
                  self.manifest_path, self.max_crashes,
                  self.crash_window)
        try:
            while True:
                now = time.monotonic()
                downtime = None if last_death is None \
                    else now - last_death
                if downtime is not None:
                    telemetry.histogram(
                        events.HIST_SUPERVISOR_DOWNTIME_SECONDS
                    ).record(downtime)
                argv = self._argv_for_attempt(attempt, downtime)
                env = dict(os.environ)
                env[MANIFEST_ENV] = self.manifest_path
                env[ATTEMPT_ENV] = str(attempt)
                self._child = subprocess.Popen(
                    self.command + argv, env=env)
                rc = self._child.wait()
                last_death = time.monotonic()
                code = _normalize_rc(rc)
                if self._shutdown_sig is not None:
                    self.warning("supervisor was signaled — not "
                                 "resuming; child exited %d", code)
                    telemetry.event(events.EV_SUPERVISOR_SHUTDOWN, rc=code)
                    return code
                if code == EXIT_DONE:
                    telemetry.event(events.EV_SUPERVISOR_DONE,
                                    attempts=attempt + 1)
                    self.info("run complete after %d attempt(s), "
                              "%d restart(s)", attempt + 1,
                              self.restarts)
                    return EXIT_DONE
                if code in RESUME_CODES:
                    kind = "preempt" if code == EXIT_PREEMPTED \
                        else "multihost_abort"
                    consecutive_crashes = 0
                    self._note_restart(code, attempt, kind, 0.0)
                    attempt += 1
                    continue   # immediate respawn, budget untouched
                if code == EXIT_USAGE:
                    # argparse/config errors are deterministic: a
                    # restart loop would fail identically forever
                    telemetry.event(events.EV_SUPERVISOR_GIVEUP, rc=code,
                                    reason="usage_error")
                    self.error("child failed with a usage error (2); "
                               "giving up")
                    return code
                # a genuine crash: charge the budget
                crash_times.append(last_death)
                while crash_times and \
                        last_death - crash_times[0] > self.crash_window:
                    crash_times.popleft()
                if len(crash_times) >= self.max_crashes:
                    telemetry.event(
                        events.EV_SUPERVISOR_GIVEUP, rc=code,
                        crashes=len(crash_times),
                        window=self.crash_window)
                    telemetry.flush()
                    self.error(
                        "crash loop: %d failures inside %.0fs "
                        "(budget %d) — giving up; last exit %d",
                        len(crash_times), self.crash_window,
                        self.max_crashes, code)
                    return code
                consecutive_crashes += 1
                delay = self._backoff(consecutive_crashes)
                self._note_restart(code, attempt, "crash", delay)
                if delay > 0:
                    time.sleep(delay)
                attempt += 1
        finally:
            uninstall()
            child = self._child
            if child is not None and child.poll() is None:
                child.kill()

    def _backoff(self, consecutive: int) -> float:
        """The evaluator pool's restart shape: first restart
        immediate, then exponential with deterministic +-25% jitter."""
        if consecutive <= 1:
            return 0.0
        delay = min(self.restart_backoff_cap,
                    self.restart_backoff * (2.0 ** (consecutive - 2)))
        return delay * (0.75 + 0.5 * float(self._backoff_rng.random()))

    def _note_restart(self, code: int, attempt: int, kind: str,
                      delay: float) -> None:
        self.restarts += 1
        telemetry.counter(events.CTR_SUPERVISOR_RESTARTS).inc()
        telemetry.event(events.EV_SUPERVISOR_RESTART, rc=code,
                        attempt=attempt,
                        kind=kind, budget_charged=(kind == "crash"),
                        delay=round(delay, 3))
        self.warning("child exited %d (%s) — restarting (attempt %d"
                     "%s)", code, kind, attempt + 1,
                     f", backoff {delay:.2f}s" if delay else "")


def run(argv: List[str], **kwargs) -> int:
    """``python -m veles_tpu --supervise ...`` lands here: supervise
    the same CLI invocation minus the flag."""
    return Supervisor(argv, **kwargs).run()


# -- child-side GA graceful stop --------------------------------------

def install_ga_stop(grace: Optional[float] = None,
                    ) -> Tuple[callable, callable]:
    """Graceful stop for the GA parent (``--optimize`` runs have no
    Launcher): SIGTERM/SIGINT requests a cooperative stop at the next
    GENERATION boundary — the per-generation ``--ga-state`` checkpoint
    already on disk is the resume point — and a watchdog enforces
    ``$VELES_PREEMPT_GRACE`` (a generation can outlive any grace
    deadline; the checkpoint loses only the in-flight generation,
    which the resumed run re-evaluates bit-identically).

    Returns ``(stop_check, finish)``: pass ``stop_check`` to
    ``GeneticOptimizer(stop_check=...)``; call ``finish()`` after the
    run — it returns the exit code to use (14) when a stop was
    requested, else None.  No-op (never stops) off the main thread.
    """
    import logging
    import signal
    log = logging.getLogger("veles.supervisor")
    state = {"sig": None}
    done = threading.Event()
    if threading.current_thread() is not threading.main_thread():
        return (lambda: False), (lambda: None)
    if grace is None:
        grace = float(os.environ.get("VELES_PREEMPT_GRACE", "25"))

    def watchdog(name: str) -> None:
        telemetry.event(events.EV_PREEMPT_REQUESTED, signal=name,
                        grace=grace, mode="ga")
        log.warning(
            "preemption requested (%s): stopping at the next GA "
            "generation boundary (checkpoint = resume point); hard "
            "exit in %.0fs", name, grace)
        if done.wait(grace):
            return
        telemetry.event(events.EV_PREEMPT_DEADLINE_EXCEEDED, grace=grace,
                        mode="ga")
        log.error("GA graceful stop missed the %.0fs grace deadline "
                  "— exiting %d on the last checkpoint", grace,
                  EXIT_PREEMPTED)
        telemetry.flush()
        logging.shutdown()
        os._exit(EXIT_PREEMPTED)

    def handler(signum, frame):
        if state["sig"] is not None:
            os._exit(EXIT_PREEMPTED)
        state["sig"] = signum
        threading.Thread(
            target=watchdog, args=(signal.Signals(signum).name,),
            daemon=True, name="ga-preempt-watchdog").start()

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, handler)
        except (ValueError, OSError):
            pass

    def stop_check() -> bool:
        return state["sig"] is not None

    def finish() -> Optional[int]:
        done.set()
        for sig, h in prev.items():
            try:
                signal.signal(sig, h)
            except (ValueError, OSError):
                pass
        if state["sig"] is None:
            return None
        telemetry.event(events.EV_PREEMPT_GA_EXIT, code=EXIT_PREEMPTED)
        telemetry.flush()
        log.warning("GA preempted: exiting %d (resume via the same "
                    "--ga-state / --supervise invocation)",
                    EXIT_PREEMPTED)
        return EXIT_PREEMPTED

    return stop_check, finish
